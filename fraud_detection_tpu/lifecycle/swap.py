"""Hot model swap: promotion reaches the serving path with zero restarts.

:class:`ModelSlot` holds the served model behind ONE reference. The
micro-batcher reads the slot once per flush and the XAI/shadow paths once
per batch, so a swap lands *between* device dispatches: in-flight batches
finish on the old params, the next batch scores with the new — no dropped
requests, no lock on the hot path (a Python attribute store is atomic
under the GIL, and the tuple swap means readers can never observe a
half-updated model/version pair).

:class:`ModelReloader` watches the registry aliases (poll and/or
``POST /admin/reload``) and drives the slot: when ``@prod`` moves it loads
the new champion, **warms the scorer's bucket ladder off-path** (a cold
XLA compile must stall the reloader thread, never a request), swaps, and
rebinds the watchtower's baseline profile; when ``@shadow`` moves it
rebinds the challenger. ``lifecycle_model_swaps_total`` counts swaps and
``lifecycle_active_model_version`` exports what's serving — the
promotion-went-live signal the runbook watches.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.lifecycle")


class ModelSlot:
    """The single swappable reference to (model, source, version)."""

    def __init__(self, model, source: str, version: int | None = None):
        self._ref = (model, source, version)

    def get(self) -> tuple:
        return self._ref  # one attribute read — atomic snapshot

    @property
    def model(self):
        return self._ref[0]

    @property
    def source(self) -> str:
        return self._ref[1]

    @property
    def version(self) -> int | None:
        return self._ref[2]

    def swap(self, model, source: str, version: int | None = None) -> None:
        self._ref = (model, source, version)
        metrics.lifecycle_model_swaps.inc()
        metrics.lifecycle_active_model_version.set(version or 0)
        log.warning(
            "model slot swapped → %s (v%s)", source, version
        )


def warm_scorer(scorer, max_batch: int | None = None) -> None:
    """Pre-compile the bucket ladder for a freshly loaded model so the swap
    pause is a pointer write, not an XLA compile (same ladder the
    micro-batcher warms at startup). Marked expected for the compile
    sentinel — a reload's ladder is not a RecompileStorm."""
    from fraud_detection_tpu.ops.scorer import _bucket
    from fraud_detection_tpu.telemetry.compile_sentinel import (
        expected_compiles,
    )

    max_batch = max_batch or config.scorer_max_batch()
    d = scorer.n_features
    b = scorer.min_bucket
    top = _bucket(max_batch, b)
    with expected_compiles():
        while b <= top:
            scorer.predict_proba(np.zeros((b, d), np.float32))
            b *= 2


def warm_fused_ladder(
    watchtower,
    scorer,
    max_batch: int | None = None,
    explain_k: int | None = None,
    return_wire: str | None = None,
    drift=None,
) -> None:
    """Pre-compile the FUSED flush executables for a freshly loaded model
    before it swaps in. Same-family promotions hit the jit cache (the
    params change, the program doesn't), but a CROSS-family promotion —
    linear champion → GBT challenger or back (evergreen) — binds a
    different static score body and a different explain-args pytree, so
    without this warm the first post-swap flush would pay a cold XLA
    compile under live traffic. Warms the exact executables serving will
    dispatch: the configured return wire, and the fused explain leg when
    SCORER_EXPLAIN=topk. No-op when no fused target exists (no watchtower
    / no drift monitor / no fused spec). ``drift`` overrides the monitor
    the warm drives through — a CROSS-WIDTH promotion (narrow → wide /
    ledger, broadside) changes the drift window's feature width, so the
    warm must trace against a monitor built from the NEW champion's
    profile (the jit cache is global: the executables warmed here are
    exactly the ones the post-rebind monitor dispatches). Runs under
    expected_compiles — a promotion's ladder is not a RecompileStorm."""
    from fraud_detection_tpu.ops import scorer as scorer_mod
    from fraud_detection_tpu.ops.scorer import _bucket
    from fraud_detection_tpu.telemetry.compile_sentinel import (
        expected_compiles,
    )

    if drift is None:
        drift = getattr(watchtower, "drift", None)
    if drift is None or not hasattr(drift, "warm_fused"):
        return
    spec = getattr(scorer, "fused_spec", lambda: None)()
    if spec is None:
        return
    # the serving configuration (what the micro-batcher will dispatch) by
    # default; explicit overrides for callers that configured the batcher
    # directly rather than through env
    out_dtype = scorer_mod.RETURN_WIRES[
        return_wire if return_wire is not None else config.scorer_return_wire()
    ][1]
    if explain_k is None:
        explain_k = (
            config.scorer_explain_k()
            if config.scorer_explain() == "topk"
            else 0
        )
    if spec.explain_args is None:
        explain_k = 0
    explain_k = min(explain_k, scorer.n_features)
    max_batch = max_batch or config.scorer_max_batch()
    top = _bucket(max_batch, scorer.min_bucket)
    if (
        getattr(spec, "ledger", None) is not None
        and getattr(drift, "n_shards", 1) > 1
    ):
        # sharded ledger placement can bump a skewed batch's bucket by up
        # to the shard factor (the micro-batcher start() discipline)
        top *= drift.n_shards
    b = scorer.min_bucket
    with expected_compiles():
        while b <= top:
            drift.warm_fused(scorer, b, out_dtype=out_dtype, explain_k=explain_k)
            b *= 2


class ModelReloader:
    """Alias watcher + swap driver for one serving process."""

    def __init__(
        self,
        slot: ModelSlot,
        watchtower=None,
        interval: float | None = None,
        max_batch: int | None = None,
    ):
        self.slot = slot
        self.watchtower = watchtower
        self.interval = (
            interval
            if interval is not None
            else config.lifecycle_reload_interval()
        )
        self.max_batch = max_batch
        self._shadow_version: int | None = self._current_shadow_version()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # check_once can be driven concurrently by the poll thread and
        # /admin/reload — serialize so two loads can't interleave swaps
        self._lock = lockdep.lock("lifecycle.reloader")
        metrics.lifecycle_active_model_version.set(slot.version or 0)

    # -- registry probes ---------------------------------------------------
    def _registry(self):
        from fraud_detection_tpu.tracking import TrackingClient

        return TrackingClient().registry

    def _current_shadow_version(self) -> int | None:
        try:
            return self._registry().get_version_by_alias(
                config.model_name(), config.shadow_stage()
            )
        except Exception:
            log.debug("shadow alias probe failed", exc_info=True)
            return None

    # -- the reload step ---------------------------------------------------
    def check_once(self) -> dict:
        """One alias sweep; returns what changed (the /admin/reload body)."""
        with self._lock:
            out = {"champion": "unchanged", "shadow": "unchanged"}
            try:
                out["champion"] = self._check_champion()
            except Exception as e:
                out["champion"] = f"error: {e}"
                log.warning("champion reload check failed: %s", e)
            try:
                out["shadow"] = self._check_shadow()
            except Exception as e:
                out["shadow"] = f"error: {e}"
                log.warning("shadow reload check failed: %s", e)
            return out

    def _check_champion(self) -> str:
        from fraud_detection_tpu.models import load_any_model

        registry = self._registry()
        name, stage = config.model_name(), config.model_stage()
        version = registry.get_version_by_alias(name, stage)
        if version is None or version == self.slot.version:
            return "unchanged"
        art = registry.artifact_dir(name, version)
        model = load_any_model(art)
        old = self.slot.model
        if old is not None and list(
            getattr(model, "base_feature_names", model.feature_names)
        ) != list(getattr(old, "base_feature_names", old.feature_names)):
            # the hot-swap safety condition is the WIRE schema (what
            # clients send): a widened family (broadside crosses, ledger
            # velocity columns) extends feature_names with device-computed
            # columns but keeps the base schema — narrow ↔ wide promotions
            # are exactly the conductor's broadside flow and must hot-swap
            raise ValueError(
                f"v{version} wire schema differs from the served model — "
                "refusing to hot-swap (deploy instead)"
            )
        warm_scorer(model.scorer, self.max_batch)  # compile BEFORE the swap
        profile = None
        if self.watchtower is not None:
            from fraud_detection_tpu.monitor.baseline import load_profile

            profile = load_profile(art)
            # cross-family promotions (evergreen: linear ↔ GBT) bind a new
            # fused program — warm its flush/explain executables BEFORE
            # the swap so the first post-swap flush is a cache hit. A
            # CROSS-WIDTH promotion (narrow → wide/ledger) additionally
            # changes the drift window's feature width: warm against a
            # monitor built from the NEW champion's profile — the same
            # executables the post-rebind monitor dispatches.
            drift_override = None
            old_width = len(old.feature_names) if old is not None else None
            if (
                profile is not None
                and old_width is not None
                and len(model.feature_names) != old_width
            ):
                drift_override = self.watchtower._make_drift(profile)
            warm_fused_ladder(
                self.watchtower, model.scorer, self.max_batch,
                drift=drift_override,
            )
        source = f"registry:models:/{name}@{stage}"
        self.slot.swap(model, source, version)
        if self.watchtower is not None:
            # ledger: a widened champion's entity table rebinds WITH the
            # model (the stamped snapshot its weights were replayed
            # against) — same zero-recompile discipline as the weights,
            # since the table shapes are fixed by LEDGER_SLOTS
            ledger = (
                (model.ledger_spec, model.ledger_state)
                if getattr(model, "ledger_spec", None) is not None
                else None
            )
            self.watchtower.rebind_champion(profile, ledger=ledger)
            # rebind_champion drops the shadow scorer (the old challenger is
            # usually the new champion); force the shadow sweep that runs
            # right after this to re-bind even if the @shadow alias version
            # itself didn't change
            self._shadow_version = -1
        return f"swapped to v{version}"

    def _check_shadow(self) -> str:
        version = self._current_shadow_version()
        if version == self._shadow_version:
            return "unchanged"
        prev = self._shadow_version
        if self.watchtower is None:
            self._shadow_version = version
            return "unchanged"  # nothing to rebind without a watchtower
        if version is None:
            self.watchtower.rebind_challenger(None, None)
            self._shadow_version = None
            return f"challenger v{prev} unloaded"
        from fraud_detection_tpu.models import load_any_model

        # record the version only AFTER a successful bind: a transient
        # registry/download failure must retry on the next poll, not park
        # the challenger unbound until the alias moves again
        name = config.model_name()
        art = self._registry().artifact_dir(name, version)
        challenger = load_any_model(art)
        served = self.slot.model
        if served is not None and list(
            getattr(challenger, "base_feature_names", challenger.feature_names)
        ) != list(
            getattr(served, "base_feature_names", served.feature_names)
        ):
            # the WIRE schema is the bind condition: a wide/ledger-widened
            # challenger shadowing a narrow champion (the broadside
            # promotion flow) scores the same base rows through its null
            # path — only a genuine schema change refuses
            log.warning(
                "shadow v%s wire schema mismatch — not binding", version
            )
            self._shadow_version = version  # terminal for this version
            return "schema mismatch"
        warm_scorer(challenger.scorer, self.max_batch)
        self.watchtower.rebind_challenger(
            challenger, f"registry:models:/{name}@{config.shadow_stage()}"
        )
        self._shadow_version = version
        return f"challenger swapped to v{version}"

    # -- polling -----------------------------------------------------------
    def start(self) -> None:
        if self.interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._poll_loop, name="lifecycle-reloader", daemon=True
        )
        self._thread.start()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.check_once()
            except Exception:
                log.warning("reloader poll failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
