"""Durable lifecycle state: labeled feedback + the conductor state machine.

Two tables beside the task queue (``LIFECYCLE_DB_URL``, defaulting to the
broker database when that is a SQL backend — sqlite WAL or PostgreSQL over
the built-in wire client, same dual-dialect pattern as taskq.py/pgclient.py):

- ``feedback_rows`` — append-only labeled feedback, partitioned into two
  pools:

  * **window**: the most recent ``CONDUCTOR_FEEDBACK_WINDOW`` rows (oldest
    pruned) — the "what does settled traffic look like *now*" slice the
    challenger gate evaluates on;
  * **reservoir**: a uniform-over-history sample of fixed size (classic
    reservoir sampling, slot-addressed replacement, ``seen`` persisted so
    the uniformity survives restarts) — the replay mix that keeps old
    regimes represented in retraining after the window has forgotten them.

  A row lands in the window always and in the reservoir with probability
  ``R/seen`` — both pools are maintained in one pass per batch.

- ``lifecycle_state`` — one row per model name holding the conductor's
  state machine (``idle → retraining → gated → shadowing → promoting →
  done/rolled_back``, with ``rolling_back`` as the persisted rollback
  intent) plus the challenger/champion versions, gate evidence, and the
  episode owner. Transitions go through :meth:`LifecycleStore.transition`
  — a *single* guarded ``UPDATE ... WHERE state IN (...)`` so the
  compare-and-set is atomic across processes, not just across threads:
  under PG READ COMMITTED the post-lock predicate re-check makes a lost
  race return rowcount 0, and under sqlite the one DML statement holds the
  write lock for its whole evaluation. A crashed worker resumes mid-step
  without double-promoting and two workers can't run the same step twice.
"""

from __future__ import annotations

import json
import logging
import sqlite3
import threading
import time
import uuid
from typing import Any, Iterable

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.range.faults import fire
from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.lifecycle")

WINDOW = "window"
RESERVOIR = "reservoir"

# State machine vocabulary (ISSUE-pinned): terminal states re-arm to a new
# episode via begin-retrain. ROLLING_BACK is the persisted promotion-rollback
# intent — recorded before any alias moves so a crash mid-rollback resumes.
IDLE = "idle"
RETRAINING = "retraining"
GATED = "gated"
SHADOWING = "shadowing"
PROMOTING = "promoting"
ROLLING_BACK = "rolling_back"
DONE = "done"
ROLLED_BACK = "rolled_back"
STATES = (
    IDLE, RETRAINING, GATED, SHADOWING, PROMOTING, ROLLING_BACK, DONE,
    ROLLED_BACK,
)

# Columns of lifecycle_state a transition may set (everything but the PK and
# updated_at, which the CAS always stamps).
_FIELD_COLS = (
    "challenger_version", "champion_version", "reason", "gate", "owner",
)

_SCHEMA = [
    """
    CREATE TABLE IF NOT EXISTS feedback_rows (
        id TEXT PRIMARY KEY,
        seq INTEGER NOT NULL,
        pool TEXT NOT NULL,
        slot INTEGER,
        features TEXT NOT NULL,
        score REAL NOT NULL,
        label INTEGER NOT NULL,
        created_at REAL NOT NULL,
        entity TEXT,
        ts REAL
    )
    """,
    "CREATE INDEX IF NOT EXISTS idx_feedback_pool_seq ON feedback_rows(pool, seq)",
    "CREATE INDEX IF NOT EXISTS idx_feedback_pool_slot ON feedback_rows(pool, slot)",
    """
    CREATE TABLE IF NOT EXISTS feedback_meta (
        key TEXT PRIMARY KEY,
        value TEXT NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS lifecycle_state (
        name TEXT PRIMARY KEY,
        state TEXT NOT NULL,
        challenger_version INTEGER,
        champion_version INTEGER,
        reason TEXT,
        gate TEXT,
        owner TEXT,
        updated_at REAL NOT NULL
    )
    """,
]


def _sqlite_path(url: str) -> str:
    return url[len("sqlite:///") :] if url.startswith("sqlite:///") else url


class LifecycleStore:
    """SQLite implementation; :class:`PgLifecycleStore` swaps the connection
    for the pgwire adapter and inherits every query (written in the
    PG/SQLite common dialect — no AUTOINCREMENT, no INSERT OR REPLACE)."""

    def __init__(
        self,
        url: str | None = None,
        window_size: int | None = None,
        reservoir_size: int | None = None,
        seed: int = 0,
    ):
        self.url = url or config.lifecycle_db_url()
        self.window_size = int(
            window_size
            if window_size is not None
            else config.conductor_feedback_window()
        )
        self.reservoir_size = int(
            reservoir_size
            if reservoir_size is not None
            else config.conductor_reservoir_size()
        )
        self._rng = np.random.default_rng(seed)
        self._lock = lockdep.lock("lifecycle.store")
        self._connect()
        with self._lock, self._conn:
            for stmt in _SCHEMA:
                self._conn.executescript(stmt)
        # stores created before the owner column existed: best-effort add
        # (its own transaction — a PG error aborts the enclosing txn)
        with self._lock:
            try:
                with self._conn:
                    self._conn.execute(
                        "ALTER TABLE lifecycle_state ADD COLUMN owner TEXT"
                    )
            except Exception:
                # column already present (the common case: CREATE TABLE
                # above ships it; only pre-owner stores need the ALTER)
                log.debug("lifecycle owner column migration skipped",
                          exc_info=True)
        # ledger: pre-ledger stores lack the entity/ts feedback columns
        for col_ddl in ("entity TEXT", "ts REAL"):
            with self._lock:
                try:
                    with self._conn:
                        self._conn.execute(
                            f"ALTER TABLE feedback_rows ADD COLUMN {col_ddl}"
                        )
                except Exception:
                    log.debug(
                        "feedback %s column migration skipped", col_ddl,
                        exc_info=True,
                    )

    def _connect(self) -> None:
        import os

        path = _sqlite_path(self.url)
        if path != ":memory:" and os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False, timeout=30.0)
        self._conn.row_factory = sqlite3.Row
        self._conn.execute("PRAGMA journal_mode=WAL")

    # -- feedback ----------------------------------------------------------
    def _meta_get(self, key: str, default: int = 0) -> int:
        row = self._conn.execute(
            "SELECT value FROM feedback_meta WHERE key = ?", (key,)
        ).fetchone()
        return int(row["value"]) if row else default

    def _meta_set(self, key: str, value: int) -> None:
        cur = self._conn.execute(
            "UPDATE feedback_meta SET value = ? WHERE key = ?",
            (str(int(value)), key),
        )
        if cur.rowcount == 0:
            self._conn.execute(
                "INSERT INTO feedback_meta (key, value) VALUES (?, ?)",
                (key, str(int(value))),
            )

    def add_feedback(
        self, features: Iterable, scores: Iterable, labels: Iterable,
        entity_ids=None, timestamps=None,
    ) -> int:
        """Append one labeled batch; returns rows ingested. One transaction
        per batch: a crash mid-batch loses the batch, never corrupts the
        reservoir's uniformity invariants (``seen`` commits with the rows).

        ``entity_ids``/``timestamps`` (ledger): per-row entity + event time
        so the conductor's retrain can replay feedback through the velocity
        aggregator in timestamp order. Optional — rows without them replay
        through the null slot."""
        feats = np.asarray(features, np.float32)
        if feats.ndim == 1:
            feats = feats[None, :]
        scores = np.asarray(scores, np.float64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        # fraud-range injection point: the poisoned-feedback drill corrupts
        # the batch in flight here; the guards below are the blast door
        fire(
            "lifecycle.store.add_feedback",
            features=feats, scores=scores, labels=labels,
        )
        n = feats.shape[0]
        if not (scores.shape[0] == n and labels.shape[0] == n):
            raise ValueError("features/scores/labels must have equal length")
        # Poison guard: this store feeds the conductor's retrain replay and
        # the challenger gate — a NaN/Inf row or out-of-range score would
        # silently corrupt the training mix and NaN the gate statistics
        # (which fail closed, bricking promotion). /monitor/feedback
        # validates at the API edge; queue-delivered feedback
        # (lifecycle.record_feedback) and embedded callers land here, so
        # the store is the boundary that must hold.
        if not np.all(np.isfinite(feats)):
            raise ValueError("feedback features must be finite")
        if not (
            np.all(np.isfinite(scores))
            and np.all((scores >= 0.0) & (scores <= 1.0))
        ):
            raise ValueError("feedback scores must be probabilities in [0, 1]")
        if not np.all((labels == 0) | (labels == 1)):
            raise ValueError("feedback labels must be 0 or 1")
        ents: list = list(entity_ids) if entity_ids is not None else [None] * n
        tss: list = list(timestamps) if timestamps is not None else [None] * n
        if len(ents) != n or len(tss) != n:
            raise ValueError("entity_ids/timestamps must align with features")
        ents = [None if e is None else str(e) for e in ents]
        for t in tss:
            if t is not None and not (float(t) > 0 and np.isfinite(float(t))):
                raise ValueError("timestamps must be positive finite numbers")
        tss = [None if t is None else float(t) for t in tss]
        now = time.time()
        with self._lock, self._conn:
            seq = self._meta_get("seq")
            seen = self._meta_get("reservoir_seen")
            res_count = self._count(RESERVOIR)
            for i in range(n):
                seq += 1
                payload = json.dumps([float(v) for v in feats[i]])
                self._conn.execute(
                    "INSERT INTO feedback_rows (id, seq, pool, slot, features,"
                    " score, label, created_at, entity, ts)"
                    " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    (
                        uuid.uuid4().hex, seq, WINDOW, None, payload,
                        float(scores[i]), int(labels[i]), now,
                        ents[i], tss[i],
                    ),
                )
                # reservoir sampling (Vitter's R): row i of history occupies
                # each slot with probability R/seen at every point in time
                seen += 1
                if res_count < self.reservoir_size:
                    slot = res_count
                    res_count += 1
                else:
                    j = int(self._rng.integers(seen))
                    slot = j if j < self.reservoir_size else None
                if slot is not None:
                    self._conn.execute(
                        "DELETE FROM feedback_rows WHERE pool = ? AND slot = ?",
                        (RESERVOIR, slot),
                    )
                    self._conn.execute(
                        "INSERT INTO feedback_rows (id, seq, pool, slot,"
                        " features, score, label, created_at, entity, ts)"
                        " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                        (
                            uuid.uuid4().hex, seq, RESERVOIR, slot, payload,
                            float(scores[i]), int(labels[i]), now,
                            ents[i], tss[i],
                        ),
                    )
            self._meta_set("seq", seq)
            self._meta_set("reservoir_seen", seen)
            # prune the window to its bound (oldest first)
            excess = self._count(WINDOW) - self.window_size
            if excess > 0:
                self._conn.execute(
                    "DELETE FROM feedback_rows WHERE pool = ? AND seq <= ("
                    "SELECT seq FROM feedback_rows WHERE pool = ? "
                    "ORDER BY seq LIMIT 1 OFFSET ?)",
                    (WINDOW, WINDOW, excess - 1),
                )
        return n

    def _count(self, pool: str) -> int:
        row = self._conn.execute(
            "SELECT COUNT(*) AS n FROM feedback_rows WHERE pool = ?", (pool,)
        ).fetchone()
        return int(row["n"])

    def _rows(self, pool: str, limit: int | None = None):
        sql = (
            "SELECT features, score, label, entity, ts FROM feedback_rows "
            "WHERE pool = ? ORDER BY seq DESC"
        )
        params: list[Any] = [pool]
        if limit is not None:
            sql += " LIMIT ?"
            params.append(int(limit))
        return self._conn.execute(sql, params).fetchall()

    @staticmethod
    def _unpack(rows) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not rows:
            return (
                np.zeros((0, 0), np.float32),
                np.zeros((0,), np.float32),
                np.zeros((0,), np.int32),
            )
        x = np.asarray([json.loads(r["features"]) for r in rows], np.float32)
        s = np.asarray([r["score"] for r in rows], np.float32)
        y = np.asarray([r["label"] for r in rows], np.int32)
        return x, s, y

    @staticmethod
    def _unpack_meta(rows) -> tuple[list, np.ndarray]:
        """Ledger columns for a fetched row set: (entities, timestamps) —
        entity None / ts 0.0 for rows persisted before the columns existed
        (they replay through the null slot)."""
        if not rows:
            return [], np.zeros((0,), np.float32)
        ents = [r["entity"] for r in rows]
        ts = np.asarray(
            [r["ts"] if r["ts"] is not None else 0.0 for r in rows],
            np.float32,
        )
        return ents, ts

    def window_rows(
        self, limit: int | None = None
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Most-recent-first labeled window → (features, scores, labels)."""
        with self._lock:
            return self._unpack(self._rows(WINDOW, limit))

    def window_rows_meta(self, limit: int | None = None):
        """Window rows WITH the ledger columns →
        ``(features, scores, labels, entities, timestamps)`` — one fetch,
        so rows and their replay metadata can never misalign."""
        with self._lock:
            rows = self._rows(WINDOW, limit)
            return (*self._unpack(rows), *self._unpack_meta(rows))

    def reservoir_rows(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The uniform-over-history replay sample."""
        with self._lock:
            return self._unpack(self._rows(RESERVOIR))

    def reservoir_rows_meta(self):
        """Reservoir rows WITH the ledger columns (see window_rows_meta)."""
        with self._lock:
            rows = self._rows(RESERVOIR)
            return (*self._unpack(rows), *self._unpack_meta(rows))

    def feedback_counts(self) -> dict:
        with self._lock:
            return {
                "window": self._count(WINDOW),
                "reservoir": self._count(RESERVOIR),
                "seen": self._meta_get("reservoir_seen"),
            }

    # -- conductor state machine -------------------------------------------
    def get_state(self, name: str) -> dict:
        # fraud-range injection point: a chaos plan stalls/errors the
        # lifecycle store read here — the /lifecycle/status degradation
        # drill (503 + Retry-After instead of a hung 500)
        fire("lifecycle.store.get_state", name=name)
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM lifecycle_state WHERE name = ?", (name,)
            ).fetchone()
        if row is None:
            return {
                "name": name, "state": IDLE, "challenger_version": None,
                "champion_version": None, "reason": None, "gate": None,
                "owner": None, "updated_at": None,
            }
        d = dict(row)
        d["gate"] = json.loads(d["gate"]) if d.get("gate") else None
        return d

    def _write_state(self, name: str, state: str, fields: dict) -> None:
        gate = fields.get("gate")
        vals = (
            state,
            fields.get("challenger_version"),
            fields.get("champion_version"),
            fields.get("reason"),
            json.dumps(gate) if gate is not None else None,
            fields.get("owner"),
            time.time(),
        )
        cur = self._conn.execute(
            "UPDATE lifecycle_state SET state = ?, challenger_version = ?, "
            "champion_version = ?, reason = ?, gate = ?, owner = ?, "
            "updated_at = ? WHERE name = ?",
            vals + (name,),
        )
        if cur.rowcount == 0:
            self._conn.execute(
                "INSERT INTO lifecycle_state (state, challenger_version, "
                "champion_version, reason, gate, owner, updated_at, name) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                vals + (name,),
            )

    def set_state(self, name: str, state: str, **fields) -> None:
        """Unconditional write (operator override path; the conductor itself
        uses :meth:`transition`)."""
        if state not in STATES:
            raise ValueError(f"unknown lifecycle state {state!r}")
        with self._lock, self._conn:
            self._write_state(name, state, fields)

    def transition(
        self,
        name: str,
        from_states: Iterable[str],
        to_state: str,
        *,
        owner_guard: str | None = None,
        **fields,
    ) -> bool:
        """Compare-and-set: move to ``to_state`` only if the current state is
        in ``from_states``; fields not named keep their value. Returns False
        on a lost race / wrong precondition — the caller's idempotency
        signal.

        The CAS is ONE guarded UPDATE (state — and owner, when
        ``owner_guard`` is given — checked in the WHERE clause), so it is
        atomic across processes and replicas, not merely under the
        per-process lock: concurrent callers serialize on the row and the
        loser's re-checked predicate yields rowcount 0 in both dialects
        (sqlite holds the write lock for the whole statement; PG READ
        COMMITTED re-evaluates the predicate after the row lock). A name
        never written before is implicitly IDLE; it is materialized with a
        PK-guarded insert (``ON CONFLICT DO NOTHING`` — a lost race
        collapses to a no-op) so the UPDATE stays the single decision
        point."""
        if to_state not in STATES:
            raise ValueError(f"unknown lifecycle state {to_state!r}")
        unknown = set(fields) - set(_FIELD_COLS)
        if unknown:
            raise ValueError(
                f"unknown lifecycle_state fields {sorted(unknown)}"
            )
        froms = tuple(from_states)
        # database clock, same as heartbeat/reclaim: the stamp a transition
        # into RETRAINING writes is the first value the staleness predicate
        # reads, so it must not come from a (possibly skewed) host clock
        now = self._db_now()
        sets, vals = ["state = ?", "updated_at = ?"], [to_state, now]
        for col in _FIELD_COLS:
            if col in fields:
                v = fields[col]
                if col == "gate" and v is not None:
                    v = json.dumps(v)
                sets.append(f"{col} = ?")
                vals.append(v)
        where = f"name = ? AND state IN ({', '.join('?' * len(froms))})"
        vals += [name, *froms]
        if owner_guard is not None:
            where += " AND owner = ?"
            vals.append(owner_guard)
        with self._lock, self._conn:
            if IDLE in froms and owner_guard is None:
                self._conn.execute(
                    "INSERT INTO lifecycle_state (name, state, updated_at) "
                    "VALUES (?, ?, ?) ON CONFLICT (name) DO NOTHING",
                    (name, IDLE, now),
                )
            cur = self._conn.execute(
                f"UPDATE lifecycle_state SET {', '.join(sets)} WHERE {where}",
                vals,
            )
            return cur.rowcount == 1

    def _db_now(self) -> float:
        """Epoch seconds on the DATABASE's clock. Heartbeat stamps and the
        staleness predicate must read one clock — comparing two hosts'
        ``time.time()`` lets clock skew eat into (or inflate) the stale
        threshold. A sqlite file is host-local, so the host clock IS the
        database clock; :class:`PgLifecycleStore` asks the server."""
        return time.time()

    def heartbeat(self, name: str, owner: str) -> bool:
        """Refresh the liveness stamp of an owned RETRAINING episode. The
        retrain executor beats immediately and then every ``stale_after /
        3`` seconds; resume() treats a row whose stamp is older than
        ``stale_after`` as a dead owner's."""
        now = self._db_now()
        with self._lock, self._conn:
            cur = self._conn.execute(
                "UPDATE lifecycle_state SET updated_at = ? "
                "WHERE name = ? AND state = ? AND owner = ?",
                (now, name, RETRAINING, owner),
            )
            return cur.rowcount == 1

    def reclaim_stale_retrain(self, name: str, stale_after: float) -> bool:
        """Atomically reset a RETRAINING row to IDLE iff its heartbeat is at
        least ``stale_after`` seconds old — the guarded steal resume() uses
        so only a provably dead owner's episode gets re-run. The staleness
        predicate lives inside the UPDATE: a live owner's concurrent
        heartbeat makes the steal lose (rowcount 0) instead of hijacking a
        running fit. Both sides of the comparison come from the database's
        clock (:meth:`_db_now`), so cross-replica host skew cannot fake or
        mask staleness."""
        now = self._db_now()
        with self._lock, self._conn:
            cur = self._conn.execute(
                "UPDATE lifecycle_state SET state = ?, owner = NULL, "
                "updated_at = ?, reason = ? WHERE name = ? AND state = ? "
                "AND updated_at <= ?",
                (
                    IDLE, now, "reclaimed stale retrain episode", name,
                    RETRAINING, now - float(stale_after),
                ),
            )
            return cur.rowcount == 1

    # -- plumbing ----------------------------------------------------------
    def ping(self) -> bool:
        try:
            with self._lock:
                self._conn.execute("SELECT 1").fetchone()
            return True
        except Exception:
            log.debug("lifecycle store ping failed", exc_info=True)
            return False

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class PgLifecycleStore(LifecycleStore):
    """Same queries over genuine PostgreSQL via the pgwire adapter."""

    def _connect(self) -> None:
        from fraud_detection_tpu.service.pgclient import _PgAdapter

        self._conn = _PgAdapter(self.url)

    def _db_now(self) -> float:
        try:
            with self._lock:
                row = self._conn.execute(
                    "SELECT EXTRACT(EPOCH FROM now()) AS t"
                ).fetchone()
            return float(row["t"])
        except Exception:
            # protocol emulator / exotic servers without EXTRACT: host time
            # (same behavior as the sqlite store — skew risk returns only
            # where the shared-server guarantee was absent anyway)
            log.debug("db clock unavailable; using host clock", exc_info=True)
            return time.time()


def open_lifecycle_store(url: str | None = None, **kw) -> LifecycleStore:
    """Scheme dispatch mirroring ``taskq.Broker``: sqlite or postgresql."""
    url = url or config.lifecycle_db_url()
    if url.startswith("sqlite"):
        return LifecycleStore(url, **kw)
    if url.startswith(("postgresql://", "postgres://")):
        return PgLifecycleStore(url, **kw)
    raise NotImplementedError(
        f"lifecycle store backend for {url.split(':', 1)[0]} not available; "
        "use sqlite:/// or postgresql:// (set LIFECYCLE_DB_URL)"
    )
