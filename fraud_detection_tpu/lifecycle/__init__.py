"""Conductor: the closed-loop model lifecycle (retrain → gate → promote).

Watchtower (:mod:`fraud_detection_tpu.monitor`) detects drift and emits
recommendations; this package acts on them hands-free:

- :mod:`store` — durable labeled-feedback (windowed + reservoir) and the
  persisted, crash-resumable state machine;
- :mod:`retrain` — warm-started sharded DP refit + evaluation assembly;
- :mod:`gate` — the jitted challenger gate (AUC/ECE/score-PSI bounds);
- :mod:`conductor` — the state machine driver consuming the taskq tasks;
- :mod:`swap` — atomic hot model swap on the serving path (no restarts).
"""

from fraud_detection_tpu.lifecycle.conductor import (  # noqa: F401
    FEEDBACK_TASK,
    PROMOTE_TASK,
    ROLLBACK_TASK,
    Conductor,
)
from fraud_detection_tpu.lifecycle.gate import (  # noqa: F401
    GateResult,
    GateThresholds,
    evaluate_gate,
)
from fraud_detection_tpu.lifecycle.retrain import run_retrain  # noqa: F401
from fraud_detection_tpu.lifecycle.store import (  # noqa: F401
    LifecycleStore,
    open_lifecycle_store,
)
from fraud_detection_tpu.lifecycle.swap import (  # noqa: F401
    ModelReloader,
    ModelSlot,
    warm_scorer,
)
