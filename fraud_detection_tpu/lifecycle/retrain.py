"""The retrain executor: what actually runs when watchtower says "retrain".

Assembles a training set from the base data plus durable feedback replay
(recent window + uniform-over-history reservoir —
:mod:`fraud_detection_tpu.lifecycle.store`), warm-starts the solver from
the incumbent champion's params, runs the SAME sharded data-parallel
L-BFGS fit the offline trainer uses (the DP mesh "Automatic Cross-Replica
Sharding" motivates, PAPERS.md), and evaluates the result against the
champion on a frozen holdout plus the recent-labeled-window slice through
the jitted challenger gate (:mod:`fraud_detection_tpu.lifecycle.gate`).

The warm start crosses scaler spaces correctly: the champion's params are
folded to raw-input space (the identity the serving scorer already relies
on), then re-expressed in the NEW scaler's space — so a champion fitted
under last month's feature statistics still seeds this month's fit at its
true decision boundary, not at a mis-scaled copy of it.

Methodological hygiene inherited from train.py: the holdout is carved with
the same stratified split and seed as offline training (so the gate's
"frozen holdout" is the artifact every champion was judged on), the scaler
is fitted on the train side only, and SMOTE never sees eval rows.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.ckpt.checkpoint import save_artifacts
from fraud_detection_tpu.data.loader import load_creditcard_csv, stratified_split
from fraud_detection_tpu.lifecycle.gate import (
    GateResult,
    GateThresholds,
    evaluate_gate,
)
from fraud_detection_tpu.models.logistic import FraudLogisticModel
from fraud_detection_tpu.monitor.baseline import build_baseline_profile, save_profile
from fraud_detection_tpu.ops.logistic import LogisticParams, logistic_fit_lbfgs
from fraud_detection_tpu.ops.quant import derive_calibration, save_calibration
from fraud_detection_tpu.ops.scaler import scaler_fit, scaler_transform
from fraud_detection_tpu.ops.scorer import fold_scaler_into_linear
from fraud_detection_tpu.ops.smote import smote

log = logging.getLogger("fraud_detection_tpu.lifecycle")

HOLDOUT_SEED = 42  # train.py's default split seed — the frozen holdout
HOLDOUT_FRACTION = 0.2


@dataclass
class RetrainResult:
    gate: GateResult
    challenger: FraudLogisticModel | None
    artifact_dir: str | None
    run_id: str | None
    champion_version: int | None
    metrics: dict = field(default_factory=dict)


def warm_start_from(champion, new_scaler) -> LogisticParams | None:
    """Champion params re-expressed in the new scaler's space (None when the
    champion family carries no linear params — e.g. GBT — and the fit must
    start cold)."""
    params = getattr(champion, "params", None)
    if params is None or not isinstance(params, LogisticParams):
        return None
    folded = fold_scaler_into_linear(params, getattr(champion, "scaler", None))
    w_raw = np.asarray(folded.coef, np.float32)
    b_raw = np.float32(folded.intercept)
    if new_scaler is None:
        return LogisticParams(coef=w_raw, intercept=b_raw)
    scale = np.asarray(new_scaler.scale, np.float32)
    mean = np.asarray(new_scaler.mean, np.float32)
    return LogisticParams(
        coef=w_raw * scale, intercept=b_raw + np.dot(mean, w_raw)
    )


def _replay_widened(
    spec, x, feature_names, seed, fx_w, fe_w, ft_w, fx_r, fe_r, ft_r,
):
    """Materialize the widened feature blocks for a ledger retrain: ONE
    causal replay (timestamp order) over base + feedback rows through the
    serving body. Base rows get the same seeded pseudo-entities the
    offline trainer assigns; feedback rows carry their recorded entity/
    timestamp (rows persisted without them replay through the null slot,
    ordered after the base clock). Returns the widened base matrix, the
    widened feature names, the spec to stamp on the challenger (clock
    origin advanced to serve time), the final table snapshot, and the
    widened window/reservoir blocks."""
    import dataclasses as _dc

    from fraud_detection_tpu.ledger import (
        LEDGER_FEATURE_NAMES,
        materialize_features,
        synthesize_entities,
    )

    n_b, n_w, n_r = x.shape[0], fx_w.shape[0], fx_r.shape[0]
    ents_b, ts_b = synthesize_entities(
        x, feature_names, seed,
        config.ledger_synth_events_per_entity(),
    )
    base_max = float(ts_b.max()) if n_b else 0.0

    def fb_meta(ents, ts, n, newest_first: bool, offset: float):
        ents = list(ents) if ents else [None] * n
        out_ts = np.zeros(n, np.float32)
        for i in range(n):
            t = float(ts[i]) if ts is not None and i < len(ts) else 0.0
            if t > 0:
                out_ts[i] = spec.rel_ts(t)
            else:
                # no recorded event time: order after the base clock,
                # preserving the fetch order (window rows arrive newest
                # first — reverse so older rows replay first)
                rank = (n - i) if newest_first else (i + 1)
                out_ts[i] = base_max + offset + rank
        return ents, out_ts

    ents_r, ts_r = fb_meta(fe_r, ft_r, n_r, False, 0.25)
    ents_w, ts_w = fb_meta(fe_w, ft_w, n_w, True, 0.5)
    all_x = np.concatenate([a for a in (x, fx_w, fx_r) if a.size]) if (
        n_w or n_r
    ) else x
    all_ents = ents_b + (ents_w if n_w else []) + (ents_r if n_r else [])
    all_ts = np.concatenate(
        [a for a, k in ((ts_b, n_b), (ts_w, n_w), (ts_r, n_r)) if k]
    )
    feats, final_state = materialize_features(spec, all_x, all_ents, all_ts)
    xw = np.concatenate([all_x, feats], axis=1).astype(np.float32)
    new_spec = _dc.replace(
        spec, ts_origin=time.time() - (float(all_ts.max()) + 1.0)
    )
    names = list(feature_names) + list(LEDGER_FEATURE_NAMES)
    return (
        xw[:n_b], names, new_spec, final_state,
        xw[n_b : n_b + n_w], xw[n_b + n_w :],
    )


def run_retrain(
    store,
    champion,
    champion_version: int | None,
    reason: str = "",
    data_csv: str | None = None,
    use_smote: bool = True,
    max_iter: int = 200,
    seed: int = HOLDOUT_SEED,
    thresholds: GateThresholds | None = None,
    tracking_client=None,
) -> RetrainResult:
    """One full retrain → gate pass. Pure with respect to the registry: the
    conductor decides what to do with a passing challenger (register,
    alias, state transitions); this function only trains and judges."""
    from fraud_detection_tpu.tracking import TrackingClient

    t0 = time.time()
    client = tracking_client or TrackingClient()
    thresholds = thresholds or GateThresholds.from_config()

    # ---- base data + frozen holdout (the split every champion was judged on)
    x, y, feature_names = load_creditcard_csv(data_csv or config.data_csv())
    train_idx, test_idx = stratified_split(y, HOLDOUT_FRACTION, seed)

    # ---- feedback replay: recent window + history reservoir (raw features).
    # The window is split disjointly: even rows replay into TRAINING, odd
    # rows become the gate's recent-eval slice — evaluating the challenger
    # on rows it trained on would inflate its recent AUC vs a champion that
    # never saw them (train-set evaluation) and let a worse model pass.
    # Interleaved (not chronological) so both halves span the same period.
    ledger_spec = getattr(champion, "ledger_spec", None)
    ledger_state = None
    wide_spec = getattr(champion, "wide_spec", None)
    if wide_spec is None and config.wide_enabled():
        if ledger_spec is not None:
            # the two widenings are mutually exclusive by construction
            # (models/logistic refuses both sidecars) — and a ledger
            # champion widens to the SAME total width as a cross-widened
            # block (K == n_cross == 4), so entering the wide path here
            # would feed cross contributions into the champion's velocity
            # coefficients at the gate. Keep the ledger retrain.
            log.warning(
                "WIDE_ENABLED ignored: the champion is ledger-widened — "
                "retraining the ledger family instead"
            )
        else:
            # WIDE_ENABLED retrains fit the wide family even under a
            # narrow champion — the narrow→wide promotion flow: the
            # challenger's crosses start from a zero table, the warm
            # start seeds the base slice from the incumbent, and the
            # gate judges each model at its own width over the same rows
            from fraud_detection_tpu.ops.crosses import spec_from_config

            wide_spec = spec_from_config(x.shape[1])
    wide_table = None
    fps_base = fps_w = fps_r = None
    if wide_spec is not None:
        # broadside: the wide challenger retrains on the SAME hashed
        # crosses serving computes — recorded entities for feedback rows
        # (the meta fetch rides the same store read as the rows), the
        # ledger's seeded pseudo-entities for the entity-less base CSV.
        # The base block itself stays unwidened: the cross contributions
        # depend on the table being FITTED, so widening happens at gate /
        # profile time from the learned table.
        from fraud_detection_tpu.ledger.replay import synthesize_entities
        from fraud_detection_tpu.ops.crosses import entity_fingerprints

        fx_w, fs_w, fy_w, fe_w, ft_w = store.window_rows_meta()
        fx_r, fs_r, fy_r, fe_r, ft_r = store.reservoir_rows_meta()
        ents_b, _ = synthesize_entities(
            x, feature_names, seed, config.ledger_synth_events_per_entity()
        )
        fps_base = entity_fingerprints(ents_b, x.shape[0])
        fps_w = entity_fingerprints(fe_w, fx_w.shape[0])
        fps_r = entity_fingerprints(fe_r, fx_r.shape[0])
    elif ledger_spec is None:
        fx_w, fs_w, fy_w = store.window_rows()
        fx_r, fs_r, fy_r = store.reservoir_rows()
    else:
        # ledger (stateful feature engine): a widened champion retrains on
        # WIDENED features — base + feedback rows replay through the SAME
        # traced body the serving flush runs (ledger/replay), in timestamp
        # order, so the challenger's training features are, by
        # construction, the features serving computes (skew is
        # structurally impossible). The meta fetch rides the same store
        # read as the rows, so entities/timestamps cannot misalign.
        fx_w, fs_w, fy_w, fe_w, ft_w = store.window_rows_meta()
        fx_r, fs_r, fy_r, fe_r, ft_r = store.reservoir_rows_meta()
        (
            x, feature_names, ledger_spec, ledger_state, fx_w, fx_r,
        ) = _replay_widened(
            ledger_spec, x, feature_names, seed,
            fx_w, fe_w, ft_w, fx_r, fe_r, ft_r,
        )
    x_train, y_train = x[train_idx], y[train_idx]
    x_hold, y_hold = x[test_idx], y[test_idx]
    fx_train, fy_train = fx_w[0::2], fy_w[0::2]
    fx_eval, fy_eval = fx_w[1::2], fy_w[1::2]
    fps_fit = fps_hold = fps_eval = None
    x_hold_champ = fx_eval_champ = None
    if wide_spec is not None:
        fps_hold = fps_base[test_idx]
        fps_eval = fps_w[1::2]
        fps_fit = np.concatenate(
            [
                a for a in (fps_base[train_idx], fps_w[0::2], fps_r)
                if a.size
            ]
        ).astype(np.uint32)
    replay_x = [a for a in (fx_train, fx_r) if a.size]
    replay_y = [a for a in (fy_train, fy_r) if a.size]
    n_replay = int(sum(a.shape[0] for a in replay_x))
    if replay_x:
        if any(a.shape[1] != x_train.shape[1] for a in replay_x):
            raise ValueError(
                "feedback feature arity does not match the base dataset"
            )
        x_fit = np.concatenate([x_train, *replay_x]).astype(np.float32)
        y_fit = np.concatenate(
            [y_train, *(a.astype(y_train.dtype) for a in replay_y)]
        )
    else:
        x_fit, y_fit = x_train, y_train

    # MapReduce aggregation of the sharded feedback pools (2403.07128,
    # DrJAX idiom): each mesh shard summarizes its slice of the replay
    # rows, one psum reduces the summaries — the pool composition the run
    # records and operators audit, computed without a host-side row loop.
    pool_stats: dict | None = None
    if replay_x:
        from fraud_detection_tpu.mesh.retrain import mapreduce_pool_stats

        # scores captured from the SAME fetch as the replay rows above —
        # a second store read could interleave with arriving feedback and
        # silently misalign scores with rows
        pool_scores = np.concatenate(
            [fs_w[0::2], fs_r]
        ) if fs_r.size else fs_w[0::2]
        try:
            pool_stats = mapreduce_pool_stats(
                np.concatenate(replay_x),
                np.concatenate(replay_y),
                pool_scores,
            )
        except Exception as e:
            log.warning("feedback pool aggregation failed: %s", e)

    with client.start_run() as run:
        run.log_params(
            {
                "trigger": "conductor_retrain",
                "reason": reason[:500],
                "n_base_rows": int(len(y_train)),
                "n_feedback_rows": n_replay,
                "warm_start": champion_version is not None,
                "parent_version": champion_version,
                "use_smote": use_smote,
                "max_iter": max_iter,
                "device": jax.devices()[0].platform,
                "n_devices": jax.device_count(),
                "mesh_retrain": config.mesh_retrain(),
            }
        )
        if pool_stats is not None:
            run.log_metric("feedback_label_rate", pool_stats["label_rate"])
            run.log_metric("feedback_score_mean", pool_stats["score_mean"])

        # ---- scaler on the train side only, then the sharded DP fit
        scaler = scaler_fit(x_fit)
        xs_fit = scaler_transform(scaler, x_fit)
        ws = None if wide_spec is not None else warm_start_from(champion, scaler)
        x_final, y_final = xs_fit, y_fit
        if use_smote and wide_spec is not None:
            # SMOTE interpolates feature rows; a synthetic row carries no
            # hashable entity/cross identity, so the wide fit trains on
            # the class-weighted raw mix instead
            use_smote = False
            run.set_tag("smote_skipped", "wide family: crosses are discrete")
        if use_smote:
            try:
                x_final, y_final = smote(
                    xs_fit, y_fit, jax.random.key(seed + 1000)
                )
            except ValueError as e:
                # degenerate minority (too few positives for k-NN): fit on
                # the raw mix rather than failing the whole loop closure
                log.warning("retrain SMOTE skipped: %s", e)
                run.set_tag("smote_skipped", str(e))
        wide_names = None
        wide_scaler = None
        if wide_spec is not None:
            # broadside: the 2-D (data × model) sharded wide fit
            # (mesh/retrain.wide_sgd_fit, 2004.13336 extended to the
            # tensor-parallel mesh) — grads psum_scatter on the data axis,
            # the cross-weight table column-owned on the model axis. The
            # warm start crosses scaler spaces on the BASE slice; the
            # champion's table warm-starts verbatim (cross contributions
            # are raw-space, no scaler touches them).
            from fraud_detection_tpu.mesh.retrain import (
                wide_sgd_fit,
                wide_training_mesh,
            )
            from fraud_detection_tpu.ops.crosses import cross_indices

            ws_base = None
            champ_params = getattr(champion, "params", None)
            if isinstance(champ_params, LogisticParams):
                # the warm_start_from discipline on the BASE slice: a
                # champion without linear params (GBT) cold-starts
                folded = fold_scaler_into_linear(
                    champ_params, getattr(champion, "scaler", None)
                )
                w_raw = np.asarray(folded.coef, np.float32)[: wide_spec.n_base]
                sc_v = np.asarray(scaler.scale, np.float32)
                mu_v = np.asarray(scaler.mean, np.float32)
                ws_base = LogisticParams(
                    coef=w_raw * sc_v,
                    intercept=(
                        np.float32(folded.intercept) + np.dot(mu_v, w_raw)
                    ),
                )
            # indices hash the RAW rows — the values serving hashes
            idx_fit = cross_indices(x_fit, fps_fit, wide_spec)
            has_fit = (fps_fit != 0).astype(np.float32)
            params, wide_table = wide_sgd_fit(
                x_final, idx_fit, has_fit, y_final, wide_spec,
                epochs=max(max_iter // 10, 5), seed=seed,
                class_weight="balanced",
                warm_start=(ws_base, getattr(champion, "wide_table", None)),
                mesh=wide_training_mesh(),
            )
            from fraud_detection_tpu.ops.crosses import (
                widen_scaler,
                widen_with_crosses,
            )

            wide_names = list(feature_names) + list(wide_spec.cross_names)
            wide_scaler = widen_scaler(scaler, wide_spec.n_cross)
            challenger = FraudLogisticModel(
                params, wide_scaler, wide_names,
                wide_spec=wide_spec, wide_table=wide_table,
            )
            # the gate judges WIDENED slices — the same widened block the
            # fused flush materializes for these rows, so the gate's AUC
            # measures each model as it would actually serve: the
            # challenger's block gathers from ITS freshly fitted table,
            # and a wide CHAMPION gets its OWN view from its own table
            # (feeding it the challenger's contributions would mis-score
            # the incumbent and bias every wide→wide promotion)
            champ_table = getattr(champion, "wide_table", None)
            if champ_table is not None:
                x_hold_champ = widen_with_crosses(
                    x_hold, fps_hold, champ_table, champion.wide_spec
                )
                fx_eval_champ = (
                    widen_with_crosses(
                        fx_eval, fps_eval, champ_table, champion.wide_spec
                    )
                    if fx_eval.size
                    else None
                )
            x_hold = widen_with_crosses(x_hold, fps_hold, wide_table, wide_spec)
            if fx_eval.size:
                fx_eval = widen_with_crosses(
                    fx_eval, fps_eval, wide_table, wide_spec
                )
        elif config.mesh_retrain():
            # MESH_RETRAIN=1: the warm-started update itself shards across
            # the mesh — each replica owns 1/N of the params and optimizer
            # state (2004.13336) instead of replicating the full update
            from fraud_detection_tpu.mesh.retrain import mesh_sgd_fit

            params = mesh_sgd_fit(
                x_final, y_final, epochs=max(max_iter // 20, 3),
                warm_start=ws,
            )
        else:
            params = logistic_fit_lbfgs(
                x_final, y_final, max_iter=max_iter, sharded=True,
                warm_start=ws,
            )
        if wide_spec is None:
            challenger = FraudLogisticModel(
                params, scaler, list(feature_names),
                ledger_spec=ledger_spec, ledger_state=ledger_state,
            )

        # ---- the challenger gate: frozen holdout + recent labeled window
        gate = evaluate_gate(
            champion,
            challenger,
            x_hold,
            y_hold,
            x_recent=fx_eval if fx_eval.size else None,
            y_recent=fy_eval if fy_eval.size else None,
            thresholds=thresholds,
            x_holdout_champion=x_hold_champ,
            x_recent_champion=fx_eval_champ,
        )
        for k, v in gate.metrics.items():
            run.log_metric(k, float(v))
        run.set_tag("gate_passed", gate.passed)
        if gate.reasons:
            run.set_tag("gate_reasons", "; ".join(gate.reasons)[:900])

        # ---- artifacts: model + drift baseline beside it (every resolution
        # path carries its own monitor profile, train.py contract)
        artifact_dir = run.artifact_path("model")
        if wide_spec is not None:
            from fraud_detection_tpu.ops.crosses import save_wide

            save_artifacts(artifact_dir, params, wide_scaler, wide_names)
            save_wide(artifact_dir, wide_spec, wide_table)
        else:
            save_artifacts(artifact_dir, params, scaler, list(feature_names))
        if ledger_spec is not None:
            # stamp the replayed entity table beside the challenger: a
            # promotion hot-swaps the model AND its table snapshot, so
            # serving resumes exactly where the training replay ended
            from fraud_detection_tpu.ledger.state import save_ledger

            save_ledger(artifact_dir, ledger_spec, ledger_state)
        if scaler is not None:
            # quickwire: stamp the int8 wire calibration beside the
            # challenger's weights — a promotion hot-swaps BOTH, so the
            # serving quantizer always matches the scored model
            save_calibration(
                artifact_dir,
                derive_calibration(
                    wide_scaler if wide_spec is not None else scaler
                ),
            )
        hold_scores = np.asarray(
            challenger.scorer.predict_proba(np.asarray(x_hold, np.float32))
        )
        if wide_spec is not None:
            # the drift baseline covers the WIDENED block (base + cross
            # contributions) — the distribution the fused wide flush
            # bins. Reuses the fit's cross indices: rehashing x_fit here
            # would duplicate a full-dataset device pass
            contrib_fit = wide_table[idx_fit] * has_fit[:, None]
            profile = build_baseline_profile(
                np.concatenate([x_fit, contrib_fit], axis=1).astype(
                    np.float32
                ),
                hold_scores, feature_names=wide_names,
            )
        else:
            profile = build_baseline_profile(
                x_fit, hold_scores, feature_names=list(feature_names)
            )
        save_profile(artifact_dir, profile)

        wall = time.time() - t0
        run.log_metric("retrain_seconds", wall)
        metrics = dict(gate.metrics)
        metrics.update(
            {
                "retrain_seconds": wall,
                "n_feedback_rows": n_replay,
                "n_fit_rows": int(x_final.shape[0]),
            }
        )
        return RetrainResult(
            gate=gate,
            challenger=challenger,
            artifact_dir=artifact_dir,
            run_id=run.run_id,
            champion_version=champion_version,
            metrics=metrics,
        )
