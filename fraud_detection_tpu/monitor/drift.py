"""Online drift accumulators: jitted sliding-window statistics.

Driven from the microbatch scorer path. On the fastlane hot path the drift
fold doesn't even get its own device call: ``_fused_flush`` traces the
scorer's raw score body together with the histogram update into ONE
donated, multi-output program per shape bucket, so a serving flush pays a
single dispatch for scores *and* monitoring (see service/microbatch).
Feedback replays and direct updates use ``_window_update`` (window state
donated so XLA updates the buffers in place), which bins the batch against
the baseline edges and folds it into exponentially-decayed window
histograms. No per-row host work; the host only computes the scalar decay
factor.

Statistics are derived lazily (``_drift_stats``, a second small jitted
program) when ``/monitor/status`` or a Prometheus scrape asks:

- **PSI** per feature and for the score distribution — the population
  stability index ``Σ (p−q)·ln(p/q)`` over smoothed bin masses (industry
  convention: <0.1 stable, 0.1–0.2 moderate, >0.2 drifted);
- **KS** — the two-sample Kolmogorov–Smirnov statistic
  ``max |CDF_p − CDF_q|`` from the same histograms;
- **windowed ECE** — expected calibration error over uniform score bins,
  accumulated only for rows that arrive with feedback labels (fraud labels
  are delayed; unlabeled traffic leaves calibration state untouched).

The window is exponential (half-life in rows) rather than a ring of
per-batch histograms: O(1) state, O(1) update, and the half-life knob maps
directly to "how fast do alerts forget".
"""

from __future__ import annotations

import threading
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.monitor.baseline import (
    BaselineProfile,
    feature_histogram,
    score_histogram,
)
from fraud_detection_tpu.ops.scorer import _bucket, _raw_score_linear
from fraud_detection_tpu.utils import lockdep

PSI_EPS = 1e-4
N_CALIB_BINS = 10


class DriftWindow(NamedTuple):
    """Decayed window state — a pytree of device buffers, donated through
    every update so monitoring holds one live copy."""

    feature_counts: jax.Array  # (d, n_bins)
    score_counts: jax.Array  # (s_bins,)
    calib_count: jax.Array  # (c_bins,) labeled rows per score bin
    calib_conf: jax.Array  # (c_bins,) Σ score over labeled rows
    calib_label: jax.Array  # (c_bins,) Σ label over labeled rows
    n_rows: jax.Array  # () decayed row count


class DriftStats(NamedTuple):
    feature_psi: jax.Array  # (d,)
    feature_ks: jax.Array  # (d,)
    score_psi: jax.Array  # ()
    score_ks: jax.Array  # ()
    ece: jax.Array  # ()
    n_labeled: jax.Array  # ()


def init_window(
    n_features: int, n_feature_bins: int, n_score_bins: int,
    n_calib_bins: int = N_CALIB_BINS,
) -> DriftWindow:
    return DriftWindow(
        feature_counts=jnp.zeros((n_features, n_feature_bins), jnp.float32),
        score_counts=jnp.zeros((n_score_bins,), jnp.float32),
        calib_count=jnp.zeros((n_calib_bins,), jnp.float32),
        calib_conf=jnp.zeros((n_calib_bins,), jnp.float32),
        calib_label=jnp.zeros((n_calib_bins,), jnp.float32),
        n_rows=jnp.zeros((), jnp.float32),
    )


def _narrow_scores(scores: jax.Array, out_dtype) -> jax.Array:
    """Cast the fetched score output to the d2h return wire (quickwire
    compressed d2h). Traced at the END of the fused programs, so the drift
    fold always bins full-precision f32 scores — only the bytes crossing
    the device→host link narrow. ``uint8`` ships ``round(p·255)`` codes
    (decoded host-side by ops/scorer.decode_scores_into)."""
    if out_dtype == jnp.uint8:
        return jnp.round(scores * 255.0).astype(jnp.uint8)
    if out_dtype == jnp.float32:
        return scores
    return scores.astype(out_dtype)


def _narrow_reasons(
    idx: jax.Array, val: jax.Array, n_features: int, out_dtype
) -> tuple[jax.Array, jax.Array]:
    """Compress the fetched reason codes for the d2h link (lantern).

    Indices are feature positions: one byte covers any schema up to 256
    features (the Kaggle schema is 30), so they always ship ``uint8`` when
    they fit. Values follow the score return wire's spirit — f16 halves
    the bytes when any narrow wire is configured — except ``uint8``:
    attributions are signed and unbounded, so the probability lattice does
    not apply and the uint8 wire ships f16 values instead. Both decode
    host-side into the staging slot's preallocated explain buffers
    (ops/scorer.decode_explain_into)."""
    if n_features <= 256:
        idx = idx.astype(jnp.uint8)
    if out_dtype != jnp.float32:
        val = val.astype(jnp.float16)
    return idx, val


def _topk_attributions(
    xf: jax.Array, explain_args, explain_k: int
) -> tuple[jax.Array, jax.Array]:
    """The lantern/evergreen explain leg: exact interventional SHAP
    attributions over the values the model actually scored (``xf`` is the
    dequantized/upcast f32 batch the drift histograms bin), reduced to the
    per-row arg-top-k.

    Family dispatch rides the ``explain_args`` pytree STRUCTURE (part of
    the jit cache key, so each family compiles its own executable under
    the same fused program): a ``TreeShapExplainer`` traces the exact
    interventional TreeSHAP body (``ops/tree_shap._raw_tree_shap`` — the
    GPUTreeShap-style all-rows formulation, arXiv 2010.13972), anything
    else is the linear family's ``(coef, background_mean)`` pair. Both
    share their standalone explainer's body, so fused attributions are
    bitwise the standalone explainer's on the f32 wire for BOTH
    families."""
    from fraud_detection_tpu.ops.linear_shap import (
        _raw_linear_shap,
        topk_reasons,
    )
    from fraud_detection_tpu.ops.tree_shap import (
        TreeShapExplainer,
        _raw_tree_shap,
    )

    if isinstance(explain_args, TreeShapExplainer):
        return topk_reasons(
            _raw_tree_shap(explain_args.model, explain_args.bg_table, xf),
            explain_k,
        )
    coef, background_mean = explain_args
    return topk_reasons(_raw_linear_shap(coef, background_mean, xf), explain_k)


def _fold_serving_batch(
    window: DriftWindow,
    xf: jax.Array,
    scores: jax.Array,
    valid: jax.Array,
    decay: jax.Array,
    feature_edges: jax.Array,
    score_edges: jax.Array,
) -> DriftWindow:
    """The serving-flush window fold — ONE body shared by every fused
    program (plain/quant × with/without the explain leg, single-device and
    the shard_map body): bin the batch the model actually scored,
    decay-fold the drift histograms, pass calibration state through
    untouched (serving batches carry no labels). A fold change edited here
    reaches all the fused programs at once — they can never desync."""
    fc = feature_histogram(xf, feature_edges, weights=valid)
    sc = score_histogram(scores, score_edges, weights=valid)
    return DriftWindow(
        feature_counts=window.feature_counts * decay + fc,
        score_counts=window.score_counts * decay + sc,
        calib_count=window.calib_count,
        calib_conf=window.calib_conf,
        calib_label=window.calib_label,
        n_rows=window.n_rows * decay + jnp.sum(valid),
    )


@partial(jax.jit, static_argnames=("score_fn", "out_dtype"), donate_argnums=(0,))
def _fused_flush(
    window: DriftWindow,
    x: jax.Array,  # (b, d) staged batch, possibly narrow-IO encoded
    valid: jax.Array,  # (b,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree: the scorer's device params
    *,
    score_fn,  # static: module-level raw score body (ops/scorer)
    out_dtype=jnp.float32,  # static: d2h return wire (quickwire)
) -> tuple[jax.Array, DriftWindow]:
    """The fastlane flush program: scores **and** the drift-window update in
    ONE device dispatch per shape bucket.

    The serving flush previously paid two dispatches — the scorer's
    ``_score`` and, on the watchtower ingest thread, ``_window_update`` —
    plus a second h2d upload of the same batch. Here ``score_fn`` (a
    module-level raw score body, static so jit caches one executable per
    (bucket, scorer-family)) traces inline with the histogram fold, the
    window state is donated through, and the scores come back as the only
    fetched output — optionally narrowed to the f16/uint8 return wire
    (``out_dtype``), since the d2h link measures ~70× slower than h2d.
    Serving flushes carry no feedback labels, so the calibration state
    passes through untouched (exactly what ``_window_update`` computes for
    an unlabeled batch: zero label weights, calibration decay 1.0) —
    delayed-feedback replays keep using ``_window_update`` off the hot
    path.

    For the bf16 wire the drift histograms bin the bf16-rounded values
    rather than the raw f32 rows — the same values the model actually
    scored, which is the distribution drift must monitor. The int8 wire
    ships quantization codes that are NOT raw-space; it dispatches the
    sibling :func:`_fused_flush_quant` program instead, which dequantizes
    in-program.
    """
    xf = x.astype(jnp.float32)
    scores = score_fn(score_args, x).astype(jnp.float32)
    return _narrow_scores(scores, out_dtype), _fold_serving_batch(
        window, xf, scores, valid, decay, feature_edges, score_edges
    )


@partial(
    jax.jit,
    static_argnames=("score_fn", "score_codes", "out_dtype"),
    donate_argnums=(0,),
)
def _fused_flush_quant(
    window: DriftWindow,
    x: jax.Array,  # (b, d) int8 quantization codes
    valid: jax.Array,  # (b,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree: the scorer's device params
    dequant_scale: jax.Array,  # (d,) per-feature dequant scale
    *,
    score_fn,  # static: module-level raw score body (ops/scorer)
    score_codes: bool,  # static: score_fn consumes codes (True) or xf
    out_dtype=jnp.float32,  # static: d2h return wire
) -> tuple[jax.Array, DriftWindow]:
    """The quickwire flush program: fused dequant·score·drift in ONE
    device dispatch per shape bucket.

    The int8 wire previously died at the flush boundary — codes aren't
    raw-space, so the fused path demoted to the split two-dispatch flush.
    Here the dequantization lives INSIDE the program: ``xf = codes ·
    dequant_scale`` feeds the drift histograms (they bin the dequantized
    values the model actually scored, against the same raw-space baseline
    edges as the f32 path — PSI/KS stay comparable across wire formats
    within the gated tolerance), while scoring either consumes the codes
    directly (``score_codes=True`` — linear family, dequant scale folded
    into the weights, bitwise-identical to the split int8 path) or the
    shared ``xf`` (``score_codes=False`` — explicit dequant for kernels
    that need raw-space inputs; the multiply is already paid for the
    histogram bin). Window donated through, calibration state untouched,
    return wire narrowable — exactly the fastlane contract, now quantized
    end to end.
    """
    xf = x.astype(jnp.float32) * dequant_scale
    scores = score_fn(score_args, x if score_codes else xf).astype(jnp.float32)
    return _narrow_scores(scores, out_dtype), _fold_serving_batch(
        window, xf, scores, valid, decay, feature_edges, score_edges
    )


@partial(
    jax.jit,
    static_argnames=("score_fn", "explain_k", "out_dtype"),
    donate_argnums=(0,),
)
def _fused_flush_explain(
    window: DriftWindow,
    x: jax.Array,  # (b, d) staged batch, possibly narrow-IO encoded
    valid: jax.Array,  # (b,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree: the scorer's device params
    explain_args,  # (coef (d,), background_mean (d,)) — linear-SHAP params
    *,
    score_fn,  # static: module-level raw score body (ops/scorer)
    explain_k: int,  # static: reason codes per row (pre-clamped to d)
    out_dtype=jnp.float32,  # static: d2h return wire (quickwire)
) -> tuple[jax.Array, jax.Array, jax.Array, DriftWindow]:
    """The lantern flush program: scores, per-row top-k SHAP reason codes,
    AND the drift-window fold in ONE device dispatch per shape bucket.

    The reference system ships explanations minutes behind the score on an
    async worker; device-side linear SHAP measures ~3.9B values/s
    (BENCH_r03), so the attribution belongs INSIDE the accelerator program
    (GPUTreeShap, arXiv 2010.13972; TPU-XAI, arXiv 2103.11927). The explain
    leg is one fused elementwise expression + a top-k over d=30 features —
    the same ``xf`` the drift histograms already bin feeds it, so the
    marginal device cost is bounded (bench gate: ≥0.8× the plain fused
    flush). Attributions are bitwise the standalone ``ops/linear_shap``
    values (shared body), and the window fold is bitwise the plain
    ``_fused_flush``'s — enabling explanations cannot move monitoring
    state. Returns ``(scores, reason_idx, reason_val, window)``; the
    reason outputs ride the compressed d2h wire (uint8 indices, f16 values
    on narrow return wires — :func:`_narrow_reasons`)."""
    xf = x.astype(jnp.float32)
    scores = score_fn(score_args, x).astype(jnp.float32)
    idx, val = _topk_attributions(xf, explain_args, explain_k)
    idx, val = _narrow_reasons(idx, val, x.shape[1], out_dtype)
    return (
        _narrow_scores(scores, out_dtype),
        idx,
        val,
        _fold_serving_batch(
            window, xf, scores, valid, decay, feature_edges, score_edges
        ),
    )


@partial(
    jax.jit,
    static_argnames=("score_fn", "score_codes", "explain_k", "out_dtype"),
    donate_argnums=(0,),
)
def _fused_flush_quant_explain(
    window: DriftWindow,
    x: jax.Array,  # (b, d) int8 quantization codes
    valid: jax.Array,  # (b,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree: the scorer's device params
    dequant_scale: jax.Array,  # (d,) per-feature dequant scale
    explain_args,  # (coef (d,), background_mean (d,)) — RAW-space SHAP params
    *,
    score_fn,  # static: module-level raw score body (ops/scorer)
    score_codes: bool,  # static: score_fn consumes codes (True) or xf
    explain_k: int,  # static: reason codes per row (pre-clamped to d)
    out_dtype=jnp.float32,  # static: d2h return wire
) -> tuple[jax.Array, jax.Array, jax.Array, DriftWindow]:
    """The lantern flush on the quantized wire: fused
    dequant·score·explain·drift in ONE dispatch.

    The attribution is EXPLICIT-DEQUANT: ``xf = codes · dequant_scale`` —
    already paid for the drift histograms — feeds the raw-space linear-SHAP
    body, so reason codes explain the values the model actually scored
    (the quantized lattice points), not the pre-quantization floats the
    client sent. Versus the f32 wire the attributions therefore carry the
    quantization error and parity is tolerance-gated, exactly like the
    quant score parity; versus a standalone explainer over the SAME
    dequantized rows they are bitwise."""
    xf = x.astype(jnp.float32) * dequant_scale
    scores = score_fn(score_args, x if score_codes else xf).astype(jnp.float32)
    idx, val = _topk_attributions(xf, explain_args, explain_k)
    idx, val = _narrow_reasons(idx, val, x.shape[1], out_dtype)
    return (
        _narrow_scores(scores, out_dtype),
        idx,
        val,
        _fold_serving_batch(
            window, xf, scores, valid, decay, feature_edges, score_edges
        ),
    )


@partial(
    jax.jit,
    static_argnames=("score_fn", "explain_k", "amount_col", "out_dtype"),
    donate_argnums=(0, 1),
)
def _fused_flush_ledger(
    window: DriftWindow,
    ledger,  # ledger.LedgerState — donated, like the window
    x: jax.Array,  # (b, d_base) staged batch (wire codes on a quant wire)
    valid: jax.Array,  # (b,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    feature_edges: jax.Array,  # (d_base + K, bins - 1) WIDENED edges
    score_edges: jax.Array,
    score_args,  # pytree: raw-space params over the WIDENED feature block
    slot_idx: jax.Array,  # (b,) int32 ledger slot per row
    fp: jax.Array,  # (b,) uint32 entity fingerprint (0 = none)
    ts: jax.Array,  # (b,) f32 event timestamp
    has_entity: jax.Array,  # (b,) f32 1.0 when the row carries an entity
    null_features: jax.Array,  # (K,) features for entity-less rows
    halflife_s: jax.Array,  # () f32 ledger decay half-life
    dequant_scale=None,  # (d_base,) per-feature dequant scale (int8 wire)
    explain_args=None,  # (coef (d_base+K,), background_mean) — lantern leg
    *,
    score_fn,  # static: module-level raw score body (ops/scorer)
    explain_k: int = 0,  # static: reason codes per row (0 = no explain leg)
    amount_col: int = -1,  # static: Amount column in the base row
    out_dtype=jnp.float32,  # static: d2h return wire
):
    """The ledger flush program: per-entity velocity state read+update,
    feature widening, scoring, (optional) top-k reason codes, AND the
    drift-window fold — ONE donated device dispatch per shape bucket.

    The stateful extension of the fastlane/quickwire/lantern family: the
    hashed entity table (``ledger``) is donated through every flush exactly
    like the drift window, the K velocity features come from the SAME
    traced body training's replay materializes with
    (``ledger/features._ledger_read_update`` — train/serve skew is
    structurally impossible), and the widened ``[b, d_base + K]`` block
    feeds scoring, the drift histograms (widened baseline edges — drift
    monitoring covers the velocity features for free), and the explain leg
    when ``explain_k > 0``. One program covers all four wire/explain
    combos: a quant wire passes ``dequant_scale`` (codes dequantize
    in-program before the ledger/concat — explicit-dequant scoring over
    raw-space weights, the multiply shared with the histogram bin), and
    lantern passes ``explain_args`` + ``explain_k``. Entity-less rows
    (legacy clients) read the stamped null-profile features through the
    reserved null path and leave the table bitwise untouched — so do
    all-padding warmups. The body lives in :func:`_ledger_serving_body` —
    ONE expression shared with the mesh shard twin, so the widening
    sequence can never desync between single-device and N-shard (the
    ``_fold_serving_batch`` lesson)."""
    return _ledger_serving_body(
        window, ledger, x, valid, decay, feature_edges, score_edges,
        score_args, slot_idx, fp, ts, has_entity, null_features,
        halflife_s, dequant_scale, explain_args,
        score_fn=score_fn, explain_k=explain_k, amount_col=amount_col,
        out_dtype=out_dtype,
    )


def _ledger_serving_body(
    window, ledger, x, valid, decay, feature_edges, score_edges,
    score_args, slot_idx, fp, ts, has_entity, null_features, halflife_s,
    dequant_scale=None, explain_args=None,
    *, score_fn, explain_k=0, amount_col=-1, out_dtype=jnp.float32,
):
    """The ONE stateful widening sequence: dequant → amount slice → ledger
    read-update → concat → score → (explain) → drift fold. Traced by
    ``_fused_flush_ledger`` AND the shard_map body in mesh/shardflush — a
    change edited here reaches both at once, so the N-shard-bitwise-
    matches-single-device contract holds by construction, not by keeping
    two copies in sync."""
    from fraud_detection_tpu.ledger.features import _ledger_read_update

    xb = x.astype(jnp.float32)
    if dequant_scale is not None:
        xb = xb * dequant_scale
    amount = xb[:, amount_col]
    feats, new_ledger = _ledger_read_update(
        ledger, slot_idx, fp, ts, amount, has_entity, null_features,
        halflife_s,
    )
    xf = jnp.concatenate([xb, feats], axis=1)
    scores = score_fn(score_args, xf).astype(jnp.float32)
    new_window = _fold_serving_batch(
        window, xf, scores, valid, decay, feature_edges, score_edges
    )
    if explain_k > 0:
        idx, val = _topk_attributions(xf, explain_args, explain_k)
        idx, val = _narrow_reasons(idx, val, xf.shape[1], out_dtype)
        return (
            _narrow_scores(scores, out_dtype), idx, val,
            new_window, new_ledger,
        )
    return _narrow_scores(scores, out_dtype), new_window, new_ledger


def _wide_serving_body(
    window, x, valid, decay, feature_edges, score_edges, score_args,
    wide_table, fp, has_entity, dequant_scale=None, explain_args=None,
    *, cross_spec, explain_k=0, out_dtype=jnp.float32, model_axis=None,
):
    """The ONE wide (broadside) serving sequence: dequant → hashed cross
    indices → table gather → concat → score → (explain) → drift fold.
    Traced by ``_fused_flush_wide`` AND the 2-D shard body in
    mesh/shardflush — the ``_ledger_serving_body`` discipline, so the
    2-D-shard-bitwise-matches-single-device contract holds by
    construction.

    ``model_axis`` is None on a single device (full-table gather) and the
    mesh's model-axis name inside the shard body: there ``wide_table`` is
    this shard's column slice, the gather masks to its range, and ONE
    ``psum`` over the model axis assembles the widened block — each cross
    index lives on exactly one shard, so the reduce adds one real value
    and M−1 exact zeros (bitwise the single-device gather). The drift fold
    is masked to model-rank 0 (rows are replicated over the model axis;
    folding them M times would overcount the merged window), which keeps
    "per-(data,model)-shard windows merged only at scrape" exact."""
    from fraud_detection_tpu.ops.crosses import (
        _gather_contrib,
        _gather_contrib_shard,
        _raw_cross_indices,
    )

    xb = x.astype(jnp.float32)
    if dequant_scale is not None:
        xb = xb * dequant_scale
    idx = _raw_cross_indices(xb, fp, spec=cross_spec)
    if model_axis is None:
        contrib = _gather_contrib(wide_table, idx, has_entity)
        fold_valid = valid
    else:
        local = _gather_contrib_shard(wide_table, idx, has_entity, model_axis)
        # THE one model-axis collective on the wide hot path
        contrib = jax.lax.psum(local, model_axis)
        fold_valid = valid * (
            jax.lax.axis_index(model_axis) == 0
        ).astype(valid.dtype)
    xf = jnp.concatenate([xb, contrib], axis=1)
    scores = _raw_score_linear(score_args, xf).astype(jnp.float32)
    new_window = _fold_serving_batch(
        window, xf, scores, fold_valid, decay, feature_edges, score_edges
    )
    if explain_k > 0:
        ridx, rval = _topk_attributions(xf, explain_args, explain_k)
        ridx, rval = _narrow_reasons(ridx, rval, xf.shape[1], out_dtype)
        return _narrow_scores(scores, out_dtype), ridx, rval, new_window
    return _narrow_scores(scores, out_dtype), new_window


@partial(
    jax.jit,
    static_argnames=("cross_spec", "explain_k", "out_dtype"),
    donate_argnums=(0,),
)
def _fused_flush_wide(
    window: DriftWindow,
    x: jax.Array,  # (b, n_base) staged batch (wire codes on a quant wire)
    valid: jax.Array,  # (b,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    feature_edges: jax.Array,  # (n_base + n_cross, bins - 1) WIDENED edges
    score_edges: jax.Array,
    score_args,  # (widened raw-space coef, intercept)
    wide_table: jax.Array,  # (buckets,) the learned cross-weight table
    fp: jax.Array,  # (b,) uint32 entity fingerprint (0 = none)
    has_entity: jax.Array,  # (b,) f32 1.0 when the row carries an entity
    dequant_scale=None,  # (n_base,) per-feature dequant scale (int8 wire)
    explain_args=None,  # (widened coef, widened mean) — lantern leg
    *,
    cross_spec,  # static ops/crosses.CrossSpec (hashable geometry)
    explain_k: int = 0,  # static: reason codes per row (0 = no explain leg)
    out_dtype=jnp.float32,  # static: d2h return wire
):
    """The broadside flush program: hashed-cross widening, scoring,
    (optional) top-k reason codes AND the drift fold — ONE donated device
    dispatch per shape bucket. The wide sibling of ``_fused_flush_ledger``:
    same widened-block shape, but the extra columns are LEARNED hashed
    crosses gathered from ``wide_table`` instead of stateful velocity
    aggregates — no donated table, no scatters, so the hot path stays pure
    gather+GEMV. Null-entity rows (fp 0) leave the entire wide block
    zeroed (every template crosses the entity) and all-padding warmups
    leave the window bitwise unchanged. Registered in meshcheck
    (``broadside.flush``) and the compile sentinel."""
    return _wide_serving_body(
        window, x, valid, decay, feature_edges, score_edges, score_args,
        wide_table, fp, has_entity, dequant_scale, explain_args,
        cross_spec=cross_spec, explain_k=explain_k, out_dtype=out_dtype,
    )


@partial(jax.jit, donate_argnums=(0,))
def _window_update(
    window: DriftWindow,
    x: jax.Array,  # (n, d) padded batch
    scores: jax.Array,  # (n,)
    labels: jax.Array,  # (n,) feedback labels (0/1), garbage where unlabeled
    label_valid: jax.Array,  # (n,) 1.0 where labels[i] is real
    valid: jax.Array,  # (n,) 1.0 for real rows, 0.0 for bucket padding
    decay: jax.Array,  # () drift forgetting factor (live rows this batch)
    calib_decay: jax.Array,  # () calibration factor (labeled rows this batch)
    feature_edges: jax.Array,
    score_edges: jax.Array,
    calib_edges: jax.Array,
) -> DriftWindow:
    """Fold one scored batch into the window — the per-batch device call.

    ``valid`` masks rows into the DRIFT histograms (live traffic only);
    ``label_valid`` masks rows into the CALIBRATION state (labeled rows,
    zero on padding). They are independent so delayed feedback replays —
    already counted live — can fold calibration-only (valid=0). The decay
    factors are likewise independent: drift evidence fades in live-row
    time, calibration evidence in labeled-row time — an unlabeled batch
    must not erode the (much sparser) calibration window, and a feedback
    replay must not erode the drift window."""
    fc = feature_histogram(x.astype(jnp.float32), feature_edges, weights=valid)
    sc = score_histogram(scores, score_edges, weights=valid)
    lw = label_valid
    # calibration bins via the same dense one-hot reduction (no scatter)
    n_calib = calib_edges.shape[0] + 1
    cidx = jnp.sum(scores[:, None] >= calib_edges[None, :], axis=-1)
    onehot = (cidx[:, None] == jnp.arange(n_calib)[None, :]).astype(jnp.float32)
    cc = lw @ onehot
    cs = (lw * scores) @ onehot
    cl = (lw * labels) @ onehot
    return DriftWindow(
        feature_counts=window.feature_counts * decay + fc,
        score_counts=window.score_counts * decay + sc,
        calib_count=window.calib_count * calib_decay + cc,
        calib_conf=window.calib_conf * calib_decay + cs,
        calib_label=window.calib_label * calib_decay + cl,
        n_rows=window.n_rows * decay + jnp.sum(valid),
    )


def _smoothed_mass(counts: jax.Array) -> jax.Array:
    """Additively-smoothed bin masses along the last axis — keeps PSI finite
    on empty bins without visibly biasing populated ones."""
    n_bins = counts.shape[-1]
    total = jnp.sum(counts, axis=-1, keepdims=True)
    return (counts + PSI_EPS) / (total + PSI_EPS * n_bins)


def psi_from_counts(p_counts: jax.Array, q_counts: jax.Array) -> jax.Array:
    """Population stability index along the last axis (traceable)."""
    p = _smoothed_mass(p_counts)
    q = _smoothed_mass(q_counts)
    return jnp.sum((p - q) * jnp.log(p / q), axis=-1)


def ks_from_counts(p_counts: jax.Array, q_counts: jax.Array) -> jax.Array:
    """Two-sample KS statistic from histograms along the last axis."""
    p = p_counts / jnp.maximum(jnp.sum(p_counts, axis=-1, keepdims=True), 1.0)
    q = q_counts / jnp.maximum(jnp.sum(q_counts, axis=-1, keepdims=True), 1.0)
    return jnp.max(
        jnp.abs(jnp.cumsum(p, axis=-1) - jnp.cumsum(q, axis=-1)), axis=-1
    )


@jax.jit
def _drift_stats(
    window: DriftWindow,
    base_feature_counts: jax.Array,
    base_score_counts: jax.Array,
) -> DriftStats:
    n_labeled = jnp.sum(window.calib_count)
    cnt = jnp.maximum(window.calib_count, 1e-9)
    conf = window.calib_conf / cnt
    acc = window.calib_label / cnt
    w = window.calib_count / jnp.maximum(n_labeled, 1e-9)
    return DriftStats(
        feature_psi=psi_from_counts(window.feature_counts, base_feature_counts),
        feature_ks=ks_from_counts(window.feature_counts, base_feature_counts),
        score_psi=psi_from_counts(window.score_counts, base_score_counts),
        score_ks=ks_from_counts(window.score_counts, base_score_counts),
        ece=jnp.sum(w * jnp.abs(conf - acc)),
        n_labeled=n_labeled,
    )


def psi_np(p_counts: np.ndarray, q_counts: np.ndarray) -> float:
    """Numpy PSI with identical smoothing — for host-side consumers (the
    shadow scorer's challenger histogram) so thresholds mean the same thing
    on both paths."""
    p_counts = np.asarray(p_counts, np.float64)
    q_counts = np.asarray(q_counts, np.float64)
    n_bins = p_counts.shape[-1]
    p = (p_counts + PSI_EPS) / (p_counts.sum() + PSI_EPS * n_bins)
    q = (q_counts + PSI_EPS) / (q_counts.sum() + PSI_EPS * n_bins)
    return float(np.sum((p - q) * np.log(p / q)))


class DriftMonitor:
    """Host wrapper: owns the device-resident window, pads batches onto the
    scorer's power-of-two bucket ladder (so the update program compiles once
    per bucket, not per batch size), and surfaces stats as floats."""

    def __init__(
        self,
        profile: BaselineProfile,
        halflife_rows: float | None = None,
        min_bucket: int = 8,
    ):
        self.profile = profile
        self.halflife_rows = float(
            halflife_rows
            if halflife_rows is not None
            else config.watchtower_halflife_rows()
        )
        self.min_bucket = min_bucket
        self._feature_edges = jnp.asarray(profile.feature_edges, jnp.float32)
        self._score_edges = jnp.asarray(profile.score_edges, jnp.float32)
        self._calib_edges = jnp.asarray(
            np.linspace(0.0, 1.0, N_CALIB_BINS + 1)[1:-1], jnp.float32
        )
        self._base_fc = jnp.asarray(profile.feature_counts, jnp.float32)
        self._base_sc = jnp.asarray(profile.score_counts, jnp.float32)
        self.window = init_window(
            profile.n_features,
            profile.feature_counts.shape[1],
            profile.score_counts.shape[0],
        )
        self.rows_seen = 0  # monotonic (not decayed), host-side
        # ledger: the per-entity velocity table (ledger/), bound when the
        # served model is widened — donated through the same fused dispatch
        # as the window, under the same lock
        self.ledger = None
        self.ledger_spec = None
        self._ledger_null = None
        self._ledger_halflife = None
        # decay is a function of the true row count; caching the device
        # scalar saves one host→device put per update on the ingest path
        self._decay_cache: dict[int, jax.Array] = {}
        # update() donates the window buffers — a stats() reader (scrape /
        # /monitor/status thread) racing the ingest thread would hand
        # just-invalidated arrays to _drift_stats and crash the scrape.
        # Both paths are cheap (one dispatch / a small host sync), so one
        # lock serializes them.
        self._lock = lockdep.lock("drift.window")

    def _decay_for(self, n: int) -> jax.Array:
        decay = self._decay_cache.get(n)
        if decay is None:
            if len(self._decay_cache) >= 256:
                # /monitor/feedback batch sizes are client-controlled —
                # without a bound the cache holds one device scalar per
                # distinct size for the life of the process
                self._decay_cache.clear()
            decay = jnp.float32(0.5 ** (n / self.halflife_rows))
            self._decay_cache[n] = decay
        return decay

    # -- ledger: the per-entity velocity table -----------------------------
    def bind_ledger(self, spec, state=None) -> None:
        """Attach (or rebind, on hot swap) the ledger table: the serving
        flushes thereafter run the widened ``_fused_flush_ledger`` program.
        ``state`` is a host snapshot (ledger_state.npz) or None for a
        fresh table."""
        from fraud_detection_tpu.ledger.state import device_state

        with self._lock:
            self.ledger_spec = spec
            self.ledger = device_state(state, spec.slots)
            self._ledger_null = jnp.asarray(spec.null_features)
            self._ledger_halflife = jnp.float32(spec.halflife_s)

    def ledger_snapshot(self):
        """Host copy of the live table (materialized under the lock — the
        next flush donates these buffers). The thing hot-swap stamping and
        the chaos invariants read."""
        from fraud_detection_tpu.ledger.state import LedgerState

        with self._lock:
            if self.ledger is None:
                return None
            return LedgerState(
                *(np.asarray(leaf) for leaf in self._ledger_for_stats())
            )

    def ledger_stats(self) -> dict | None:
        """Scrape-time ledger telemetry (occupancy, collisions, evictions);
        None when no ledger is bound."""
        from fraud_detection_tpu.ledger.features import ledger_stats

        with self._lock:
            if self.ledger is None:
                return None
            return ledger_stats(
                self._ledger_for_stats(), self.ledger_spec.halflife_s
            )

    def _ledger_for_stats(self):
        """The table ``ledger_stats``/snapshot reads — the mesh subclass
        merges its per-shard sub-tables here. Called under the lock."""
        return self.ledger

    def fused_flush(
        self,
        x: jax.Array,
        valid: jax.Array,
        n_live: int,
        score_args,
        score_fn,
        dequant_scale=None,
        score_codes: bool = True,
        out_dtype=jnp.float32,
        explain_args=None,
        explain_k: int = 0,
        ledger_rows=None,
        wide_args=None,
        wide_rows=None,
    ):
        """Score one staged batch AND fold it into the drift window in ONE
        device dispatch (the fastlane hot path — ``_fused_flush``; the
        quickwire ``_fused_flush_quant`` when ``dequant_scale`` rides along
        for a quantized wire; the lantern ``_fused_flush_explain`` /
        ``_fused_flush_quant_explain`` when ``explain_k > 0`` adds the
        top-k reason-code leg; the ledger ``_fused_flush_ledger`` when a
        ledger is bound and ``ledger_rows`` — the ``(slot_idx, fp, ts,
        has_entity)`` device quadruple — rides along, widening the feature
        block with the per-entity velocity aggregates; the broadside
        ``_fused_flush_wide`` when ``wide_args`` — the scorer's
        ``(CrossSpec, wide_table)`` — and ``wide_rows`` — the
        ``(fingerprint, has_entity)`` device pair — ride along, widening
        with hashed-cross contributions). ``x`` and ``valid`` are already
        device-resident and bucket-padded; returns the device score vector
        (padded, in the ``out_dtype`` return wire; caller slices to the
        live rows and decodes) — or, with the explain leg, the ``(scores,
        reason_idx, reason_val)`` device triple.

        The lock covers only {read window → dispatch → store new window}:
        dispatch is asynchronous, so the critical section is microseconds
        and a concurrent ``stats()`` reader still can't see donated buffers.
        With pipelined flushes the device executes the chained updates in
        dispatch order — each flush's input window is the previous flush's
        output future."""
        # graftcheck: hot-path
        decay = self._decay_for(n_live)
        if wide_args is not None and wide_rows is not None:
            return self._wide_flush(
                x, valid, decay, n_live, score_args, dequant_scale,
                out_dtype, explain_args, explain_k, wide_args, wide_rows,
            )
        if ledger_rows is not None and self.ledger is not None:
            return self._ledger_flush(
                x, valid, decay, n_live, score_args, score_fn,
                dequant_scale, out_dtype, explain_args, explain_k,
                ledger_rows,
            )
        explain_k = min(int(explain_k), int(x.shape[1]))  # k ≥ d clamps to d
        with self._lock:
            if explain_k > 0 and explain_args is not None:
                if dequant_scale is None:
                    scores, eidx, eval_, self.window = _fused_flush_explain(
                        self.window,
                        x,
                        valid,
                        decay,
                        self._feature_edges,
                        self._score_edges,
                        score_args,
                        explain_args,
                        score_fn=score_fn,
                        explain_k=explain_k,
                        out_dtype=out_dtype,
                    )
                else:
                    scores, eidx, eval_, self.window = (
                        _fused_flush_quant_explain(
                            self.window,
                            x,
                            valid,
                            decay,
                            self._feature_edges,
                            self._score_edges,
                            score_args,
                            dequant_scale,
                            explain_args,
                            score_fn=score_fn,
                            score_codes=score_codes,
                            explain_k=explain_k,
                            out_dtype=out_dtype,
                        )
                    )
                self.rows_seen += n_live
                return scores, eidx, eval_
            if dequant_scale is None:
                scores, self.window = _fused_flush(
                    self.window,
                    x,
                    valid,
                    decay,
                    self._feature_edges,
                    self._score_edges,
                    score_args,
                    score_fn=score_fn,
                    out_dtype=out_dtype,
                )
            else:
                scores, self.window = _fused_flush_quant(
                    self.window,
                    x,
                    valid,
                    decay,
                    self._feature_edges,
                    self._score_edges,
                    score_args,
                    dequant_scale,
                    score_fn=score_fn,
                    score_codes=score_codes,
                    out_dtype=out_dtype,
                )
            self.rows_seen += n_live
        return scores

    def _ledger_flush(
        self, x, valid, decay, n_live, score_args, score_fn,
        dequant_scale, out_dtype, explain_args, explain_k, ledger_rows,
    ):
        """Dispatch the widened stateful flush — window AND ledger donated
        through one program (``_fused_flush_ledger``). Same critical-
        section discipline as the stateless path."""
        # graftcheck: hot-path
        slot_idx, fp, ts, has_entity = ledger_rows
        spec = self.ledger_spec
        # k clamps against the WIDENED width the explain leg attributes
        explain_k = min(int(explain_k), int(x.shape[1]) + len(spec.null_features))
        with self._lock:
            out = _fused_flush_ledger(
                self.window,
                self.ledger,
                x,
                valid,
                decay,
                self._feature_edges,
                self._score_edges,
                score_args,
                slot_idx,
                fp,
                ts,
                has_entity,
                self._ledger_null,
                self._ledger_halflife,
                dequant_scale,
                explain_args if explain_k > 0 else None,
                score_fn=score_fn,
                explain_k=explain_k if explain_args is not None else 0,
                amount_col=spec.amount_col,
                out_dtype=out_dtype,
            )
            if explain_k > 0 and explain_args is not None:
                scores, eidx, eval_, self.window, self.ledger = out
                self.rows_seen += n_live
                return scores, eidx, eval_
            scores, self.window, self.ledger = out
            self.rows_seen += n_live
        return scores

    def _wide_flush(
        self, x, valid, decay, n_live, score_args, dequant_scale,
        out_dtype, explain_args, explain_k, wide_args, wide_rows,
    ):
        """Dispatch the broadside widened flush (``_fused_flush_wide``) —
        window donated through, the cross-weight table read-only. Same
        critical-section discipline as the stateless path."""
        # graftcheck: hot-path
        cross_spec, wide_table = wide_args
        fp, has_entity = wide_rows
        # k clamps against the WIDENED width the explain leg attributes
        explain_k = min(int(explain_k), int(x.shape[1]) + cross_spec.n_cross)
        explain_k = explain_k if explain_args is not None else 0
        with self._lock:
            out = _fused_flush_wide(
                self.window,
                x,
                valid,
                decay,
                self._feature_edges,
                self._score_edges,
                score_args,
                wide_table,
                fp,
                has_entity,
                dequant_scale,
                explain_args if explain_k > 0 else None,
                cross_spec=cross_spec,
                explain_k=explain_k,
                out_dtype=out_dtype,
            )
            if explain_k > 0:
                scores, eidx, eval_, self.window = out
                self.rows_seen += n_live
                return scores, eidx, eval_
            scores, self.window = out
            self.rows_seen += n_live
        return scores

    def warm_fused(
        self, scorer, bucket: int, out_dtype=jnp.float32, explain_k: int = 0
    ) -> None:
        """Pre-compile the fused flush executable for one bucket without
        touching the window: an all-padding batch (valid = 0) with decay 1.0
        (``n_live = 0``) folds exact zeros into every histogram, so the
        window state is bitwise unchanged while XLA compiles and caches the
        executable. Stages through the scorer's real staging/encode path
        and the scorer's fused spec (wire dtype, dequant scale, return
        wire, explain leg when ``explain_k > 0``), so the warmed executable
        is exactly the one serving flushes dispatch. Run under the compile
        sentinel's expected-compiles mark by the micro-batcher's startup
        warmup."""
        spec = scorer.fused_spec()
        slot = scorer.staging.acquire(bucket)
        try:
            slot.f32[:] = 0.0
            hx = scorer._encode_slot(slot)
            slot.valid[:] = 0.0
            ledger_rows = None
            wide_rows = None
            if getattr(spec, "wide", None) is not None:
                # the wide program warms through the same all-padding
                # discipline: fingerprint 0 everywhere zeroes the entire
                # cross block (every template crosses the entity) and
                # valid = 0 folds exact zeros, so the window is bitwise
                # unchanged while the executable compiles
                slot.ensure_ledger()
                slot.lf[:] = 0
                slot.lh[:] = 0.0
                wide_rows = (jnp.asarray(slot.lf), jnp.asarray(slot.lh))
            if self.ledger is not None and getattr(spec, "ledger", None):
                # the ledger program warms through the same all-padding
                # discipline: has_entity = 0 everywhere scatter-adds exact
                # zeros and scatter-maxes a 0 anchor, so the entity table
                # is bitwise unchanged while the executable compiles
                slot.ensure_ledger()
                slot.ls[:] = 0
                slot.lf[:] = 0
                slot.lt[:] = 0.0
                slot.lh[:] = 0.0
                ledger_rows = (
                    jnp.asarray(slot.ls), jnp.asarray(slot.lf),
                    jnp.asarray(slot.lt), jnp.asarray(slot.lh),
                )
            out = self.fused_flush(
                jnp.asarray(hx), jnp.asarray(slot.valid), 0,
                spec.score_args, spec.score_fn,
                dequant_scale=spec.dequant_scale,
                score_codes=spec.score_codes,
                out_dtype=out_dtype,
                explain_args=spec.explain_args if explain_k else None,
                explain_k=explain_k,
                ledger_rows=ledger_rows,
                wide_args=getattr(spec, "wide", None),
                wide_rows=wide_rows,
            )
            jax.block_until_ready(out)
        finally:
            scorer.staging.release(slot)

    def update(self, x, scores, labels=None, calibration_only=False) -> None:
        """Fold one scored batch in — one fused device call.

        ``calibration_only=True`` is the delayed-feedback path: the rows
        were already observed live when scored, so they must update ONLY
        the calibration state — not the drift histograms or row counts."""
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None, :]
        if (
            self.ledger_spec is not None
            and x.shape[1] == self.ledger_spec.n_base
        ):
            # base-width rows into a WIDENED window (feedback replays, the
            # split path): pad with the stamped null-profile features so
            # the histogram shapes line up — for calibration_only batches
            # the feature weights are zero anyway, and for live batches
            # this is exactly the null-slot semantics serving applies
            x = np.concatenate(
                [
                    x,
                    np.broadcast_to(
                        self.ledger_spec.null_features,
                        (
                            x.shape[0],
                            self.ledger_spec.null_features.shape[0],
                        ),
                    ),
                ],
                axis=1,
            ).astype(np.float32)
        scores = np.asarray(scores, np.float32).reshape(-1)
        n = x.shape[0]
        b = _bucket(n, self.min_bucket)
        if b != n:
            x = np.concatenate([x, np.zeros((b - n, x.shape[1]), np.float32)])
            scores = np.concatenate([scores, np.zeros(b - n, np.float32)])
        real = np.zeros(b, np.float32)
        real[:n] = 1.0
        valid = np.zeros(b, np.float32) if calibration_only else real
        if labels is None:
            lab = np.zeros(b, np.float32)
            lab_valid = np.zeros(b, np.float32)
        else:
            lab = np.zeros(b, np.float32)
            lab[:n] = np.asarray(labels, np.float32).reshape(-1)
            lab_valid = real
        n_live = 0 if calibration_only else n
        n_labeled = n if labels is not None else 0
        with self._lock:
            self.window = _window_update(
                self.window,
                jnp.asarray(x),
                jnp.asarray(scores),
                jnp.asarray(lab),
                jnp.asarray(lab_valid),
                jnp.asarray(valid),
                self._decay_for(n_live),
                self._decay_for(n_labeled),
                self._feature_edges,
                self._score_edges,
                self._calib_edges,
            )
            if not calibration_only:
                self.rows_seen += n

    def _window_for_stats(self) -> DriftWindow:
        """The window ``stats()`` derives from — the mesh subclass returns
        the per-shard windows merged with the host-side window here (the
        scrape-time reduce), the base class its one live window. Called
        under the lock."""
        return self.window

    # -- lifeboat: durable window snapshot/restore -------------------------
    def window_snapshot(self) -> DriftWindow:
        """Host copy of the live window, materialized under the lock (the
        next flush donates these buffers) — the lifeboat snapshot input."""
        with self._lock:
            return DriftWindow(*(np.asarray(leaf) for leaf in self.window))

    def shard_window_snapshot(self) -> DriftWindow | None:
        """Per-shard windows (leading shard axis) — None off the mesh; the
        mesh subclass overrides."""
        return None

    def restore_window(
        self, window: DriftWindow, shard_window=None, rows_seen=None
    ) -> bool:
        """Rebind a snapshotted window into the live pytree (warm restart).
        Shapes/dtypes must match the live window exactly — the restored
        buffers feed the SAME warmed fused executables, so a matching
        restore costs zero recompiles; a mismatched one (different
        profile geometry) is skipped loudly rather than crashing the next
        flush."""
        with self._lock:
            ok = self._restore_windows_locked(window, shard_window)
            if ok and rows_seen is not None:
                self.rows_seen = int(rows_seen)
        return ok

    def _restore_windows_locked(self, window, shard_window) -> bool:
        cur = self.window
        shapes = tuple(np.shape(np.asarray(leaf)) for leaf in window)
        want = tuple(tuple(leaf.shape) for leaf in cur)
        if shapes != want:
            import logging

            logging.getLogger("fraud_detection_tpu.lifeboat").warning(
                "drift window restore skipped: snapshot shapes %s != live "
                "%s (profile geometry changed since the snapshot)",
                shapes, want,
            )
            return False
        self.window = DriftWindow(
            *(jnp.asarray(np.asarray(leaf, np.float32)) for leaf in window)
        )
        return True

    def stats(self) -> dict:
        """Host-synced snapshot (small arrays; called at status/scrape time,
        never on the per-batch path)."""
        with self._lock:
            window = self._window_for_stats()
            s = _drift_stats(window, self._base_fc, self._base_sc)
            # materialize inside the lock: once released, the next update
            # donates the window buffers these device values derive from
            feature_psi = np.asarray(s.feature_psi, np.float64)
            feature_ks = np.asarray(s.feature_ks, np.float64)
            score_psi = float(s.score_psi)
            score_ks = float(s.score_ks)
            ece = float(s.ece)
            n_labeled = float(s.n_labeled)
            window_rows = float(window.n_rows)
            rows_seen = self.rows_seen
        order = np.argsort(feature_psi)[::-1][:5]
        top = [
            {
                "feature": self.profile.feature_names[i],
                "psi": round(float(feature_psi[i]), 5),
                "ks": round(float(feature_ks[i]), 5),
            }
            for i in order
        ]
        return {
            "window_rows": window_rows,
            "rows_seen": rows_seen,
            "feature_psi_max": float(feature_psi.max(initial=0.0)),
            "feature_ks_max": float(feature_ks.max(initial=0.0)),
            "score_psi": score_psi,
            "score_ks": score_ks,
            "ece": ece,
            "n_labeled": n_labeled,
            "top_features": top,
        }
