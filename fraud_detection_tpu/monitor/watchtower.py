"""The watchtower coordinator: drift + shadow + thresholds + actions.

One instance per serving process. The micro-batcher hands every scored
batch to :meth:`Watchtower.observe`, which is non-blocking: batches are
queued to a single ingest thread (bounded backlog, drop-and-count under
pressure), so monitoring can never stall the request path — the drift
update is one fused device call and the shadow challenger runs on the same
thread behind it.

``status()`` evaluates the configured thresholds and produces:

- a status: ``warming`` (window below ``WATCHTOWER_MIN_ROWS``), ``ok``, or
  ``drift``;
- a recommendation:
  - ``retrain`` — drift detected and no healthier challenger is standing by
    (optionally fires the ``watchtower.trigger_retrain`` taskq task, once
    per drift episode);
  - ``promote_challenger`` — the champion's score distribution drifted but
    the shadow challenger's still matches the baseline;
  - ``rollback_challenger`` — champion healthy but the challenger disagrees
    with it beyond the disagreement threshold (do not promote; unregister
    the shadow alias);
  - ``none`` otherwise;
- the Prometheus gauges the ``monitoring/`` alert rules and Grafana panels
  read.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass

from fraud_detection_tpu import config
from fraud_detection_tpu.monitor.baseline import BaselineProfile, load_profile
from fraud_detection_tpu.monitor.drift import DriftMonitor
from fraud_detection_tpu.monitor.shadow import ShadowScorer
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.watchtower")

RETRAIN_TASK = "watchtower.trigger_retrain"

RECOMMENDATIONS = (
    "none", "retrain", "promote_challenger", "rollback_challenger"
)


def _challenger_explainer(challenger):
    """A family-agnostic attribution callable ``phi(rows) -> (n, d)`` for
    the shadow reason-code comparison, built on the challenger's own
    ``explain_batch`` — the SAME full-vector path its worker backfill
    runs. This covers every served family: the linear/wide families'
    vectorized raw-space linear SHAP, a LEDGER-widened challenger's
    null-slot explanation of base-width rows, and the GBT forest's exact
    TreeSHAP (``ops/tree_shap`` — a device call, which is fine here: the
    comparison runs on the watchtower ingest thread behind the sampled
    challenger re-score, never the request path; previously this returned
    the linear coef pair only, so a GBT challenger shadowed with NO
    Jaccard signal). Returns None for objects without ``explain_batch``
    (the divergence gauge then just stays unset)."""
    import numpy as np

    if not hasattr(challenger, "explain_batch"):
        log.debug(
            "challenger has no explain_batch — shadow reason divergence "
            "disabled"
        )
        return None

    def phi(rows):
        return np.asarray(
            challenger.explain_batch(np.asarray(rows, np.float32))[0],
            np.float64,
        )

    return phi


@dataclass(frozen=True)
class Thresholds:
    psi: float
    ks: float
    ece: float
    disagree: float
    min_rows: int

    @classmethod
    def from_config(cls) -> "Thresholds":
        return cls(
            psi=config.watchtower_psi_threshold(),
            ks=config.watchtower_ks_threshold(),
            ece=config.watchtower_ece_threshold(),
            disagree=config.watchtower_disagree_threshold(),
            min_rows=config.watchtower_min_rows(),
        )


def _recommend(
    warming: bool, flags: dict, shadow: dict | None, thr: Thresholds
) -> str:
    """Pure recommendation logic (unit-tested directly)."""
    if warming:
        return "none"
    drifting = any(flags.values())
    shadow_ready = (
        shadow is not None and shadow["window_rows"] >= thr.min_rows
    )
    if drifting:
        if (
            shadow_ready
            and flags.get("score_psi")
            and shadow["score_psi"] <= thr.psi
        ):
            return "promote_challenger"
        return "retrain"
    if shadow_ready and shadow["disagreement"] > thr.disagree:
        return "rollback_challenger"
    return "none"


class Watchtower:
    def __init__(
        self,
        profile: BaselineProfile,
        challenger=None,
        challenger_source: str | None = None,
        thresholds: Thresholds | None = None,
        sample_rate: float | None = None,
        halflife_rows: float | None = None,
        retrain_sender=None,
        action_sender=None,
        max_backlog: int = 32,
        mesh=None,
    ):
        self.thresholds = thresholds or Thresholds.from_config()
        self._sample_rate = sample_rate
        self._halflife_rows = halflife_rows
        # Switchyard: with a serving mesh, the drift window shards over the
        # data axis (per-shard windows donated through the SPMD fused
        # flush, merged at scrape time) — the micro-batcher's fused target
        # resolves the same fused_flush surface either way.
        self._mesh = mesh
        self.drift = self._make_drift(profile)
        self.shadow = (
            ShadowScorer(
                challenger.scorer,
                profile,
                sample_rate=sample_rate,
                halflife_rows=halflife_rows,
                explainer=_challenger_explainer(challenger),
            )
            if challenger is not None
            else None
        )
        self.challenger_source = challenger_source
        self.max_backlog = max_backlog
        self._retrain_sender = retrain_sender
        # action_sender(task_name, reason): enqueues the conductor's
        # promote/rollback tasks when CONDUCTOR_AUTO_PROMOTE=1 — same
        # one-per-episode latch discipline as the retrain trigger
        self._action_sender = action_sender
        self._retrain_latched = False
        self._action_latched: str | None = None
        # ledger counter deltas: the device accumulates cumulative totals;
        # scrape time increments the prometheus Counters by the delta
        self._ledger_counts = {"hash_collisions": 0.0, "evictions": 0.0}
        # a /metrics scrape and a /monitor/status call can evaluate status()
        # concurrently (separate to_thread workers) — the latch check/set
        # must be atomic or one episode enqueues duplicate retrain tasks
        self._retrain_lock = lockdep.lock("watchtower.retrain")
        # Bounded handoff queue + ONE daemon ingest thread, not a thread
        # pool: put_nowait is ~2µs with no per-call Future allocation — the
        # observe() hook is the only monitoring cost the request path ever
        # pays, so it is priced in microseconds (bench: monitored_scoring).
        self._queue: queue.Queue = queue.Queue(maxsize=max_backlog)
        self._stop = False
        self._thread = threading.Thread(
            target=self._ingest_loop, name="watchtower-ingest", daemon=True
        )
        self._thread.start()

    def _make_drift(self, profile) -> DriftMonitor:
        if self._mesh is not None:
            from fraud_detection_tpu.mesh.shardflush import MeshDriftMonitor

            return MeshDriftMonitor(
                profile, self._mesh, halflife_rows=self._halflife_rows
            )
        return DriftMonitor(profile, halflife_rows=self._halflife_rows)

    # -- ingest (request path adjacent; must never block) -------------------
    def wants_rows(self) -> bool:
        """True when a fastlane flush (drift already folded on-device) still
        needs the raw rows queued — i.e. a shadow challenger is bound. When
        False, the flush can skip the per-batch row copy entirely."""
        return self.shadow is not None

    def observe(
        self, rows, scores, labels=None, calibration_only=False,
        drift_done=False, reasons=None,
    ) -> bool:
        """Queue one scored batch for monitoring. Non-blocking; returns
        False when the backlog bound forced a drop (counted).

        ``calibration_only=True`` marks a delayed-feedback replay
        (/monitor/feedback): the rows were already observed live, so they
        update only calibration state and skip the shadow comparison (the
        recorded champion scores may predate the current champion).

        ``drift_done=True`` is the fastlane flush path: the drift window
        was already folded inside the scoring dispatch itself
        (drift.fused_flush), so the ingest thread only runs the sampled
        shadow comparison — ``rows`` may be None when no challenger is
        bound (see :meth:`wants_rows`).

        ``reasons`` (lantern × shadow): the champion's serve-time top-k
        reason-code INDICES for this batch, when the fused explain leg
        produced them — the shadow scorer compares them against the
        challenger's top-k (Jaccard) into
        ``watchtower_shadow_reason_divergence``."""
        try:
            self._queue.put_nowait(
                (rows, scores, labels, calibration_only, drift_done, reasons)
            )
        except queue.Full:
            metrics.watchtower_batches_dropped.inc()
            return False
        return True

    def _ingest_loop(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is None or self._stop:
                    return
                (rows, scores, labels, calibration_only, drift_done,
                 reasons) = item
                if not drift_done:
                    self.drift.update(
                        rows, scores, labels, calibration_only=calibration_only
                    )
                metrics.watchtower_batches_observed.inc()
                if (
                    self.shadow is not None
                    and rows is not None
                    and not calibration_only
                    and self.shadow.maybe_observe(rows, scores, reasons)
                ):
                    metrics.watchtower_shadow_batches.inc()
            except Exception:
                log.warning("watchtower ingest failed", exc_info=True)
            finally:
                self._queue.task_done()

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait for queued batches to finish ingesting (tests/shutdown)."""
        deadline = time.monotonic() + timeout
        while self._queue.unfinished_tasks:  # Queue.join() has no timeout
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    # -- evaluation ---------------------------------------------------------
    def status(self) -> dict:
        """Threshold evaluation + gauge refresh + recommendation. Runs a
        small host sync; called from /monitor/status and metric scrapes,
        never per batch."""
        thr = self.thresholds
        d = self.drift.stats()
        sh = self.shadow.stats() if self.shadow is not None else None
        warming = d["window_rows"] < thr.min_rows
        flags = {
            "feature_psi": d["feature_psi_max"] > thr.psi,
            "feature_ks": d["feature_ks_max"] > thr.ks,
            "score_psi": d["score_psi"] > thr.psi,
            "score_ks": d["score_ks"] > thr.ks,
            "calibration": d["n_labeled"] >= thr.min_rows
            and d["ece"] > thr.ece,
        }
        if warming:
            flags = {k: False for k in flags}
        drifting = any(flags.values())
        recommendation = _recommend(warming, flags, sh, thr)
        self._maybe_trigger_retrain(recommendation, d)
        self._maybe_send_action(recommendation, d, sh)

        # A warming window's raw stats are empty-histogram smoothing noise
        # (score PSI against an empty window is ~5): exporting them would
        # trip the `> 0.2 for 15m` alert rules on every fresh deploy, so
        # the stat gauges read 0 until min_rows. window_rows still exports
        # so operators can watch the warm-up itself.
        g = dict.fromkeys(
            ("feature_psi_max", "feature_ks_max", "score_psi", "score_ks",
             "ece"),
            0.0,
        ) if warming else d
        metrics.watchtower_feature_psi_max.set(g["feature_psi_max"])
        metrics.watchtower_feature_ks_max.set(g["feature_ks_max"])
        metrics.watchtower_score_psi.set(g["score_psi"])
        metrics.watchtower_score_ks.set(g["score_ks"])
        # ECE gets the same floor as the calibration flag: a handful of
        # labeled rows yields ECE near 1, and the calibration window fades
        # only in labeled-row time, so the noise would outlast the alert's
        # `for:` window
        metrics.watchtower_ece.set(
            g["ece"] if d["n_labeled"] >= thr.min_rows else 0.0
        )
        metrics.watchtower_window_rows.set(d["window_rows"])
        metrics.watchtower_drift_detected.set(1 if drifting else 0)
        for action in RECOMMENDATIONS:
            metrics.watchtower_recommendation.labels(action).set(
                1 if action == recommendation else 0
            )
        if sh is not None:
            # same warm-up suppression as the drift gauges: an empty shadow
            # window's smoothed PSI is ~3 until sampling fills it
            shadow_warm = sh["window_rows"] >= thr.min_rows
            metrics.watchtower_shadow_disagreement.set(
                sh["disagreement"] if shadow_warm else 0.0
            )
            metrics.watchtower_shadow_score_psi.set(
                sh["score_psi"] if shadow_warm else 0.0
            )
            rd = sh.get("reason_divergence")
            metrics.watchtower_shadow_reason_divergence.set(
                rd if (rd is not None and shadow_warm) else 0.0
            )

        ledger = self._refresh_ledger_metrics()

        return {
            "enabled": True,
            "status": "warming" if warming else ("drift" if drifting else "ok"),
            "recommendation": recommendation,
            "flags": flags,
            "drift": d,
            "shadow": sh,
            "ledger": ledger,
            "challenger_source": self.challenger_source,
            "thresholds": {
                "psi": thr.psi,
                "ks": thr.ks,
                "ece": thr.ece,
                "disagree": thr.disagree,
                "min_rows": thr.min_rows,
            },
        }

    def _refresh_ledger_metrics(self) -> dict | None:
        """Export the entity-table telemetry (ledger/): the occupancy gauge
        plus collision/eviction Counters advanced by the device totals'
        delta since the last scrape. None when no ledger is bound."""
        stats = getattr(self.drift, "ledger_stats", lambda: None)()
        if stats is None:
            metrics.ledger_active.set(0)
            return None
        metrics.ledger_active.set(1)
        metrics.ledger_slot_occupancy.set(stats["slot_occupancy"])
        for key, counter in (
            ("hash_collisions", metrics.ledger_hash_collisions),
            ("evictions", metrics.ledger_evictions),
        ):
            delta = stats[key] - self._ledger_counts[key]
            if delta > 0:
                counter.inc(delta)
                self._ledger_counts[key] = stats[key]
            elif delta < 0:  # table rebind/reset — restart the baseline
                self._ledger_counts[key] = stats[key]
        return stats

    def _maybe_trigger_retrain(self, recommendation: str, d: dict) -> None:
        with self._retrain_lock:
            if recommendation != "retrain":
                self._retrain_latched = False  # episode over; re-arm
                return
            if self._retrain_latched or self._retrain_sender is None:
                return
            if not config.watchtower_retrain_trigger():
                return
            self._retrain_latched = True  # latch before the send: a racing
            # status() must not double-enqueue while the broker call runs
            try:
                self._retrain_sender(
                    f"drift detected: "
                    f"feature_psi_max={d['feature_psi_max']:.4f} "
                    f"score_psi={d['score_psi']:.4f} ece={d['ece']:.4f}"
                )
                metrics.watchtower_retrain_triggers.inc()
                log.warning(
                    "watchtower fired retrain trigger task %s", RETRAIN_TASK
                )
            except Exception as e:
                self._retrain_latched = False  # retry on the next evaluation
                log.error("retrain trigger enqueue failed: %s", e)

    def _maybe_send_action(
        self, recommendation: str, d: dict, sh: dict | None
    ) -> None:
        """Enqueue the conductor's promote/rollback task for this episode
        (CONDUCTOR_AUTO_PROMOTE opt-in). Latched per recommendation value:
        one task per episode, re-armed when the recommendation changes."""
        if recommendation not in ("promote_challenger", "rollback_challenger"):
            with self._retrain_lock:
                self._action_latched = None  # episode over; re-arm
            return
        if self._action_sender is None or not config.conductor_auto_promote():
            return
        with self._retrain_lock:
            if self._action_latched == recommendation:
                return
            self._action_latched = recommendation
        from fraud_detection_tpu.lifecycle.conductor import (
            PROMOTE_TASK,
            ROLLBACK_TASK,
        )

        task = (
            PROMOTE_TASK
            if recommendation == "promote_challenger"
            else ROLLBACK_TASK
        )
        reason = (
            f"watchtower {recommendation}: score_psi={d['score_psi']:.4f} "
            f"shadow_psi={(sh or {}).get('score_psi', float('nan')):.4f} "
            f"disagreement={(sh or {}).get('disagreement', float('nan')):.4f}"
        )
        try:
            self._action_sender(task, reason)
            log.warning("watchtower enqueued conductor task %s", task)
        except Exception as e:
            with self._retrain_lock:
                self._action_latched = None  # retry next evaluation
            log.error("conductor action enqueue failed: %s", e)

    # -- hot swap (driven by lifecycle.ModelReloader) -----------------------
    def rebind_champion(self, profile, ledger=None) -> None:
        """A promotion went live: point drift monitoring at the NEW
        champion's baseline profile with a fresh window (the old window's
        evidence was accumulated against the old baseline). When the new
        artifacts carry no profile the old baseline keeps serving — stale
        monitoring beats none.

        ``ledger`` is the promoted artifact's ``(LedgerSpec, state)`` pair
        when the new champion is widened: the entity table rebinds WITH the
        model (the widened weights were trained against the replayed
        history that snapshot ends on), resetting the collision/eviction
        counter baselines."""
        if profile is None:
            log.warning(
                "promoted model has no baseline profile — drift window "
                "keeps the previous baseline"
            )
            if ledger is not None:
                # the TABLE must still follow the model: serving the new
                # widened weights against the old table/spec would mismatch
                # the history the challenger was replayed on (the reloader
                # already refuses cross-width swaps, so the existing
                # window's widened edges still fit)
                self._bind_ledger(*ledger)
            return
        self.drift = self._make_drift(profile)
        self._ledger_counts = {"hash_collisions": 0.0, "evictions": 0.0}
        if ledger is not None:
            self._bind_ledger(*ledger)
        if self.shadow is not None:
            # the old challenger IS usually the new champion — comparing a
            # model to itself reads as perfect agreement and would mask a
            # genuinely-different next challenger; the reloader rebinds or
            # clears it right after via the @shadow alias sweep
            self.shadow = None
            self.challenger_source = None
        log.warning("watchtower rebound to the promoted champion's baseline")

    def _bind_ledger(self, spec, state) -> None:
        self._ledger_counts = {"hash_collisions": 0.0, "evictions": 0.0}
        self.drift.bind_ledger(spec, state)
        log.warning(
            "ledger rebound with the promoted champion "
            "(%d slots, halflife %.0fs)", spec.slots, spec.halflife_s,
        )

    def rebind_challenger(self, challenger, source: str | None) -> None:
        """@shadow alias changed: swap the challenger scorer (fresh shadow
        window) or drop shadow scoring when the alias went away."""
        if challenger is None:
            self.shadow = None
            self.challenger_source = None
            log.info("shadow challenger unbound")
            return
        profile = self.drift.profile
        explainer = _challenger_explainer(challenger)
        if self.shadow is None:
            self.shadow = ShadowScorer(
                challenger.scorer,
                profile,
                sample_rate=self._sample_rate,
                halflife_rows=self._halflife_rows,
                explainer=explainer,
            )
        else:
            self.shadow.swap_scorer(challenger.scorer, explainer=explainer)
        self.challenger_source = source
        log.warning("shadow challenger rebound to %s", source)

    def close(self) -> None:
        """Stop the ingest thread; still-queued batches are discarded (the
        window is advisory state — shutdown must not wait on a challenger)."""
        self._stop = True
        try:
            self._queue.put_nowait(None)  # wake the blocked get()
        except queue.Full:
            pass  # thread sees _stop on the next dequeue
        self._thread.join(timeout=5.0)


def resolve_profile_dir(model_source: str) -> str | None:
    """Map a ``load_production_model`` source description to the artifact
    directory that may hold ``monitor_profile.npz``."""
    kind, _, rest = model_source.partition(":")
    if kind == "registry":
        from fraud_detection_tpu.tracking import TrackingClient

        try:
            return TrackingClient().registry.resolve(rest)
        except (FileNotFoundError, ValueError) as e:
            log.debug("profile dir resolution failed for %s: %s", rest, e)
            return None
    if kind == "native":
        return rest
    if kind == "joblib":
        return os.path.dirname(rest) or "."
    return None


def build_watchtower(
    model, model_source: str, retrain_sender=None, action_sender=None,
    mesh=None,
):
    """Serving-side factory: None when disabled (``WATCHTOWER_ENABLED=0``)
    or when the resolved model artifacts carry no baseline profile (models
    trained before the watchtower existed keep serving, unmonitored).
    ``mesh`` (the switchyard serving mesh) shards the drift window over the
    data axis — see mesh/shardflush."""
    enabled = config.watchtower_enabled()
    if enabled is False:
        return None
    profile_dir = resolve_profile_dir(model_source)
    profile = load_profile(profile_dir) if profile_dir else None
    if profile is None:
        lvl = logging.WARNING if enabled else logging.INFO
        log.log(
            lvl,
            "no %s beside model (%s) — serving unmonitored",
            "monitor_profile.npz",
            model_source,
        )
        return None
    if list(profile.feature_names) != list(model.feature_names):
        log.warning(
            "baseline profile feature names do not match the served model — "
            "serving unmonitored (stale profile beside a newer model?)"
        )
        return None
    challenger = challenger_source = None
    try:
        from fraud_detection_tpu.service.loading import load_shadow_model

        resolved = load_shadow_model()
        if resolved is not None:
            challenger, challenger_source = resolved
            ch_names = getattr(
                challenger, "base_feature_names",
                getattr(challenger, "feature_names", None),
            )
            if ch_names is not None and list(ch_names) != list(
                getattr(model, "base_feature_names", model.feature_names)
            ):
                # Caught here once at startup; inside the ingest loop it
                # would instead fail on every sampled batch while the
                # shadow stats silently never accumulate.
                log.warning(
                    "shadow challenger %s feature schema does not match the "
                    "champion — monitoring without it",
                    challenger_source,
                )
                challenger = challenger_source = None
    except Exception as e:
        log.warning("shadow model load failed (%s); monitoring without one", e)
    wt = Watchtower(
        profile,
        challenger=challenger,
        challenger_source=challenger_source,
        retrain_sender=retrain_sender,
        action_sender=action_sender,
        mesh=mesh,
    )
    if getattr(model, "ledger_spec", None) is not None:
        # a widened family: bind the stamped entity table so the fused
        # flush runs the stateful ledger program from the first batch
        wt.drift.bind_ledger(
            model.ledger_spec, getattr(model, "ledger_state", None)
        )
        metrics.ledger_active.set(1)
        log.info(
            "ledger bound: %d slots, halflife %.0fs, %d base + %d velocity "
            "features",
            model.ledger_spec.slots, model.ledger_spec.halflife_s,
            model.ledger_spec.n_base,
            model.ledger_spec.n_features - model.ledger_spec.n_base,
        )
    log.info(
        "watchtower active: baseline over %d rows, challenger=%s",
        profile.n_rows,
        challenger_source or "none",
    )
    return wt
