"""Train-time baseline profile: the reference distribution drift is judged
against.

Captured once per trained model as jitted reductions (histogramming 200k+
rows is an embarrassingly-parallel MapReduce — the DrJAX-style shape,
PAPERS.md), saved as ``monitor_profile.npz`` beside ``model.npz`` so every
resolution path (registry alias, native dir, promoted artifact copy) carries
its own baseline:

- **per-feature histograms** over equiprobable (training-quantile) bin
  edges — the canonical binning for PSI, so a stable live distribution puts
  ~1/n_bins of its mass in every bin;
- **score histogram** over uniform [0, 1] edges plus tail quantiles of the
  held-out score distribution (the drift reference for the serving scores
  AND for any challenger's scores).

The histogram kernel is shared with the online accumulators in
:mod:`fraud_detection_tpu.monitor.drift` so baseline and window counts can
never disagree on binning.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

PROFILE_FILE = "monitor_profile.npz"

N_FEATURE_BINS = 16
N_SCORE_BINS = 20
SCORE_QUANTILES = (0.5, 0.9, 0.95, 0.99, 0.999)


@dataclass(frozen=True)
class BaselineProfile:
    feature_edges: np.ndarray  # (d, n_bins - 1) interior edges, sorted
    feature_counts: np.ndarray  # (d, n_bins)
    score_edges: np.ndarray  # (s_bins - 1,) interior edges on [0, 1]
    score_counts: np.ndarray  # (s_bins,)
    score_quantiles: np.ndarray  # (len(SCORE_QUANTILES),)
    n_rows: int
    feature_names: tuple[str, ...]

    @property
    def n_features(self) -> int:
        return int(self.feature_edges.shape[0])


def feature_histogram(
    x: jax.Array, edges: jax.Array, weights: jax.Array | None = None
) -> jax.Array:
    """Per-feature weighted histogram: ``x`` (n, d) against ``edges``
    (d, n_edges) → (d, n_edges + 1) counts. Dense one-hot reduction rather
    than a scatter-add: the scatter unit is the TPU's weak spot (and 7×
    slower on CPU at micro-batch shapes — same trick as the GBT one-hot
    histogram contractions). Bin convention: index = #edges ≤ x
    (``searchsorted side='right'``). Traceable; callers bound ``n`` (the
    drift path is bucket-padded, the baseline path chunks), so the
    (n, d, bins) intermediate stays small and fuses."""
    n_edges = edges.shape[1]
    idx = jnp.sum(x[:, :, None] >= edges[None, :, :], axis=-1)  # (n, d)
    onehot = idx[:, :, None] == jnp.arange(n_edges + 1)[None, None, :]
    if weights is None:
        return jnp.sum(onehot, axis=0, dtype=jnp.float32)
    return jnp.sum(
        onehot * weights.astype(jnp.float32)[:, None, None], axis=0
    )


def score_histogram(
    scores: jax.Array, edges: jax.Array, weights: jax.Array | None = None
) -> jax.Array:
    """Weighted histogram of ``scores`` (n,) against interior ``edges``
    (n_edges,) → (n_edges + 1,) counts. Same dense one-hot reduction and
    bin convention as :func:`feature_histogram`. Traceable."""
    idx = jnp.sum(scores[:, None] >= edges[None, :], axis=-1)  # (n,)
    onehot = idx[:, None] == jnp.arange(edges.shape[0] + 1)[None, :]
    if weights is None:
        return jnp.sum(onehot, axis=0, dtype=jnp.float32)
    return jnp.sum(onehot * weights.astype(jnp.float32)[:, None], axis=0)


@jax.jit
def _quantile_edges(x: jax.Array, qs: jax.Array) -> jax.Array:
    """Per-feature quantile bin edges: (n, d) × (n_edges,) → (d, n_edges)."""
    return jnp.quantile(x.astype(jnp.float32), qs, axis=0).T


@jax.jit
def _profile(
    x: jax.Array,
    scores: jax.Array,
    feature_edges: jax.Array,
    score_edges: jax.Array,
    x_weights: jax.Array | None = None,
    score_weights: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """The baseline reduction: feature + score histograms in one fused
    program. ``x`` and ``scores`` may have different row counts (feature
    profile from the train split, score profile from held-out scores).
    Weights carry the chunked caller's padding mask."""
    return (
        feature_histogram(
            x.astype(jnp.float32), feature_edges, weights=x_weights
        ),
        score_histogram(
            scores.astype(jnp.float32), score_edges, weights=score_weights
        ),
    )


@jax.jit
def _score_quantiles(scores: jax.Array, qs: jax.Array) -> jax.Array:
    return jnp.quantile(scores.astype(jnp.float32), qs)


#: rows per chunk of the baseline reduction — bounds the (chunk, d, bins)
#: one-hot intermediate to a few MB while keeping one executable (the tail
#: chunk is zero-weight padded to the same shape).
PROFILE_CHUNK = 1 << 16


def build_baseline_profile(
    x,
    scores,
    feature_names: list[str] | None = None,
    n_bins: int = N_FEATURE_BINS,
    n_score_bins: int = N_SCORE_BINS,
) -> BaselineProfile:
    """Profile training features ``x`` (n, d) + model ``scores`` (m,)."""
    x = np.asarray(x, np.float32)
    scores_np = np.asarray(scores, np.float32).reshape(-1)
    qs = jnp.arange(1, n_bins, dtype=jnp.float32) / n_bins
    feature_edges = _quantile_edges(jnp.asarray(x), qs)
    score_edges = jnp.asarray(
        np.linspace(0.0, 1.0, n_score_bins + 1)[1:-1], jnp.float32
    )

    n, d = x.shape
    m = scores_np.shape[0]
    chunk = min(PROFILE_CHUNK, max(n, m, 1))

    def padded(a: np.ndarray, lo: int) -> tuple[np.ndarray, np.ndarray]:
        sl = a[lo : lo + chunk]
        w = np.zeros((chunk,), np.float32)
        w[: sl.shape[0]] = 1.0
        if sl.shape[0] < chunk:
            sl = np.concatenate(
                [sl, np.zeros((chunk - sl.shape[0],) + a.shape[1:], np.float32)]
            )
        return sl, w

    feature_counts = jnp.zeros((d, n_bins), jnp.float32)
    score_counts = jnp.zeros((n_score_bins,), jnp.float32)
    for lo in range(0, max(n, m), chunk):
        xc, xw = padded(x, lo)
        sc, sw = padded(scores_np, lo)
        fc, scc = _profile(
            jnp.asarray(xc), jnp.asarray(sc), feature_edges, score_edges,
            x_weights=jnp.asarray(xw), score_weights=jnp.asarray(sw),
        )
        feature_counts = feature_counts + fc
        score_counts = score_counts + scc
    quantiles = _score_quantiles(
        jnp.asarray(scores_np), jnp.asarray(SCORE_QUANTILES, jnp.float32)
    )
    names = tuple(feature_names) if feature_names else tuple(
        f"f{i}" for i in range(d)
    )
    return BaselineProfile(
        feature_edges=np.asarray(feature_edges, np.float32),
        feature_counts=np.asarray(feature_counts, np.float32),
        score_edges=np.asarray(score_edges, np.float32),
        score_counts=np.asarray(score_counts, np.float32),
        score_quantiles=np.asarray(quantiles, np.float32),
        n_rows=n,
        feature_names=names,
    )


def save_profile(directory: str, profile: BaselineProfile) -> str:
    """Write ``monitor_profile.npz`` beside the model artifacts."""
    from fraud_detection_tpu.ckpt.atomic import atomic_savez

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, PROFILE_FILE)
    atomic_savez(
        path,
        feature_edges=profile.feature_edges,
        feature_counts=profile.feature_counts,
        score_edges=profile.score_edges,
        score_counts=profile.score_counts,
        score_quantiles=profile.score_quantiles,
        n_rows=np.int64(profile.n_rows),
        feature_names=np.asarray(profile.feature_names),
    )
    return path


def load_profile(directory: str) -> BaselineProfile | None:
    """Load the profile from an artifact directory; None when absent (the
    serving side then runs unmonitored rather than failing the model load)."""
    path = os.path.join(directory, PROFILE_FILE)
    if not os.path.exists(path):
        return None
    with np.load(path, allow_pickle=False) as z:
        return BaselineProfile(
            feature_edges=np.asarray(z["feature_edges"], np.float32),
            feature_counts=np.asarray(z["feature_counts"], np.float32),
            score_edges=np.asarray(z["score_edges"], np.float32),
            score_counts=np.asarray(z["score_counts"], np.float32),
            score_quantiles=np.asarray(z["score_quantiles"], np.float32),
            n_rows=int(z["n_rows"]),
            feature_names=tuple(str(n) for n in z["feature_names"]),
        )
