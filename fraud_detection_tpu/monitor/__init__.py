"""watchtower: shadow scoring + online drift & quality monitoring.

The reference system scores traffic blind — observability stops at request
latency (SURVEY.md §5). This subsystem adds the model-quality layer:

- :mod:`baseline` — per-feature histogram + score-quantile profile captured
  at train time as a jitted reduction, saved beside ``model.npz``;
- :mod:`drift` — jitted sliding-window accumulators on the serving path:
  per-feature PSI/KS against the baseline, score-distribution PSI/KS, and
  windowed calibration (ECE) — one fused device call per scored batch with
  donated window state;
- :mod:`shadow` — challenger scoring (``models:/{name}@shadow``) on a
  sampled fraction of live batches, off the request path, tracking
  champion/challenger decision disagreement and challenger score drift;
- :mod:`watchtower` — the coordinator: threshold evaluation, Prometheus
  gauges, ``/monitor/status``, promotion/rollback recommendation, and the
  optional taskq retrain trigger;
- :mod:`promlint` — promtool-style validation of the alert-rule /
  dashboard configs under ``monitoring/`` so drift alerts can't ship broken.
"""

# Lazy re-exports (PEP 562): graftcheck's virtual-mesh pass and the promlint
# CLI import monitor submodules from a dependency-light environment (jax +
# numpy only) — an eager `from .watchtower import ...` here would drag in
# service.metrics → prometheus_client for every submodule import.
_EXPORTS = {
    "PROFILE_FILE": "baseline",
    "BaselineProfile": "baseline",
    "build_baseline_profile": "baseline",
    "load_profile": "baseline",
    "save_profile": "baseline",
    "DriftMonitor": "drift",
    "ShadowScorer": "shadow",
    "Watchtower": "watchtower",
    "build_watchtower": "watchtower",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"fraud_detection_tpu.monitor.{_EXPORTS[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
