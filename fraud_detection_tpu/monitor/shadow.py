"""Shadow scoring: a challenger model rides a sample of live traffic.

The challenger resolves from the registry alias ``models:/{name}@shadow``
(:func:`fraud_detection_tpu.service.loading.load_shadow_model`). A
configurable fraction of scored batches is re-scored by the challenger —
always OFF the request path (the watchtower's single ingest thread), so a
slow or broken challenger can never add champion latency; at worst its
batches are dropped by the watchtower's backlog bound.

Tracked, with the same exponential window semantics as :mod:`drift`:

- **decision disagreement**: fraction of rows where champion and challenger
  land on opposite sides of the alert threshold — the "would promotion
  change production behavior" number;
- **mean |Δscore|**: magnitude of the score gap;
- **challenger score PSI** against the baseline score histogram — per-model
  score drift, so the promotion recommendation can compare which model's
  output distribution still matches training.
"""

from __future__ import annotations

import logging

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.monitor.baseline import BaselineProfile
from fraud_detection_tpu.monitor.drift import psi_np

log = logging.getLogger("fraud_detection_tpu.watchtower")


class ShadowScorer:
    def __init__(
        self,
        scorer,
        profile: BaselineProfile,
        sample_rate: float | None = None,
        threshold: float = 0.5,
        halflife_rows: float | None = None,
        seed: int = 0,
        explainer=None,
    ):
        self._scorer = scorer
        # lantern × shadow: the challenger's raw-space linear-SHAP params
        # ``(coef, background_mean)`` — when present AND the champion's
        # serve-time top-k indices ride along with a sampled batch, the
        # window tracks reason-code divergence (mean 1 − Jaccard over the
        # index sets): how differently the challenger would EXPLAIN the
        # same traffic. Cheap host-side set math on already-fetched codes.
        self._explainer = explainer
        self.sample_rate = float(
            sample_rate
            if sample_rate is not None
            else config.watchtower_shadow_sample()
        )
        self.threshold = threshold
        self.halflife_rows = float(
            halflife_rows
            if halflife_rows is not None
            else config.watchtower_halflife_rows()
        )
        self._rng = np.random.default_rng(seed)
        self._score_edges = np.asarray(profile.score_edges, np.float64)
        self._base_counts = np.asarray(profile.score_counts, np.float64)
        self._score_counts = np.zeros_like(self._base_counts)
        self._rows = 0.0  # decayed
        self._disagree = 0.0  # decayed
        self._delta = 0.0  # decayed
        self._reason_rows = 0.0  # decayed rows with reason comparisons
        self._reason_div = 0.0  # decayed Σ (1 − Jaccard)
        self.batches_seen = 0
        self.batches_sampled = 0

    def swap_scorer(self, scorer, explainer=None) -> None:
        """Atomically replace the challenger params (the conductor's hot
        swap): one reference store between batches, then a window reset —
        disagreement/PSI accumulated against the OLD challenger would
        misjudge the new one."""
        self._scorer = scorer
        self._explainer = explainer
        self._score_counts = np.zeros_like(self._base_counts)
        self._rows = 0.0
        self._disagree = 0.0
        self._delta = 0.0
        self._reason_rows = 0.0
        self._reason_div = 0.0

    def maybe_observe(
        self,
        rows: np.ndarray,
        champion_scores: np.ndarray,
        champion_reasons=None,
    ) -> bool:
        """Sample-and-score one batch; returns True when the challenger ran.
        Called from the watchtower ingest thread, never the request path.
        ``champion_reasons`` is the (n, k) matrix of serve-time top-k
        reason-code indices when the fused explain leg rode the flush."""
        self.batches_seen += 1
        if self._rng.random() >= self.sample_rate:
            return False
        ch = np.asarray(
            self._scorer.predict_proba(np.asarray(rows, np.float32)),
            np.float64,
        ).reshape(-1)
        champ = np.asarray(champion_scores, np.float64).reshape(-1)
        n = ch.shape[0]
        # A sampled batch of n rows stands in for ~n/sample_rate rows of
        # live traffic, so fade in live-row terms — the halflife knob means
        # the same amount of traffic here as on the (full-rate) drift window.
        decay = 0.5 ** (n / (self.halflife_rows * min(self.sample_rate, 1.0)))
        self._rows = self._rows * decay + n
        self._disagree = self._disagree * decay + float(
            np.sum((ch >= self.threshold) != (champ >= self.threshold))
        )
        self._delta = self._delta * decay + float(np.sum(np.abs(ch - champ)))
        # side='right' keeps the bin convention identical to the jitted
        # histograms (index = #edges <= x) so boundary ties land the same
        hist = np.bincount(
            np.searchsorted(self._score_edges, ch, side="right"),
            minlength=self._base_counts.shape[0],
        ).astype(np.float64)
        self._score_counts = self._score_counts * decay + hist
        if champion_reasons is not None and self._explainer is not None:
            champ_idx = np.asarray(champion_reasons)
            k = champ_idx.shape[1] if champ_idx.ndim == 2 else 0
            if k > 0 and champ_idx.shape[0] == n:
                phi = self._challenger_phi(rows, n)
                if phi is not None:
                    self._fold_reasons(phi, champ_idx, k, n, decay)
        self.batches_sampled += 1
        return True

    def _challenger_phi(self, rows, n: int):
        """The challenger's per-row attribution matrix for one sampled
        batch, or None when it cannot be produced (the comparison is then
        skipped, never the sample). ``explainer`` is family-agnostic: a
        CALLABLE computes φ directly (any family with ``explain_batch`` —
        linear, wide, and the GBT forest's exact TreeSHAP, which runs on
        the watchtower ingest thread like the challenger re-score itself,
        never the request path); the legacy ``(coef, background_mean[,
        null_features])`` linear triple is kept for direct construction
        (tests, hand-built monitors)."""
        if callable(self._explainer):
            try:
                phi = np.asarray(self._explainer(rows), np.float64)
            except Exception:
                log.debug("challenger phi failed", exc_info=True)
                return None
            return phi if phi.ndim == 2 and phi.shape[0] == n else None
        coef, mu = self._explainer[0], self._explainer[1]
        nulls = self._explainer[2] if len(self._explainer) > 2 else None
        r = np.asarray(rows, np.float64)
        if r.shape[1] < coef.shape[0]:
            # WIDENED challenger, base-width monitor rows: explain through
            # the challenger's null slot (its worker-backfill view of the
            # same row); widths that can't reconcile skip the comparison
            if nulls is not None and r.shape[1] + nulls.shape[0] == coef.shape[0]:
                r = np.concatenate(
                    [r, np.broadcast_to(nulls, (n, nulls.shape[0]))], axis=1
                )
            else:
                return None
        return coef[None, :] * (r - mu[None, :])

    def _fold_reasons(self, phi, champ_idx, k, n, decay) -> None:
        """Fold one sampled batch's reason-code comparison into the decayed
        divergence window (mean 1 − Jaccard over the top-k index sets)."""
        # the challenger's top-k by signed attribution, matching
        # ops/linear_shap.topk_reasons' ranking; argsort is stable
        # so ties resolve toward the lower index like lax.top_k
        k = min(k, phi.shape[1])
        ch_idx = np.argsort(-phi, axis=1, kind="stable")[:, :k]
        inter = np.asarray(
            [
                len(set(a.tolist()) & set(b.tolist()))
                for a, b in zip(champ_idx, ch_idx)
            ],
            np.float64,
        )
        denom = np.maximum(champ_idx.shape[1] + k - inter, 1.0)
        jaccard = inter / denom
        self._reason_rows = self._reason_rows * decay + n
        self._reason_div = self._reason_div * decay + float(
            np.sum(1.0 - jaccard)
        )

    def stats(self) -> dict:
        rows = max(self._rows, 1e-9)
        return {
            "sample_rate": self.sample_rate,
            "batches_seen": self.batches_seen,
            "batches_sampled": self.batches_sampled,
            "window_rows": self._rows,
            "disagreement": self._disagree / rows,
            "mean_abs_delta": self._delta / rows,
            "score_psi": psi_np(self._score_counts, self._base_counts),
            "reason_divergence": (
                self._reason_div / self._reason_rows
                if self._reason_rows > 0
                else None
            ),
        }
