"""promtool-style validation of the monitoring configs.

``monitoring/`` ships Prometheus alert rules and a Grafana dashboard that
nothing executed before merge — a malformed expr or a truncated YAML would
only surface when the real Prometheus refused the rule file in production.
This module is the CI gate (run from ``tests/test_monitoring_configs.py``):

- when a real ``promtool`` binary is on PATH, rule files are checked with
  ``promtool check rules`` (authoritative);
- otherwise a structural lint runs: YAML parse (PyYAML when available, a
  conservative regex fallback otherwise), required keys
  (``groups[].name``, ``rules[].alert/expr``), balanced brackets and quotes
  in every expr, valid ``for:`` durations, and known severity labels.

Returns error strings rather than raising so callers can aggregate every
problem in one report.
"""

from __future__ import annotations

import json
import re
import shutil
import subprocess

_DURATION = re.compile(r"^\d+(\.\d+)?(ms|s|m|h|d|w|y)$")
_SEVERITIES = {"critical", "warning", "info", "none"}
_PAIRS = {")": "(", "]": "[", "}": "{"}


def _load_yaml(path: str):
    """Parse YAML; returns (data, error). Uses PyYAML when installed."""
    try:
        import yaml
    except ImportError:
        return None, None  # caller falls back to the regex lint
    try:
        with open(path) as f:
            return yaml.safe_load(f), None
    except yaml.YAMLError as e:
        return None, f"{path}: YAML parse error: {e}"


def check_expr(expr: str) -> str | None:
    """Balanced (), [], {} and quotes — the syntax slips a fat-fingered
    PromQL edit actually makes."""
    if not expr or not expr.strip():
        return "empty expr"
    stack: list[str] = []
    in_str: str | None = None
    for ch in expr:
        if in_str:
            if ch == in_str:
                in_str = None
            continue
        if ch in "'\"":
            in_str = ch
        elif ch in "([{":
            stack.append(ch)
        elif ch in ")]}":
            if not stack or stack.pop() != _PAIRS[ch]:
                return f"unbalanced {ch!r} in expr: {expr.strip()[:80]}"
    if in_str:
        return f"unterminated string in expr: {expr.strip()[:80]}"
    if stack:
        return f"unclosed {stack[-1]!r} in expr: {expr.strip()[:80]}"
    return None


def _lint_rule(path: str, group: str, rule, idx: int) -> list[str]:
    where = f"{path}: group {group!r} rule #{idx}"
    errors: list[str] = []
    if not isinstance(rule, dict):
        return [f"{where}: not a mapping"]
    if "alert" not in rule and "record" not in rule:
        errors.append(f"{where}: needs 'alert' or 'record'")
    expr = rule.get("expr")
    if not isinstance(expr, str):
        errors.append(f"{where}: missing/non-string 'expr'")
    else:
        err = check_expr(expr)
        if err:
            errors.append(f"{where}: {err}")
    if "for" in rule and not _DURATION.match(str(rule["for"]).strip()):
        errors.append(f"{where}: bad 'for' duration {rule['for']!r}")
    labels = rule.get("labels") or {}
    sev = labels.get("severity")
    if "alert" in rule and sev is not None and sev not in _SEVERITIES:
        errors.append(f"{where}: unknown severity {sev!r}")
    if "alert" in rule and not (rule.get("annotations") or {}).get("summary"):
        errors.append(f"{where}: alert without an annotations.summary")
    return errors


def _regex_lint_rules(path: str) -> list[str]:
    """No-PyYAML fallback: every alert must carry an expr, exprs must
    balance, and the file must declare a groups: root."""
    with open(path) as f:
        text = f.read()
    errors: list[str] = []
    if not re.search(r"^groups:\s*$", text, re.M):
        errors.append(f"{path}: no top-level 'groups:' key")
    n_alerts = len(re.findall(r"^\s*-?\s*alert:\s*\S+", text, re.M))
    n_exprs = len(re.findall(r"^\s*expr:", text, re.M))
    if n_alerts > n_exprs:
        errors.append(f"{path}: {n_alerts} alerts but only {n_exprs} exprs")
    for m in re.finditer(r"expr:\s*([^\n|]+)\n", text):
        err = check_expr(m.group(1))
        if err:
            errors.append(f"{path}: {err}")
    return errors


def lint_rules_file(path: str) -> list[str]:
    """Validate one Prometheus rule file; [] when clean."""
    promtool = shutil.which("promtool")
    if promtool:
        r = subprocess.run(
            [promtool, "check", "rules", path],
            capture_output=True, text=True, timeout=60,
        )
        if r.returncode != 0:
            return [f"{path}: promtool: {(r.stderr or r.stdout).strip()}"]
        return []
    data, err = _load_yaml(path)
    if err:
        return [err]
    if data is None:
        return _regex_lint_rules(path)
    errors: list[str] = []
    groups = data.get("groups") if isinstance(data, dict) else None
    if not isinstance(groups, list) or not groups:
        return [f"{path}: expected a non-empty top-level 'groups' list"]
    for g in groups:
        if not isinstance(g, dict) or "name" not in g:
            errors.append(f"{path}: group without a 'name'")
            continue
        rules = g.get("rules")
        if not isinstance(rules, list) or not rules:
            errors.append(f"{path}: group {g['name']!r} has no rules")
            continue
        for i, rule in enumerate(rules):
            errors.extend(_lint_rule(path, g["name"], rule, i))
    return errors


def lint_prometheus_config(path: str) -> list[str]:
    """Validate the scrape config: parseable + scrape_configs present."""
    data, err = _load_yaml(path)
    if err:
        return [err]
    if data is None:
        return []  # no YAML parser available; rule files still regex-lint
    errors = []
    if not isinstance(data, dict) or not data.get("scrape_configs"):
        errors.append(f"{path}: no scrape_configs")
    return errors


def lint_grafana_dashboard(path: str) -> list[str]:
    """Validate the dashboard JSON: parseable, panels carry non-empty
    target exprs."""
    try:
        with open(path) as f:
            data = json.load(f)
    except json.JSONDecodeError as e:
        return [f"{path}: JSON parse error: {e}"]
    errors = []
    panels = data.get("panels")
    if not isinstance(panels, list) or not panels:
        return [f"{path}: no panels"]
    for p in panels:
        title = p.get("title", "<untitled>")
        for t in p.get("targets", []):
            err = check_expr(t.get("expr", ""))
            if err:
                errors.append(f"{path}: panel {title!r}: {err}")
    return errors


def lint_monitoring_tree(monitoring_dir: str) -> list[str]:
    """Lint every config the ``monitoring/`` tree ships: all Prometheus rule
    files (top level + ``prometheus/rules/``), the scrape config, and the
    Grafana dashboard. Returns every error found, aggregated."""
    import glob
    import os

    errors: list[str] = []
    rule_files = sorted(
        glob.glob(os.path.join(monitoring_dir, "alert_rules.yml"))
        + glob.glob(os.path.join(monitoring_dir, "prometheus", "rules", "*.yml"))
    )
    if not rule_files:
        errors.append(f"{monitoring_dir}: no Prometheus rule files found")
    for path in rule_files:
        errors.extend(lint_rules_file(path))
    scrape = os.path.join(monitoring_dir, "prometheus.yml")
    if os.path.exists(scrape):
        errors.extend(lint_prometheus_config(scrape))
    dashboard = os.path.join(monitoring_dir, "grafana_dashboard.json")
    if os.path.exists(dashboard):
        errors.extend(lint_grafana_dashboard(dashboard))
    return errors


def main(argv: list[str] | None = None) -> int:
    """CLI for CI: ``python -m fraud_detection_tpu.monitor.promlint
    [monitoring_dir]`` — exits 1 on any error, printing each one."""
    import sys

    args = argv if argv is not None else sys.argv[1:]
    monitoring_dir = args[0] if args else "monitoring"
    errors = lint_monitoring_tree(monitoring_dir)
    for err in errors:
        print(err)
    if not errors:
        print(f"{monitoring_dir}: all monitoring configs clean")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
