"""The fraud range: named chaos scenarios against the live in-process stack.

Each scenario builds the REAL serving pieces (jitted ``BatchScorer`` behind
the micro-batcher, watchtower drift window, conductor CAS state machine,
sqlite broker) — no stubs — replays a seeded synthetic campaign
(range/traffic.py), optionally arms a :class:`~.faults.FaultPlan`
(range/faults.py), and asserts the end-to-end invariants
(range/invariants.py). Results serialize into the bench JSON trajectory
(``bench.py`` ``scenarios`` section) and drive the ``-m slow`` chaos test
tier (tests/test_range.py, CI ``chaos`` job).

The suite (``run_scenario(name)``):

========================  ==================================================
``burst``                 heavy-tailed diurnal arrival bursts through the
                          micro-batcher; p99 holds, every row scored, no
                          alert flap
``drift_onset``           covariate drift at a known onset row; detected
                          within the row budget, drift window ends
                          bitwise-consistent across two seeded runs
``fraud_ring``            coordinated correlated-feature rings; the model
                          separates ring rows AND the drift monitor flags
                          the contamination within budget
``label_delay``           delayed + noisy labels, one poisoned feedback
                          batch; the store rejects poison, clean rows land
                          durably and in the calibration window, ECE stays
                          finite
``control_plane_chaos``   replica killed mid-promotion + duplicate task
                          delivery past the visibility timeout; promotion
                          converges to exactly-once on resume
``hot_swap``              champion hot-swapped mid-burst; p99 holds across
                          the swap, zero new XLA compiles (no recompile
                          storm), every row scored
``shard_kill_mid_swap``   a switchyard shard killed WHILE a promotion
                          lands; load sheds to healthy shards, exactly one
                          swap applies, the ladder stays warm, p99 holds
``replica_burst``         burst across replica shards while one drains;
                          p99 holds, in-flight empties cleanly, survivors
                          share the load
``explain_under_burst``   Pareto burst with SCORER_EXPLAIN=topk fused into
                          every flush + a shard killed mid-burst; p99
                          holds, EVERY scored row carries its k reason
                          codes, the kill sheds load without dropping the
                          explain output
``gbt_explain_under_burst``  the evergreen combo: a GBT champion on the
                          int8 wire with in-dispatch TreeSHAP reason
                          codes, Pareto burst + shard kill mid-burst —
                          same invariants as explain_under_burst on the
                          family that used to demote out of both legs
``poison_entity_state``   one entity hammered with NaN/extreme amounts via
                          the ``ledger.update`` injection point; the poison
                          clamp bounds the victim slot, every other
                          entity's aggregates stay bitwise-unaffected,
                          scores stay finite, p99 holds
``ingest_storm``          open-loop Pareto-burst frames on the REAL binary
                          ingest lane with a mid-burst shard drain; the
                          bounded admission queue sheds with Retry-After
                          (never OOM, never unbounded p99), every admitted
                          row is answered, and the drift window bitwise-
                          matches a closed-loop replay of the same rows
``slo_burn_under_shed``   panopticon: a Pareto burst drives real admission
                          sheds; the SLO engine's fast-burn condition
                          fires within its shortest window, the error
                          budget drops, and after recovery traffic drains
                          the windows the condition clears without
                          flapping
``crash_warm_restart``    lifeboat: the service killed mid-flush under
                          entity-bearing traffic (after the journal
                          append, before the dispatch); the warm restart
                          bitwise-equals both an independent replay of the
                          snapshot+journal bytes and a clean uninterrupted
                          drive, /health answers 503 + Retry-After while
                          recovering then flips ready, and post-recovery
                          scoring costs 0 new compiles
``kill_mid_snapshot``     lifeboat: the snapshotter killed between the
                          journal rotation and the generation landing,
                          plus a fabricated torn newest generation; the
                          previous generation loads (skip counted), the
                          synced journal replays the FULL table bitwise,
                          and a torn journal tail loses exactly the final
                          flush — counted on the metric, never silent
``ledger_owner_failover_mid_traffic``
                          longhaul: a 2-host fleet serving routed traffic
                          loses one host to an abrupt kill; the data plane
                          never answers worse than 503 + Retry-After
                          during the handoff, the survivor replays the
                          dead peer's journal generation and ends owning
                          BOTH segments with the inherited segment bitwise
                          equal to an uninterrupted single-host serve, at
                          zero new fused-flush compiles
``host_partition_mid_promotion``
                          longhaul: a host partitioned from the directory
                          is declared dead (epoch bumps); its promotion
                          finalize — decided under the old epoch — is
                          fenced (directory unreachable), a reachable
                          host's finalize under the old epoch is fenced
                          too (epoch moved), and after rejoin a finalize
                          under the fresh epoch lands exactly once
``split_brain_scrape``    longhaul: a partitioned host keeps serving and
                          answering scrapes under its frozen epoch; the
                          fleet merge drops its contribution (counted on
                          longhaul_scrape_stale_epoch), never
                          double-counts the drift window, and re-admits
                          the host after rejoin under the fresh epoch
========================  ==================================================
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass

import numpy as np

from fraud_detection_tpu.range import faults
from fraud_detection_tpu.range.invariants import (
    AlertFlapDetector,
    InvariantOutcome,
    ScenarioResult,
    drift_detected_within,
    exactly_once_promotion,
    p99_within,
    windows_bitwise_equal,
)
from fraud_detection_tpu.range.traffic import (
    ArrivalProcess,
    CampaignSpec,
    CampaignTraffic,
    DelayedLabelJoiner,
    DriftCampaign,
    FraudRing,
    LabelFeedback,
)

KAGGLE = ["Time"] + [f"V{i}" for i in range(1, 29)] + ["Amount"]
D = 30


# -- environment builders ----------------------------------------------------

@dataclass
class RangeModel:
    """A trained-for-real champion + its baseline profile + the ground
    truth boundary the traffic generators share."""

    model: object
    profile: object
    w_true: np.ndarray
    x_base: np.ndarray
    y_base: np.ndarray


def _make_rows(n: int, rng: np.random.Generator, w_true: np.ndarray):
    x = rng.standard_normal((n, D)).astype(np.float32)
    logits = x @ w_true - 2.0
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.int32)
    return x, y


def build_model(seed: int = 7, n_base: int = 2400) -> RangeModel:
    """Fit a small logistic champion on synthetic Kaggle-schema data and
    profile it — the real scorer/profile pair every scenario serves."""
    from fraud_detection_tpu.models.logistic import FraudLogisticModel
    from fraud_detection_tpu.monitor.baseline import build_baseline_profile
    from fraud_detection_tpu.ops.logistic import logistic_fit_lbfgs
    from fraud_detection_tpu.ops.scaler import scaler_fit, scaler_transform

    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal(D).astype(np.float32)
    x, y = _make_rows(n_base, rng, w_true)
    scaler = scaler_fit(x)
    params = logistic_fit_lbfgs(scaler_transform(scaler, x), y, max_iter=100)
    model = FraudLogisticModel(params, scaler, KAGGLE)
    scores = np.asarray(model.scorer.predict_proba(x[:1024]))
    profile = build_baseline_profile(x, scores, feature_names=KAGGLE)
    return RangeModel(model, profile, w_true, x, y)


def _watchtower(profile, min_rows: int = 256, halflife: float = 1500.0):
    from fraud_detection_tpu.monitor.watchtower import Thresholds, Watchtower

    thr = Thresholds(
        psi=0.2, ks=0.15, ece=0.2, disagree=0.05, min_rows=min_rows
    )
    return Watchtower(
        profile, thresholds=thr, halflife_rows=halflife, max_backlog=256
    )


# -- shared drivers ----------------------------------------------------------

async def _timed_score(batcher, row, lat: list[float]) -> float:
    t0 = time.perf_counter()
    s = await batcher.score(row)
    lat.append(time.perf_counter() - t0)
    return s


async def _baseline_p99(batcher, rows: np.ndarray) -> float:
    """Quiet-traffic per-request latency floor: sequential lone requests."""
    lat: list[float] = []
    for r in rows:
        await _timed_score(batcher, r, lat)
    return float(np.percentile(np.asarray(lat), 99))


def _drive_bursts(
    batcher_factory,
    traffic: CampaignTraffic,
    on_batch=None,
    mid_stream=None,
) -> dict:
    """Replay a campaign through a micro-batcher on a private event loop.

    ``on_batch(batch, scores)`` runs after each batch resolves;
    ``mid_stream(batcher)`` fires once, halfway through the campaign (the
    hot-swap hook). Returns latencies, scores and counters.
    """

    async def run() -> dict:
        batcher = batcher_factory()
        await batcher.start()
        try:
            warm = traffic.rng.standard_normal((64, D)).astype(np.float32)
            base_p99 = await _baseline_p99(batcher, warm)
            lat: list[float] = []
            n_scored = 0
            batches = list(traffic.batches())
            fire_mid = len(batches) // 2
            mid_fut = None
            for bi, batch in enumerate(batches):
                if mid_stream is not None and bi == fire_mid:
                    # launch WITHOUT awaiting: requests must genuinely
                    # overlap the swap, or the p99-across-swap invariant
                    # passes vacuously (a swap that blocks serving would
                    # add zero latency to any measured request otherwise)
                    mid_fut = asyncio.get_running_loop().run_in_executor(
                        None, mid_stream, batcher
                    )
                scores = await asyncio.gather(
                    *(_timed_score(batcher, r, lat) for r in batch.rows)
                )
                n_scored += len(scores)
                if on_batch is not None:
                    on_batch(batch, np.asarray(scores, np.float32))
                await asyncio.sleep(traffic.spec.arrivals.window_s)
            if mid_fut is not None:
                await mid_fut
            return {
                "baseline_p99_s": base_p99,
                "latencies_s": lat,
                "rows_scored": n_scored,
            }
        finally:
            await batcher.stop()

    return asyncio.run(run())


def _fold_campaign(
    wt,
    model,
    traffic: CampaignTraffic,
    sample_every: int = 4,
    status_hook=None,
    on_batch=None,
) -> dict:
    """Synchronous replay: score each batch with the real scorer, hand it
    to the watchtower, drain, and sample status — the deterministic driver
    the detection-latency and bitwise invariants need."""
    flap = AlertFlapDetector()
    detected_row: int | None = None
    rows = 0
    for bi, batch in enumerate(traffic.batches()):
        scores = np.asarray(model.scorer.predict_proba(batch.rows), np.float32)
        if on_batch is not None:
            on_batch(batch, scores)
        wt.observe(batch.rows, scores)
        # drain per batch: the bounded ingest backlog must never drop a
        # batch here — determinism (the bitwise invariant) depends on every
        # batch folding, in order
        wt.drain(timeout=30.0)
        rows = batch.start_row + batch.rows.shape[0]
        if bi % sample_every == 0:
            status = wt.status()
            flap.sample(drift=status["status"] == "drift")
            if status["status"] == "drift" and detected_row is None:
                detected_row = rows
            if status_hook is not None:
                status_hook(batch, scores, status)
    wt.drain(timeout=30.0)
    status = wt.status()
    flap.sample(drift=status["status"] == "drift")
    if status["status"] == "drift" and detected_row is None:
        detected_row = rows
    return {
        "detected_row": detected_row,
        "rows": rows,
        "flap": flap,
        "final_status": status,
    }


# -- scenarios ---------------------------------------------------------------

def scenario_burst(seed: int = 2026, total_rows: int = 6144) -> ScenarioResult:
    """Heavy-tailed diurnal bursts; the serving path holds its latency SLO
    and loses nothing."""
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    rm = build_model(seed=seed)
    wt = _watchtower(rm.profile)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true,
        arrivals=ArrivalProcess(rate_hz=4000.0, window_s=0.01),
    )
    result = ScenarioResult("burst")
    try:
        out = _drive_bursts(
            lambda: MicroBatcher(
                scorer=rm.model.scorer, watchtower=wt,
                max_batch=512, max_wait_ms=2.0, telemetry=False,
            ),
            CampaignTraffic(spec),
        )
    finally:
        wt.close()
    result.metrics = {
        "rows": total_rows,
        "rows_scored": out["rows_scored"],
        "baseline_p99_ms": round(out["baseline_p99_s"] * 1e3, 3),
        "burst_p99_ms": round(
            float(np.percentile(out["latencies_s"], 99)) * 1e3, 3
        ),
    }
    result.add(
        p99_within(
            out["latencies_s"], out["baseline_p99_s"],
            factor=10.0, absolute_floor_s=0.25,
        )
    )
    result.add(
        InvariantOutcome(
            "all-rows-scored",
            out["rows_scored"] == total_rows,
            f"{out['rows_scored']}/{total_rows} rows returned a score",
        )
    )
    return result


def scenario_drift_onset(
    seed: int = 2026, total_rows: int = 6144, onset_row: int = 2048,
    budget_rows: int = 2048,
) -> ScenarioResult:
    """Covariate drift with a known onset; detection latency is bounded and
    the window state is bitwise-reproducible per seed."""
    rm = build_model(seed=seed)
    drift = DriftCampaign(onset_row=onset_row, mean_shift=3.0)

    def one_run():
        wt = _watchtower(rm.profile)
        spec = CampaignSpec(
            total_rows=total_rows, seed=seed, w_true=rm.w_true, drift=drift
        )
        try:
            out = _fold_campaign(wt, rm.model, CampaignTraffic(spec))
            window = wt.drift.window
        finally:
            wt.close()
        return out, window

    out, window_a = one_run()
    _, window_b = one_run()  # same seed → must end bitwise identical

    result = ScenarioResult("drift_onset")
    result.metrics = {
        "rows": out["rows"],
        "onset_row": onset_row,
        "detected_row": out["detected_row"],
        "feature_psi_max": round(
            out["final_status"]["drift"]["feature_psi_max"], 4
        ),
    }
    result.add(drift_detected_within(onset_row, out["detected_row"], budget_rows))
    result.add(out["flap"].check())
    result.add(windows_bitwise_equal(window_a, window_b))
    return result


def scenario_fraud_ring(
    seed: int = 2026, total_rows: int = 6144, ring_start: int = 1536,
    budget_rows: int = 3072,
) -> ScenarioResult:
    """Coordinated rings (correlated feature clusters): the scorer must
    separate ring rows from background AND the drift monitor must flag the
    contamination."""
    rm = build_model(seed=seed)
    # ~25% of post-onset traffic is ring rows (96 per 288 background): a
    # mule-network burst heavy enough that NOT flagging it is a monitoring
    # failure, not a threshold judgement call
    ring = FraudRing(start_row=ring_start, n_rings=3, ring_size=96,
                     every_rows=288, center_scale=5.0)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true, ring=ring
    )
    wt = _watchtower(rm.profile)
    ring_scores: list[float] = []
    bg_scores: list[float] = []

    def collect(batch, scores):
        ring_scores.extend(scores[batch.ring_mask].tolist())
        bg_scores.extend(scores[~batch.ring_mask].tolist())

    try:
        out = _fold_campaign(
            wt, rm.model, CampaignTraffic(spec), on_batch=collect
        )
    finally:
        wt.close()

    result = ScenarioResult("fraud_ring")
    ring_mean = float(np.mean(ring_scores)) if ring_scores else float("nan")
    bg_mean = float(np.mean(bg_scores)) if bg_scores else float("nan")
    result.metrics = {
        "rows": out["rows"],
        "ring_rows": len(ring_scores),
        "ring_mean_score": round(ring_mean, 4),
        "background_mean_score": round(bg_mean, 4),
        "detected_row": out["detected_row"],
    }
    result.add(
        InvariantOutcome(
            "ring-rows-injected",
            len(ring_scores) > 0,
            f"{len(ring_scores)} ring rows generated",
        )
    )
    result.add(
        InvariantOutcome(
            "ring-separable",
            bool(ring_scores) and abs(ring_mean - bg_mean) > 0.05,
            f"ring mean score {ring_mean:.4f} vs background {bg_mean:.4f} — "
            "a coordinated cluster must not score like background traffic",
        )
    )
    result.add(drift_detected_within(ring_start, out["detected_row"], budget_rows))
    result.add(out["flap"].check())
    return result


def scenario_label_delay(
    tmpdir: str, seed: int = 2026, total_rows: int = 4096,
    delay_rows: int = 1024, noise_rate: float = 0.05,
) -> ScenarioResult:
    """Delayed, noisy labels with one poisoned batch in flight: durable
    feedback stays consistent, poison is rejected at the store boundary,
    and calibration state stays finite."""
    from fraud_detection_tpu.lifecycle.store import LifecycleStore

    rm = build_model(seed=seed)
    fb = LabelFeedback(delay_rows=delay_rows, noise_rate=noise_rate, batch=256)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true, feedback=fb,
        # huge half-life: decayed n_labeled ≈ true labeled count, so the
        # bookkeeping invariant below is exact-ish
    )
    wt = _watchtower(rm.profile, halflife=10_000_000.0)
    joiner = DelayedLabelJoiner(fb, seed)
    store = LifecycleStore(
        f"sqlite:///{os.path.join(tmpdir, 'range-lifecycle.db')}",
        window_size=total_rows, reservoir_size=256,
    )
    delivered = 0
    rejected_batches = 0

    def poison(features=None, scores=None, labels=None, **_):
        # corrupt the scores array in flight (review pipeline bug)
        if scores is not None:
            scores[:] = np.nan

    plan = faults.FaultPlan().call(
        "lifecycle.store.add_feedback", poison, times=1
    )
    try:
        with plan.armed():
            for batch in CampaignTraffic(spec).batches():
                scores = np.asarray(
                    rm.model.scorer.predict_proba(batch.rows), np.float32
                )
                wt.observe(batch.rows, scores)
                joiner.observe(batch, scores)
                current = batch.start_row + batch.rows.shape[0]
                for fx, fs, fy in joiner.due(current):
                    fs = fs.copy()  # the poison fault mutates in flight
                    try:
                        store.add_feedback(fx, fs, fy)
                    except ValueError:
                        rejected_batches += 1
                        continue
                    wt.observe(fx, fs, fy, calibration_only=True)
                    delivered += fy.shape[0]
        wt.drain(timeout=30.0)
        status = wt.status()
    finally:
        counts = store.feedback_counts()
        store.close()
        wt.close()

    result = ScenarioResult("label_delay")
    n_labeled = float(status["drift"]["n_labeled"])
    ece = float(status["drift"]["ece"])
    result.metrics = {
        "rows": total_rows,
        "labels_released": joiner.released_rows,
        "labels_flipped": joiner.flipped_rows,
        "labels_delivered": delivered,
        "poisoned_batches_rejected": rejected_batches,
        "store_window_rows": counts["window"],
        "ece": round(ece, 4),
    }
    result.add(
        InvariantOutcome(
            "poison-rejected",
            rejected_batches == 1 and plan.fired() == 1,
            f"{rejected_batches} poisoned batch(es) rejected at the store "
            f"boundary ({plan.fired()} fault(s) fired)",
        )
    )
    result.add(
        InvariantOutcome(
            "feedback-durable",
            counts["window"] == delivered and counts["seen"] == delivered,
            f"store window {counts['window']} / seen {counts['seen']} vs "
            f"{delivered} delivered rows",
        )
    )
    result.add(
        InvariantOutcome(
            "calibration-bookkeeping",
            abs(n_labeled - delivered) <= max(2.0, 0.01 * delivered),
            f"calibration window holds {n_labeled:.0f} labeled rows, "
            f"{delivered} delivered",
        )
    )
    result.add(
        InvariantOutcome(
            "ece-finite",
            np.isfinite(ece),
            f"windowed ECE = {ece} over noisy delayed labels",
        )
    )
    return result


# -- lifecycle scenarios -----------------------------------------------------

def build_lifecycle_env(tmpdir: str, seed: int = 7) -> dict:
    """Registered champion + lifecycle store + conductor, self-contained in
    ``tmpdir`` (no environment variables touched)."""
    from fraud_detection_tpu.lifecycle import Conductor, GateThresholds, LifecycleStore
    from fraud_detection_tpu.monitor.baseline import (
        build_baseline_profile,
        save_profile,
    )
    from fraud_detection_tpu.tracking.store import TrackingClient

    rm = build_model(seed=seed)
    csv = os.path.join(tmpdir, "base.csv")
    with open(csv, "w") as f:
        f.write(",".join(KAGGLE + ["Class"]) + "\n")
        for row, label in zip(rm.x_base, rm.y_base):
            f.write(",".join(f"{v:.6f}" for v in row) + f",{int(label)}\n")
    art = os.path.join(tmpdir, "champion")
    rm.model.save(art, joblib_too=False)
    scores = np.asarray(rm.model.scorer.predict_proba(rm.x_base[:512]))
    save_profile(
        art, build_baseline_profile(rm.x_base, scores, feature_names=KAGGLE)
    )
    client = TrackingClient(uri=f"file:{os.path.join(tmpdir, 'mlruns')}")
    v1 = client.registry.register("fraud", art)
    client.registry.set_alias("fraud", "prod", v1)
    store = LifecycleStore(
        f"sqlite:///{os.path.join(tmpdir, 'lifecycle.db')}",
        window_size=600, reservoir_size=200, seed=3,
    )
    loose = GateThresholds(
        auc_margin=0.05, ece_bound=0.5, psi_bound=2.0, min_eval_rows=64
    )
    conductor = Conductor(
        store=store,
        tracking_client=client,
        retrain_kwargs={
            "data_csv": csv, "use_smote": False, "max_iter": 100,
            "thresholds": loose,
        },
    )
    return {
        "rm": rm, "client": client, "registry": client.registry,
        "store": store, "conductor": conductor, "v1": v1, "tmp": tmpdir,
    }


def _feed_store(env, n: int = 512, seed: int = 99) -> None:
    rng = np.random.default_rng(seed)
    x, y = _make_rows(n, rng, env["rm"].w_true)
    s = 1.0 / (1.0 + np.exp(-(x @ env["rm"].w_true - 2.0)))
    env["store"].add_feedback(x, s.astype(np.float32), y)


def scenario_control_plane_chaos(
    tmpdir: str, seed: int = 7, kill_point: str = "conductor.promoting.pre_alias",
) -> ScenarioResult:
    """The mid-promotion kill + duplicate-delivery drill: a replica dies at
    ``kill_point`` with the promotion intent persisted; the promote task is
    redelivered past a collapsed visibility window AND a second replica
    resumes — the CAS machine must converge to exactly one promotion."""
    from fraud_detection_tpu.lifecycle import Conductor
    from fraud_detection_tpu.lifecycle import store as lst
    from fraud_detection_tpu.service import metrics
    from fraud_detection_tpu.service.taskq import SqliteBroker

    env = build_lifecycle_env(tmpdir, seed=seed)
    result = ScenarioResult("control_plane_chaos")
    _feed_store(env, n=512, seed=seed + 1)

    out = env["conductor"].handle_retrain("range: control-plane drill")
    result.add(
        InvariantOutcome(
            "retrain-gated",
            out.get("outcome") == "gated",
            f"retrain outcome {out.get('outcome')!r}",
        )
    )
    if out.get("outcome") != "gated":
        return result
    v2 = out["version"]
    versions_before = env["registry"].latest_version("fraud")
    promos_before = metrics.lifecycle_promotions._value.get()

    # --- duplicate delivery: the promote task redelivered past a collapsed
    # visibility window (simulating a worker that claimed, then stalled)
    broker = SqliteBroker(f"sqlite:///{os.path.join(tmpdir, 'taskq.db')}")
    redeliveries_before = broker.redeliveries
    plan = (
        faults.FaultPlan()
        .kill(kill_point)
        .patch("taskq.visibility_timeout", 0.0, times=1)
    )
    killed = False
    with plan.armed():
        broker.send_task("lifecycle.promote_challenger", ["range drill"])
        first = broker.claim("worker-a")  # visibility collapsed to 0 → stays deliverable
        second = broker.claim("worker-b")  # the at-least-once redelivery
        try:
            env["conductor"].handle_promote("range drill")
        except faults.ReplicaKilled:
            killed = True  # replica died mid-promotion, intent persisted
    result.add(
        InvariantOutcome(
            "fault-fired",
            killed and plan.fired(kill_point) == 1,
            f"kill at {kill_point}: fired={plan.fired(kill_point)}",
        )
    )
    result.add(
        InvariantOutcome(
            "task-redelivered",
            first is not None and second is not None
            and second.id == first.id
            and broker.redeliveries - redeliveries_before >= 1,
            "collapsed visibility window produced an observable redelivery "
            f"(redeliveries +{broker.redeliveries - redeliveries_before})",
        )
    )
    state = env["store"].get_state("fraud")["state"]
    result.add(
        InvariantOutcome(
            "intent-persisted",
            state in (lst.PROMOTING, lst.SHADOWING),
            f"state after kill = {state!r} (intent must be durable)",
        )
    )

    # --- two replicas resume concurrently-ish: the first completes the
    # promotion, the second finds nothing to do
    replica_b = Conductor(store=env["store"], tracking_client=env["client"])
    resumed = replica_b.resume()
    replica_c = Conductor(store=env["store"], tracking_client=env["client"])
    resumed_again = replica_c.resume()
    dup = env["conductor"].handle_promote("duplicate delivery replay")
    broker.ack(second.id)
    broker.close()

    result.metrics = {
        "kill_point": kill_point,
        "challenger_version": v2,
        "resume_outcome": (resumed or {}).get("outcome"),
        "second_resume": resumed_again,
        "duplicate_promote_outcome": dup.get("outcome"),
        "redeliveries": broker.redeliveries - redeliveries_before,
    }
    result.add(
        InvariantOutcome(
            "resume-completes",
            (resumed or {}).get("outcome") == "promoted"
            and resumed_again is None,
            f"first resume {resumed!r}, second resume {resumed_again!r}",
        )
    )
    result.add(
        InvariantOutcome(
            "duplicate-promote-dropped",
            dup.get("outcome") in ("skipped", "no_challenger"),
            f"replayed promote task outcome {dup.get('outcome')!r}",
        )
    )
    promos_delta = metrics.lifecycle_promotions._value.get() - promos_before
    result.add(
        exactly_once_promotion(
            env["registry"], env["store"], "fraud",
            challenger_version=v2, versions_before=versions_before,
            promotions_delta=promos_delta,
        )
    )
    env["store"].close()
    return result


def scenario_hot_swap(
    seed: int = 2026, total_rows: int = 4096
) -> ScenarioResult:
    """Champion hot swap under burst traffic: the slot flip lands between
    flushes with p99 intact and ZERO new XLA compiles (the ladder was
    pre-warmed — a swap must never page RecompileStorm)."""
    from fraud_detection_tpu.lifecycle.swap import ModelSlot, warm_scorer
    from fraud_detection_tpu.monitor import drift as drift_mod
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    rm = build_model(seed=seed)
    challenger = build_model(seed=seed + 1)
    wt = _watchtower(rm.profile)
    slot = ModelSlot(rm.model, "range:champion", 1)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true,
        arrivals=ArrivalProcess(rate_hz=4000.0, window_s=0.01),
    )
    swap_state = {"compiles_delta": None, "swapped": False}

    def swap(batcher) -> None:
        # what ModelReloader does on an alias flip, minus the registry:
        # warm the incoming ladder off-path, THEN flip the slot
        warm_scorer(challenger.model.scorer, max_batch=512)
        before = drift_mod._fused_flush._cache_size()
        slot.swap(challenger.model, "range:challenger", 2)
        swap_state["compiles_before"] = before
        swap_state["swapped"] = True

    result = ScenarioResult("hot_swap")
    try:
        out = _drive_bursts(
            lambda: MicroBatcher(
                slot=slot, watchtower=wt,
                max_batch=512, max_wait_ms=2.0, telemetry=False,
            ),
            CampaignTraffic(spec),
            mid_stream=swap,
        )
        compiles_after = drift_mod._fused_flush._cache_size()
    finally:
        wt.close()

    compiles_delta = (
        compiles_after - swap_state.get("compiles_before", compiles_after)
        if swap_state["swapped"]
        else None
    )
    result.metrics = {
        "rows": total_rows,
        "rows_scored": out["rows_scored"],
        "baseline_p99_ms": round(out["baseline_p99_s"] * 1e3, 3),
        "swap_p99_ms": round(
            float(np.percentile(out["latencies_s"], 99)) * 1e3, 3
        ),
        "post_swap_compiles": compiles_delta,
    }
    result.add(
        InvariantOutcome(
            "swap-applied",
            swap_state["swapped"] and slot.version == 2,
            f"slot now serves v{slot.version}",
        )
    )
    result.add(
        p99_within(
            out["latencies_s"], out["baseline_p99_s"],
            factor=10.0, absolute_floor_s=0.25,
        )
    )
    result.add(
        InvariantOutcome(
            "no-recompile-storm",
            compiles_delta == 0,
            f"{compiles_delta} fused-flush executables compiled after the "
            "pre-warmed swap (must be 0)",
        )
    )
    result.add(
        InvariantOutcome(
            "all-rows-scored",
            out["rows_scored"] == total_rows,
            f"{out['rows_scored']}/{total_rows} rows returned a score",
        )
    )
    return result


# -- switchyard scenarios ----------------------------------------------------

def _shard_front(rm, wt, n_shards: int, slot=None, max_batch: int = 512):
    from fraud_detection_tpu.mesh.front import ShardFront
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    kw = dict(max_batch=max_batch, max_wait_ms=2.0, telemetry=False)
    if slot is not None:
        batchers = [
            MicroBatcher(slot=slot, watchtower=wt, **kw)
            for _ in range(n_shards)
        ]
    else:
        batchers = [
            MicroBatcher(scorer=rm.model.scorer, watchtower=wt, **kw)
            for _ in range(n_shards)
        ]
    return ShardFront(batchers, max_consecutive_errors=3)


def scenario_shard_kill_mid_swap(
    seed: int = 2026, total_rows: int = 4096, n_shards: int = 3,
    victim: int = 1,
) -> ScenarioResult:
    """Kill a switchyard shard WHILE a promotion hot-swap lands: the dead
    shard must shed its load to the healthy shards (every row still
    scored), exactly one swap must apply across all shards (they share the
    slot), the pre-warmed ladder must hold (zero new fused-flush compiles
    after the swap), and p99 must survive both disturbances at once."""
    from fraud_detection_tpu.lifecycle.swap import ModelSlot, warm_scorer
    from fraud_detection_tpu.mesh.front import DEAD
    from fraud_detection_tpu.monitor import drift as drift_mod

    rm = build_model(seed=seed)
    challenger = build_model(seed=seed + 1)
    wt = _watchtower(rm.profile)
    slot = ModelSlot(rm.model, "range:champion", 1)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true,
        arrivals=ArrivalProcess(rate_hz=4000.0, window_s=0.01),
    )
    swap_state = {"swapped": False, "compiles_before": None}
    kill_armed = {"on": False}
    injected = {"n": 0}  # ACTUAL injected failures — the call rule fires
    # (and counts in plan.fired()) on every routed row, raising only for
    # the armed victim, so plan.fired() alone would be routing volume
    fronts: list = []

    def shard_fault(shard=None, **_):
        if kill_armed["on"] and shard == victim:
            injected["n"] += 1
            raise RuntimeError("range: injected shard flush failure")

    def swap_and_kill(front) -> None:
        # the ModelReloader sequence minus the registry (warm off-path,
        # then flip) — with the victim shard dying in the same window
        kill_armed["on"] = True
        warm_scorer(challenger.model.scorer, max_batch=512)
        swap_state["compiles_before"] = drift_mod._fused_flush._cache_size()
        slot.swap(challenger.model, "range:challenger", 2)
        swap_state["swapped"] = True

    def factory():
        front = _shard_front(rm, wt, n_shards, slot=slot)
        fronts.append(front)
        return front

    plan = faults.FaultPlan().call("mesh.shard_flush", shard_fault, times=-1)
    result = ScenarioResult("shard_kill_mid_swap")
    try:
        with plan.armed():
            out = _drive_bursts(
                factory, CampaignTraffic(spec), mid_stream=swap_and_kill
            )
        compiles_after = drift_mod._fused_flush._cache_size()
    finally:
        wt.close()
    front = fronts[0]
    status = front.status()
    compiles_delta = (
        compiles_after - swap_state["compiles_before"]
        if swap_state["swapped"]
        else None
    )
    result.metrics = {
        "rows": total_rows,
        "rows_scored": out["rows_scored"],
        "shards": n_shards,
        "victim": victim,
        "victim_state": status["per_shard"][victim]["state"],
        "victim_errors": status["per_shard"][victim]["errors_total"],
        "healthy_after": status["healthy"],
        "baseline_p99_ms": round(out["baseline_p99_s"] * 1e3, 3),
        "chaos_p99_ms": round(
            float(np.percentile(out["latencies_s"], 99)) * 1e3, 3
        ),
        "post_swap_compiles": compiles_delta,
        "failures_injected": injected["n"],
    }
    result.add(
        InvariantOutcome(
            "shard-killed",
            status["per_shard"][victim]["state"] == DEAD
            and injected["n"] > 0,
            f"victim shard {victim} ended {status['per_shard'][victim]['state']!r} "
            f"after {injected['n']} injected failure(s)",
        )
    )
    result.add(
        InvariantOutcome(
            "load-shed",
            out["rows_scored"] == total_rows,
            f"{out['rows_scored']}/{total_rows} rows scored with a shard "
            "dead — the front must shed, not drop",
        )
    )
    result.add(
        InvariantOutcome(
            "exactly-once-swap",
            swap_state["swapped"] and slot.version == 2,
            f"slot serves v{slot.version} (one swap, shared by all shards)",
        )
    )
    result.add(
        InvariantOutcome(
            "ladder-stays-warm",
            compiles_delta == 0,
            f"{compiles_delta} fused-flush executables compiled after the "
            "pre-warmed swap (must be 0 — the shards share the ladder)",
        )
    )
    result.add(
        p99_within(
            out["latencies_s"], out["baseline_p99_s"],
            factor=10.0, absolute_floor_s=0.25,
        )
    )
    return result


def scenario_replica_burst(
    seed: int = 2026, total_rows: int = 4096, n_shards: int = 4,
    drain_shard: int = 0,
) -> ScenarioResult:
    """Burst traffic across replica shards while one shard drains: p99
    holds through the drain, every row is scored, the drained shard's
    in-flight count empties, and the survivors share the load without a
    pathological skew (least-in-flight routing)."""
    from fraud_detection_tpu.mesh.front import DRAINING

    rm = build_model(seed=seed)
    wt = _watchtower(rm.profile)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true,
        arrivals=ArrivalProcess(rate_hz=4000.0, window_s=0.01),
    )
    fronts: list = []
    drained = {"ok": None, "rows_at_drain": None}

    def factory():
        front = _shard_front(rm, wt, n_shards, max_batch=256)
        fronts.append(front)
        return front

    def drain_mid(front) -> None:
        # snapshot BEFORE draining: the load-sharing invariant must hold
        # on post-drain deltas — cumulative totals would pass vacuously
        # on pre-drain traffic alone
        drained["rows_at_drain"] = [h.rows_total for h in front.shards]
        front.drain(drain_shard)
        drained["ok"] = front.wait_drained(drain_shard, timeout=15.0)

    result = ScenarioResult("replica_burst")
    try:
        out = _drive_bursts(
            factory, CampaignTraffic(spec), mid_stream=drain_mid
        )
    finally:
        wt.close()
    front = fronts[0]
    status = front.status()
    at_drain = drained["rows_at_drain"] or [0] * n_shards
    survivor_rows = [
        s["rows_total"] - at_drain[s["shard"]]
        for s in status["per_shard"]
        if s["shard"] != drain_shard
    ]
    result.metrics = {
        "rows": total_rows,
        "rows_scored": out["rows_scored"],
        "shards": n_shards,
        "drained_shard": drain_shard,
        "drained_state": status["per_shard"][drain_shard]["state"],
        "rows_per_shard": [s["rows_total"] for s in status["per_shard"]],
        "post_drain_rows_per_survivor": survivor_rows,
        "baseline_p99_ms": round(out["baseline_p99_s"] * 1e3, 3),
        "burst_p99_ms": round(
            float(np.percentile(out["latencies_s"], 99)) * 1e3, 3
        ),
    }
    result.add(
        InvariantOutcome(
            "drain-clean",
            drained["ok"] is True
            and status["per_shard"][drain_shard]["state"] == DRAINING
            and status["per_shard"][drain_shard]["inflight"] == 0,
            f"shard {drain_shard} drained to 0 in-flight "
            f"(state {status['per_shard'][drain_shard]['state']!r})",
        )
    )
    result.add(
        InvariantOutcome(
            "all-rows-scored",
            out["rows_scored"] == total_rows,
            f"{out['rows_scored']}/{total_rows} rows returned a score "
            "across the drain",
        )
    )
    result.add(
        InvariantOutcome(
            "survivors-share-load",
            drained["rows_at_drain"] is not None
            and all(r > 0 for r in survivor_rows),
            f"post-drain routed rows per survivor {survivor_rows} — every "
            "healthy shard must carry traffic AFTER the drain (deltas "
            "from the drain-time snapshot, not cumulative totals)",
        )
    )
    result.add(
        p99_within(
            out["latencies_s"], out["baseline_p99_s"],
            factor=10.0, absolute_floor_s=0.25,
        )
    )
    return result


def _drive_explain_burst(
    rm: RangeModel, seed: int, total_rows: int, n_shards: int,
    victim: int, explain_k: int,
) -> tuple[dict, dict, int]:
    """The shared explain-under-burst harness (lantern AND evergreen
    scenarios): an explain-on shard front over ``rm``'s scorer, warmed,
    driven with a Pareto burst, the victim shard killed mid-burst.
    Returns ``(out, front_status, failures_injected)``. A row counts as
    explained only when it carries k FINITE reason codes — ONE counting
    rule for every family, so a NaN-attribution regression fails whichever
    scenario serves it."""
    from fraud_detection_tpu.mesh.front import ShardFront
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    wt = _watchtower(rm.profile)
    spec = CampaignSpec(
        total_rows=total_rows, seed=seed, w_true=rm.w_true,
        arrivals=ArrivalProcess(rate_hz=4000.0, window_s=0.01),
    )
    kill_armed = {"on": False}
    injected = {"n": 0}
    fronts: list = []

    def shard_fault(shard=None, **_):
        if kill_armed["on"] and shard == victim:
            injected["n"] += 1
            raise RuntimeError("range: injected shard flush failure")

    def factory():
        front = ShardFront(
            [
                MicroBatcher(
                    scorer=rm.model.scorer, watchtower=wt,
                    max_batch=512, max_wait_ms=2.0, telemetry=False,
                    explain=True, explain_k=explain_k,
                )
                for _ in range(n_shards)
            ],
            max_consecutive_errors=3,
        )
        fronts.append(front)
        return front

    async def run() -> dict:
        front = factory()
        await front.start()
        try:
            traffic = CampaignTraffic(spec)
            warm = traffic.rng.standard_normal((64, D)).astype(np.float32)
            base_lat: list[float] = []
            for r in warm:
                t0 = time.perf_counter()
                s, reasons = await front.score_ex(r)
                base_lat.append(time.perf_counter() - t0)
                assert reasons is not None
            base_p99 = float(np.percentile(np.asarray(base_lat), 99))
            lat: list[float] = []
            n_scored = 0
            n_with_reasons = 0

            async def one(row) -> None:
                nonlocal n_scored, n_with_reasons
                t0 = time.perf_counter()
                s, reasons = await front.score_ex(row)
                lat.append(time.perf_counter() - t0)
                n_scored += 1
                if (
                    reasons is not None
                    and len(reasons[0]) == explain_k
                    and len(reasons[1]) == explain_k
                    and np.all(np.isfinite(np.asarray(reasons[1])))
                ):
                    n_with_reasons += 1

            batches = list(traffic.batches())
            fire_mid = len(batches) // 2
            for bi, batch in enumerate(batches):
                if bi == fire_mid:
                    kill_armed["on"] = True  # the victim dies under load
                await asyncio.gather(*(one(r) for r in batch.rows))
                await asyncio.sleep(traffic.spec.arrivals.window_s)
            return {
                "baseline_p99_s": base_p99,
                "latencies_s": lat,
                "rows_scored": n_scored,
                "rows_with_reasons": n_with_reasons,
            }
        finally:
            await front.stop()

    plan = faults.FaultPlan().call("mesh.shard_flush", shard_fault, times=-1)
    try:
        with plan.armed():
            out = asyncio.run(run())
    finally:
        wt.close()
    return out, fronts[0].status(), injected["n"]


def _explain_burst_result(
    name: str, out: dict, status: dict, injected_n: int,
    total_rows: int, n_shards: int, victim: int, explain_k: int,
) -> ScenarioResult:
    """Common metrics + invariants of the explain-under-burst scenarios:
    p99 within budget, every row scored, every row explained (k finite
    reason codes), victim shard dead-and-shed. Family-specific scenarios
    add their own metrics/invariants on top."""
    from fraud_detection_tpu.mesh.front import DEAD

    result = ScenarioResult(name)
    result.metrics = {
        "rows": total_rows,
        "rows_scored": out["rows_scored"],
        "rows_with_reasons": out["rows_with_reasons"],
        "explain_k": explain_k,
        "shards": n_shards,
        "victim": victim,
        "victim_state": status["per_shard"][victim]["state"],
        "failures_injected": injected_n,
        "baseline_p99_ms": round(out["baseline_p99_s"] * 1e3, 3),
        "burst_p99_ms": round(
            float(np.percentile(out["latencies_s"], 99)) * 1e3, 3
        ),
    }
    result.add(
        p99_within(
            out["latencies_s"], out["baseline_p99_s"],
            factor=10.0, absolute_floor_s=0.25,
        )
    )
    result.add(
        InvariantOutcome(
            "all-rows-scored",
            out["rows_scored"] == total_rows,
            f"{out['rows_scored']}/{total_rows} rows returned a score "
            "with the explain leg fused and a shard dying mid-burst",
        )
    )
    result.add(
        InvariantOutcome(
            "reasons-on-every-row",
            out["rows_with_reasons"] == total_rows,
            f"{out['rows_with_reasons']}/{total_rows} rows carried "
            f"{explain_k} finite reason codes — the contract is every "
            "scored row, including rows re-routed off the dead shard",
        )
    )
    result.add(
        InvariantOutcome(
            "shard-killed-and-shed",
            status["per_shard"][victim]["state"] == DEAD
            and injected_n > 0,
            f"victim shard {victim} ended "
            f"{status['per_shard'][victim]['state']!r} after "
            f"{injected_n} injected failure(s); load shed without "
            "dropping explain output",
        )
    )
    return result


def scenario_explain_under_burst(
    seed: int = 2026, total_rows: int = 4096, n_shards: int = 3,
    victim: int = 1, explain_k: int = 3,
) -> ScenarioResult:
    """Pareto burst with SCORER_EXPLAIN=topk on a shard front, a shard
    killed mid-burst: the p99 invariant holds with the explain leg fused
    into every flush, EVERY scored row carries its k reason codes (the
    lantern contract — explanations at flush latency, not minutes behind),
    and the mid-burst shard kill sheds load WITHOUT dropping the explain
    output (a re-routed row gets its reason codes from the surviving
    shard)."""
    out, status, injected_n = _drive_explain_burst(
        build_model(seed=seed), seed, total_rows, n_shards, victim, explain_k
    )
    return _explain_burst_result(
        "explain_under_burst", out, status, injected_n,
        total_rows, n_shards, victim, explain_k,
    )


def build_gbt_model(seed: int = 7, n_base: int = 2400) -> RangeModel:
    """Fit a small GBT champion on the same synthetic Kaggle-schema data —
    served on the int8 wire with the fused TreeSHAP explain leg (the
    evergreen stack: calibration derived from the training scaler before
    the bin-edge fold, exactly what train.py --model gbt stamps)."""
    from fraud_detection_tpu.models.gbt import FraudGBTModel
    from fraud_detection_tpu.monitor.baseline import build_baseline_profile
    from fraud_detection_tpu.ops.gbt import GBTConfig, gbt_fit
    from fraud_detection_tpu.ops.scaler import scaler_fit, scaler_transform

    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal(D).astype(np.float32)
    x, y = _make_rows(n_base, rng, w_true)
    scaler = scaler_fit(x)
    # fit on SCALED inputs — fold_scaler_into_gbt maps the bin edges back
    # to raw space at wrap time, so the served forest scores raw rows
    # identically to this fit (the train.py --model gbt pipeline)
    forest = gbt_fit(
        np.asarray(scaler_transform(scaler, x)), y.astype(np.float32),
        GBTConfig(n_trees=16, max_depth=3, n_bins=32),
    )
    model = FraudGBTModel(
        forest, KAGGLE, scaler=scaler, background=x[:64], io_dtype="int8"
    )
    scores = np.asarray(model.scorer.predict_proba(x[:1024]))
    profile = build_baseline_profile(x, scores, feature_names=KAGGLE)
    return RangeModel(model, profile, w_true, x, y)


def scenario_gbt_explain_under_burst(
    seed: int = 2027, total_rows: int = 4096, n_shards: int = 3,
    victim: int = 1, explain_k: int = 3,
) -> ScenarioResult:
    """The evergreen combo under fire: a GBT champion serving the int8
    wire with in-dispatch TreeSHAP reason codes, Pareto burst, a shard
    killed mid-burst. Same harness and invariants as
    ``explain_under_burst`` (shared ``_drive_explain_burst`` — the two
    scenarios cannot diverge), plus the evergreen exit criterion: BOTH
    fusion gauges hold 1 throughout, on the family that before evergreen
    loudly demoted out of both legs."""
    from fraud_detection_tpu.service import metrics as svc_metrics

    rm = build_gbt_model(seed=seed)
    assert rm.model.scorer.io_dtype == "int8", "evergreen serves int8"
    out, status, injected_n = _drive_explain_burst(
        rm, seed, total_rows, n_shards, victim, explain_k
    )
    result = _explain_burst_result(
        "gbt_explain_under_burst", out, status, injected_n,
        total_rows, n_shards, victim, explain_k,
    )
    explain_fused = svc_metrics.scorer_explain_fused._value.get()
    wire_fused = svc_metrics.scorer_wire_fused._value.get()
    result.metrics.update(
        wire=rm.model.scorer.io_dtype,
        explain_fused_gauge=float(explain_fused),
        wire_fused_gauge=float(wire_fused),
    )
    result.add(
        InvariantOutcome(
            "fused-end-to-end",
            explain_fused == 1 and wire_fused == 1,
            "scorer_explain_fused and scorer_wire_fused must BOTH hold 1 "
            "with a GBT champion on the int8 wire — the ROADMAP item-3 "
            "exit criterion (demotion can only be config error)",
        )
    )
    return result


def build_ledger_model(seed: int = 7, n_base: int = 2400):
    """A trained-for-real WIDENED champion (ledger velocity features
    replayed through the serving body) + its widened profile — the stack
    the stateful-feature scenarios serve."""
    import jax.numpy as jnp

    from fraud_detection_tpu.ledger import (
        LEDGER_FEATURE_NAMES,
        LedgerSpec,
        materialize_features,
    )
    from fraud_detection_tpu.models.logistic import FraudLogisticModel
    from fraud_detection_tpu.monitor.baseline import build_baseline_profile
    from fraud_detection_tpu.ops.logistic import logistic_fit_lbfgs
    from fraud_detection_tpu.ops.scaler import scaler_fit, scaler_transform

    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal(D).astype(np.float32)
    x, y = _make_rows(n_base, rng, w_true)
    x[:, -1] = np.abs(x[:, -1]) * 40.0  # a plausible Amount column
    spec0 = LedgerSpec(
        n_base=D, slots=1024, halflife_s=900.0, amount_col=-1,
        null_features=np.zeros(4, np.float32),
    )
    ents = [f"card-{i % 60}" for i in range(n_base)]
    ts = np.arange(1.0, n_base + 1.0, dtype=np.float32)
    feats, state = materialize_features(spec0, x, ents, ts)
    import dataclasses as _dc

    spec = _dc.replace(spec0, null_features=feats.mean(axis=0))
    xw = np.concatenate([x, feats], axis=1).astype(np.float32)
    scaler = scaler_fit(xw)
    params = logistic_fit_lbfgs(scaler_transform(scaler, xw), y, max_iter=100)
    names = KAGGLE + list(LEDGER_FEATURE_NAMES)
    model = FraudLogisticModel(
        params, scaler, names, ledger_spec=spec, ledger_state=state
    )
    scores = np.asarray(model.scorer.predict_proba(xw[:1024]))
    profile = build_baseline_profile(xw, scores, feature_names=names)
    del jnp
    return RangeModel(model, profile, w_true, x, y), spec, state, float(ts.max())


def scenario_poison_entity_state(
    seed: int = 2026, n_batches: int = 24, batch: int = 64,
) -> ScenarioResult:
    """One entity hammered with NaN/extreme amounts through the
    ``ledger.update`` injection point (a FraudRing-style mule account gone
    adversarial): the traced body's poison clamp must bound the victim
    slot's aggregates, every OTHER entity's aggregates must stay BITWISE
    untouched relative to a clean run, scores stay finite, and the flush
    latency holds.

    Determinism by construction: both runs drive the REAL micro-batcher
    flush body (``MicroBatcher._flush_device`` — staging, the injection
    point, the fused stateful dispatch) synchronously over identical fixed
    batches, so the only difference between them is the poison itself."""
    from fraud_detection_tpu.ledger.state import entity_fingerprint
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    rmodel, spec, state0, t0 = build_ledger_model(seed=seed)
    target_fp = entity_fingerprint("mule-1")
    rng = np.random.default_rng(seed)
    # the campaign: background entities + the hammered mule account
    batches = []
    t = t0 + 10.0
    for b in range(n_batches):
        rows = rng.standard_normal((batch, D)).astype(np.float32)
        rows[:, -1] = np.abs(rows[:, -1]) * 40.0
        ents = []
        for i in range(batch):
            if i % 4 == 0:
                ents.append("mule-1")  # 25% of traffic is the mule
            elif i % 11 == 0:
                ents.append(None)  # legacy rows ride the null slot
            else:
                ents.append(f"card-{int(rng.integers(0, 60))}")
        ts = np.asarray([t + i * 0.25 for i in range(batch)], np.float32)
        t += batch * 0.25
        batches.append((rows, ents, ts))

    poisoned: set[tuple[int, int]] = set()  # (batch_idx, row_idx)
    fire_count = {"b": 0}

    def poison(slot=None, batch=None, placement=None, **_):
        # alternate genuine NaN and absurd-magnitude amounts on a third of
        # the mule's staged rows (k-indexed over MULE rows, so both
        # branches are actually reachable — the mule sits at fixed batch
        # positions). The untouched mule rows then score off the CLAMPED
        # slot state, which is the containment the invariants pin.
        b = fire_count["b"]
        fire_count["b"] += 1
        k = 0
        for j in range(len(batch)):
            if slot.lf[j] == target_fp:
                if k % 3 == 0:
                    use_nan = (k // 3) % 2 == 1
                    slot.f32[j, -1] = np.nan if use_nan else 1e30
                    poisoned.add((b, j, "nan" if use_nan else "big"))
                k += 1

    def drive(armed_plan):
        rm, spec_, state_, _ = build_ledger_model(seed=seed)
        wt = _watchtower(rm.profile, halflife=50_000.0)
        wt.drift.bind_ledger(spec_, state_)
        mb = MicroBatcher(
            scorer=rm.model.scorer, watchtower=wt, telemetry=False,
            max_batch=batch,
        )
        scorer = rm.model.scorer
        tgt = mb._fused_target(scorer)
        lat: list[float] = []
        all_scores: list[float] = []
        try:
            for rows, ents, ts in batches:
                items = []
                for i in range(batch):
                    ent = None
                    if ents[i] is not None:
                        s, fp = spec_.row_keys(ents[i])
                        ent = (s, fp, float(ts[i]))
                    items.append((rows[i], None, None, ent))
                t_start = time.perf_counter()
                out = mb._flush_device(scorer, tgt, items, False)
                lat.append(time.perf_counter() - t_start)
                all_scores.extend(np.asarray(out[0], np.float64).tolist())
            snap = wt.drift.ledger_snapshot()
        finally:
            wt.close()
        return snap, lat, all_scores

    clean_snap, clean_lat, _ = drive(None)
    plan = faults.FaultPlan().call("ledger.update", poison)
    with plan.armed():
        poison_snap, poison_lat, poison_scores = drive(plan)

    result = ScenarioResult("poison_entity_state")
    from fraud_detection_tpu.ledger.state import entity_slot

    mule_slot = entity_slot(target_fp, spec.log2_slots)
    result.metrics = {
        "batches": n_batches,
        "poison_fired": plan.fired("ledger.update"),
        "mule_slot": mule_slot,
        "mule_count": float(poison_snap.count[mule_slot]),
        "mule_amount_sum": float(poison_snap.amount_sum[mule_slot]),
    }
    kinds = {kind for _, _, kind in poisoned}
    result.add(
        InvariantOutcome(
            "poison-injected",
            plan.fired("ledger.update") > 0 and kinds == {"nan", "big"},
            f"{plan.fired('ledger.update')} ledger.update firings, "
            f"{len(poisoned)} rows poisoned ({sorted(kinds)}) — both the "
            "NaN and extreme-amount branches must actually land",
        )
    )
    finite = all(
        bool(np.all(np.isfinite(np.asarray(leaf))))
        for leaf in (
            poison_snap.count, poison_snap.amount_sum,
            poison_snap.amount_sumsq, poison_snap.last_ts,
        )
    )
    from fraud_detection_tpu.ledger.state import AMOUNT_CLIP

    bounded = abs(float(poison_snap.amount_sum[mule_slot])) <= (
        AMOUNT_CLIP * max(float(poison_snap.count[mule_slot]), 1.0) + 1.0
    )
    result.add(
        InvariantOutcome(
            "poison-guard-clamps",
            finite and bounded,
            "victim slot stayed finite and clamp-bounded"
            if finite and bounded
            else f"finite={finite} bounded={bounded} "
            f"sum={float(poison_snap.amount_sum[mule_slot])}",
        )
    )
    # every slot EXCEPT the mule's must be bitwise the clean run's
    others_ok = True
    detail = "all non-victim slots bitwise identical to the clean run"
    for name in ("count", "amount_sum", "amount_sumsq", "last_ts"):
        a = np.asarray(getattr(clean_snap, name)).copy()
        b = np.asarray(getattr(poison_snap, name)).copy()
        a[mule_slot] = 0
        b[mule_slot] = 0
        if a.tobytes() != b.tobytes():
            others_ok = False
            n_diff = int(np.sum(a != b))
            detail = f"{name}: {n_diff} non-victim slots differ"
            break
    result.add(InvariantOutcome("other-entities-unaffected", others_ok, detail))
    # a poisoned row's OWN score may be NaN (its staged feature is NaN —
    # request-input garbage, the service edge's concern); the containment
    # claim is that every NON-poisoned row — including the mule's clean
    # rows, which score off the clamped slot state — stays finite
    flat_poisoned = {b * batch + j for b, j, _ in poisoned}
    clean = [
        s for i, s in enumerate(poison_scores) if i not in flat_poisoned
    ]
    result.add(
        InvariantOutcome(
            "scores-finite",
            bool(np.all(np.isfinite(np.asarray(clean)))),
            f"all {len(clean)} non-poisoned rows' scores finite (incl. the "
            "mule's clean rows scoring off the clamped slot)",
        )
    )
    base_p99 = float(np.percentile(np.asarray(clean_lat), 99))
    result.add(p99_within(poison_lat, base_p99, factor=5.0))
    return result


def scenario_ingest_storm(
    seed: int = 2026, n_frames: int = 48, frame_rows: int = 64,
    admit_max: int = 192,
) -> ScenarioResult:
    """Open-loop Pareto-burst frames on the REAL binary ingest lane
    (sockets, not the in-process shortcut) against a 2-shard front, with a
    mid-burst shard drain (hyperloop, ISSUE 11). Invariants:

    - **sheds-bounded**: the bounded admission queue sheds with busy
      frames carrying a Retry-After hint (the binary twin of HTTP 429) and
      the queued-row count never exceeds the bound — overload backs off,
      it never grows an unbounded queue (never OOM);
    - **all-admitted-answered**: every frame the lane ACCEPTED returned
      exactly its row count of finite scores, through the drain;
    - **drain-clean + survivor-carries**: the drained shard empties, the
      survivor keeps scoring;
    - **p99-holds**: accepted-frame p99 stays within budget of the quiet
      baseline (never unbounded p99);
    - **bitwise-consistent**: a single-shard open-loop socket run's drift
      window bitwise-matches a closed-loop replay of the same rows in the
      same flush groupings (continuous batching changes WHEN rows flush,
      never what the monitor sees).
    """
    import asyncio as aio
    import threading

    from fraud_detection_tpu.mesh.front import DRAINING, ShardFront
    from fraud_detection_tpu.service.binlane import (
        BinaryIngestServer,
        BinLaneClient,
        LaneBusy,
    )
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    rm = build_model(seed=seed)
    rng = np.random.default_rng(seed)
    # Pareto-burst frame sizes: heavy-tailed like ArrivalProcess, clamped
    # to the frame ceiling
    sizes = np.clip(
        (frame_rows * (1.0 + rng.pareto(2.5, n_frames))).astype(int),
        8, 2 * frame_rows,
    )
    frames = [
        rng.standard_normal((int(k), D)).astype(np.float32) for k in sizes
    ]

    def loop_thread():
        loop = aio.new_event_loop()
        t = threading.Thread(
            target=lambda: (aio.set_event_loop(loop), loop.run_forever()),
            daemon=True,
        )
        t.start()
        return loop, t

    def run_on(loop, coro):
        return aio.run_coroutine_threadsafe(coro, loop).result(60.0)

    result = ScenarioResult("ingest_storm")

    # -- phase A: overload + shed + mid-burst drain on a 2-shard front ----
    wt = _watchtower(rm.profile)
    batchers = [
        MicroBatcher(
            scorer=rm.model.scorer, watchtower=wt, telemetry=False,
            max_batch=128, max_wait_ms=20.0, admit_max_rows=admit_max,
        )
        for _ in range(2)
    ]
    front = ShardFront(batchers)
    loop, _t = loop_thread()
    srv = None
    try:
        run_on(loop, front.start())
        srv = BinaryIngestServer(
            front, scorer_fn=lambda: rm.model.scorer,
            host="127.0.0.1", port=0, max_rows=128,
        )
        srv.start(loop)

        # quiet baseline: sequential lone frames
        base_lat: list[float] = []
        with BinLaneClient("127.0.0.1", srv.port) as cli:
            for f in frames[:6]:
                t0 = time.perf_counter()
                cli.score_batch(f[:64])
                base_lat.append(time.perf_counter() - t0)
        base_p99 = float(np.percentile(np.asarray(base_lat), 99))

        # open-loop burst: 4 connections drain a shared frame queue at max
        # rate (no response pacing across the fleet), splitting oversized
        # frames to the lane ceiling
        work: list[np.ndarray] = []
        for f in frames:
            for lo in range(0, f.shape[0], 128):
                work.append(f[lo:lo + 128])
        qlock = threading.Lock()
        stats = {
            "answered_rows": 0, "accepted_rows": 0, "accepted": 0,
            "shed": 0, "retry_hints": [], "errors": 0, "lat": [],
        }
        queue_peaks: list[int] = []

        def sample_queues(stop_evt):
            while not stop_evt.is_set():
                queue_peaks.append(
                    max(b._queued_rows for b in batchers)
                )
                time.sleep(0.002)

        def client_worker():
            with BinLaneClient("127.0.0.1", srv.port) as c:
                while True:
                    with qlock:
                        if not work:
                            return
                        f = work.pop()
                    t0 = time.perf_counter()
                    try:
                        scores, _ = c.score_batch(f)
                        ok = (
                            scores.shape[0] == f.shape[0]
                            and bool(np.all(np.isfinite(scores)))
                        )
                        with qlock:
                            stats["accepted"] += 1
                            stats["accepted_rows"] += f.shape[0]
                            stats["lat"].append(time.perf_counter() - t0)
                            if ok:
                                stats["answered_rows"] += f.shape[0]
                    except LaneBusy as e:
                        with qlock:
                            stats["shed"] += 1
                            stats["retry_hints"].append(e.retry_after_s)
                    except Exception:  # graftcheck: ignore[silent-except] — counted into stats["errors"], asserted 0 by the all-admitted-answered invariant
                        with qlock:
                            stats["errors"] += 1

        stop_evt = threading.Event()
        sampler = threading.Thread(
            target=sample_queues, args=(stop_evt,), daemon=True
        )
        sampler.start()
        threads = [
            threading.Thread(target=client_worker, daemon=True)
            for _ in range(4)
        ]
        n_before_drain = len(work) // 2
        for th in threads:
            th.start()
        # mid-burst drain: wait until roughly half the work is consumed
        while True:
            with qlock:
                if len(work) <= n_before_drain:
                    break
            time.sleep(0.002)
        rows_at_drain = [h.rows_total for h in front.shards]
        front.drain(0)
        drained = front.wait_drained(0, timeout=20.0)
        for th in threads:
            th.join(timeout=60.0)
        stop_evt.set()
        sampler.join(timeout=5.0)
    finally:
        if srv is not None:
            srv.stop()
        run_on(loop, front.stop())
        wt.close()
        loop.call_soon_threadsafe(loop.stop)

    survivor_delta = front.shards[1].rows_total - rows_at_drain[1]
    peak = max(queue_peaks) if queue_peaks else 0
    result.metrics = {
        "frames_offered": len(stats["lat"]) + stats["shed"] + stats["errors"],
        "frames_accepted": stats["accepted"],
        "frames_shed": stats["shed"],
        "errors": stats["errors"],
        "answered_rows": stats["answered_rows"],
        "admit_max_rows": admit_max,
        "peak_queued_rows": peak,
        "drained_shard_state": front.shards[0].state,
        "survivor_rows_post_drain": survivor_delta,
        "baseline_p99_ms": round(base_p99 * 1e3, 3),
        "burst_p99_ms": round(
            float(np.percentile(stats["lat"], 99)) * 1e3, 3
        ) if stats["lat"] else None,
    }
    result.add(
        InvariantOutcome(
            "sheds-bounded",
            stats["shed"] > 0
            and all(r > 0 for r in stats["retry_hints"])
            and peak <= admit_max,
            f"{stats['shed']} frames shed with Retry-After hints "
            f"{sorted(set(stats['retry_hints']))}, peak queue {peak} ≤ "
            f"bound {admit_max} — overload backs off, the queue never "
            "grows unbounded",
        )
    )
    result.add(
        InvariantOutcome(
            "all-admitted-answered",
            stats["errors"] == 0
            and stats["answered_rows"] > 0
            and stats["answered_rows"] == stats["accepted_rows"],
            f"{stats['accepted']} accepted frames returned "
            f"{stats['answered_rows']}/{stats['accepted_rows']} admitted "
            f"rows as finite scores; {stats['errors']} hard errors",
        )
    )
    result.add(
        InvariantOutcome(
            "drain-clean",
            drained and front.shards[0].state == DRAINING
            and front.shards[0].inflight == 0
            and survivor_delta > 0,
            f"shard 0 drained to 0 in-flight (state "
            f"{front.shards[0].state!r}); survivor scored {survivor_delta} "
            "rows post-drain",
        )
    )
    result.add(
        p99_within(
            stats["lat"], base_p99, factor=10.0, absolute_floor_s=0.5
        )
    )

    # -- phase B: open-loop socket run vs closed-loop replay, bitwise -----
    flush_snapshots: list[np.ndarray] = []

    class RecordingBatcher(MicroBatcher):
        async def _flush(self, batch):
            rows = np.concatenate(
                [np.atleast_2d(item[0]) for item in batch]
            ).copy()
            flush_snapshots.append(rows)
            return await super()._flush(batch)

    def window_of(driver) -> object:
        wt2 = _watchtower(rm.profile, halflife=50_000.0)
        loop2, _t2 = loop_thread()
        try:
            win = driver(wt2, loop2)
        finally:
            wt2.close()
            loop2.call_soon_threadsafe(loop2.stop)
        return win

    def open_loop(wt2, loop2):
        # max_inflight=1 serializes the window folds into snapshot order:
        # with pipelined flushes the donated window chains in DISPATCH
        # order, which executor-thread timing can reorder relative to the
        # collection order the snapshots record — the determinism claim
        # under test is about batching GROUPS, not pipeline overlap
        mb = RecordingBatcher(
            scorer=rm.model.scorer, watchtower=wt2, telemetry=False,
            max_batch=128, max_wait_ms=5.0, max_inflight=1,
        )
        run_on(loop2, mb.start())
        srv2 = BinaryIngestServer(
            mb, scorer_fn=lambda: rm.model.scorer,
            host="127.0.0.1", port=0, max_rows=128,
        )
        srv2.start(loop2)
        try:
            def send(sub):
                with BinLaneClient("127.0.0.1", srv2.port) as c:
                    for f in sub:
                        c.score_batch(f[:128])

            parts = [frames[0::3], frames[1::3], frames[2::3]]
            ths = [
                threading.Thread(target=send, args=(p,), daemon=True)
                for p in parts
            ]
            for th in ths:
                th.start()
            for th in ths:
                th.join(timeout=60.0)
        finally:
            srv2.stop()
            run_on(loop2, mb.stop())
        return wt2.drift.window

    win_a = window_of(open_loop)

    def closed_loop(wt2, loop2):
        from fraud_detection_tpu.ops.scorer import _bucket
        from fraud_detection_tpu.service.microbatch import IngestBlock

        mb = MicroBatcher(
            scorer=rm.model.scorer, watchtower=wt2, telemetry=False,
            max_batch=128, max_wait_ms=0.0,
        )
        run_on(loop2, mb.start())
        scorer = rm.model.scorer
        try:
            async def replay(rows):
                slot = scorer.staging.acquire(
                    _bucket(rows.shape[0], scorer.min_bucket)
                )
                try:
                    slot.f32[: rows.shape[0]] = rows
                    await mb.score_block(IngestBlock(slot, rows.shape[0]))
                finally:
                    scorer.staging.release(slot)

            for rows in flush_snapshots:
                run_on(loop2, replay(rows))
        finally:
            run_on(loop2, mb.stop())
        return wt2.drift.window

    win_b = window_of(closed_loop)
    result.metrics["flushes_replayed"] = len(flush_snapshots)
    result.add(windows_bitwise_equal(win_a, win_b))
    return result


# -- registry ----------------------------------------------------------------

def scenario_slo_burn_under_shed(seed: int = 2033) -> ScenarioResult:
    """Panopticon: a Pareto burst drives the bounded admission queue into
    sheds; the fleet SLO engine's fast-burn condition fires within its
    shortest window, the error-budget gauge drops, and — after recovery
    traffic drains the windows — the condition clears WITHOUT flapping.

    The engine runs with compressed windows (real thresholds, shorter
    spans) so the whole burn/recover cycle fits a chaos budget; the
    admission path, shed exceptions, and recording sites are the REAL
    serving ones (MicroBatcher._admit → AdmissionFull → the lane-edge
    record), not a simulation of them.
    """
    from fraud_detection_tpu.service.microbatch import (
        AdmissionFull,
        MicroBatcher,
    )
    from fraud_detection_tpu.telemetry.slo import SLOEngine

    rng = np.random.default_rng(seed)
    rm = build_model(seed=seed)
    # compressed multi-window ladder: same 1:12:72 shape as 5m/1h/6h
    windows = {"5m": 0.5, "1h": 2.0, "6h": 6.0}
    eng = SLOEngine(windows=windows, bucket_s=0.05)
    eng.declare_lanes(("json",))

    class _SlowScorer:
        """Legacy-protocol scorer with a per-flush stall: the drain rate
        the burst must outrun to hit the admission bound (warmup/min_bucket
        delegate; no staging protocol → the batcher's legacy flush path)."""

        def __init__(self, inner, delay_s: float):
            self.inner = inner
            self.delay_s = delay_s
            self.min_bucket = inner.min_bucket

        def warmup(self, top):
            self.inner.warmup(top)

        def predict_proba(self, rows):
            time.sleep(self.delay_s)
            return self.inner.predict_proba(rows)

    flap = AlertFlapDetector(min_hold_samples=3)
    arrivals = ArrivalProcess(rate_hz=3000.0, window_s=0.01)

    async def run() -> dict:
        batcher = MicroBatcher(
            scorer=_SlowScorer(rm.model.scorer, 0.02),
            max_batch=8, max_wait_ms=1.0, max_inflight=1,
            telemetry=False, admit_max_rows=8,
        )
        await batcher.start()
        out: dict = {"sheds": 0, "scored": 0, "non_finite": 0}
        try:
            # phase 1 — healthy floor: sequential singles, all good
            for r in rng.standard_normal((24, D)).astype(np.float32):
                t0 = time.perf_counter()
                s = await batcher.score(r)
                eng.record("json", True, time.perf_counter() - t0)
                if not np.isfinite(s):
                    out["non_finite"] += 1
            out["budget_before"] = eng.snapshot()[
                "availability:json"]["budget_remaining"]
            out["fast_before"] = eng.fast_burn("json")

            # phase 2 — Pareto burst: concurrent waves sized off the
            # arrival process, far over the admission bound → sheds
            first_shed_t: float | None = None
            first_fast_t: float | None = None
            # ten Pareto-burst waves, each offered concurrently — far
            # over the 8-row admission bound, so the tail of every wave
            # sheds exactly as a saturated open-loop client would see
            waves = [
                max(24, n) for n in arrivals.batch_sizes(480, rng)
            ][:10]
            for wave_n in waves:
                rows = rng.standard_normal((wave_n, D)).astype(np.float32)

                async def one(r):
                    t0 = time.perf_counter()
                    try:
                        s = await batcher.score(r)
                    except AdmissionFull:
                        eng.record("json", False)
                        return None
                    eng.record("json", True, time.perf_counter() - t0)
                    return s

                scores = await asyncio.gather(*(one(r) for r in rows))
                shed = sum(1 for s in scores if s is None)
                out["sheds"] += shed
                out["scored"] += sum(1 for s in scores if s is not None)
                out["non_finite"] += sum(
                    1 for s in scores
                    if s is not None and not np.isfinite(s)
                )
                now = time.monotonic()
                if shed and first_shed_t is None:
                    first_shed_t = now
                fast = eng.fast_burn("json")
                flap.sample(slo_fast_burn=fast)
                if fast and first_fast_t is None:
                    first_fast_t = now
            out["budget_after_burst"] = eng.snapshot()[
                "availability:json"]["budget_remaining"]
            out["first_shed_t"] = first_shed_t
            out["first_fast_t"] = first_fast_t

            # phase 3 — recovery: light good traffic until the longest
            # window drains; the condition must clear and stay clear
            t_end = time.monotonic() + windows["6h"] + 1.0
            cleared_samples = 0
            while time.monotonic() < t_end:
                r = rng.standard_normal(D).astype(np.float32)
                t0 = time.perf_counter()
                s = await batcher.score(r)
                eng.record("json", True, time.perf_counter() - t0)
                if not np.isfinite(s):
                    out["non_finite"] += 1
                fast = eng.fast_burn("json")
                flap.sample(slo_fast_burn=fast)
                if not fast:
                    cleared_samples += 1
                await asyncio.sleep(0.1)
            out["fast_after_recovery"] = eng.fast_burn("json")
            out["cleared_samples"] = cleared_samples
            out["budget_after_recovery"] = eng.snapshot()[
                "availability:json"]["budget_remaining"]
            return out
        finally:
            await batcher.stop()

    out = asyncio.run(run())
    result = ScenarioResult("slo_burn_under_shed")
    result.metrics = {
        "sheds": out["sheds"],
        "scored": out["scored"],
        "budget_before": out["budget_before"],
        "budget_after_burst": out["budget_after_burst"],
        "budget_after_recovery": out["budget_after_recovery"],
    }
    result.add(InvariantOutcome(
        "burst-drives-sheds",
        out["sheds"] > 0 and out["scored"] > 0,
        f"{out['sheds']} sheds, {out['scored']} scored — the burst must "
        "genuinely hit the admission bound while traffic still flows",
    ))
    result.add(InvariantOutcome(
        "scores-finite",
        out["non_finite"] == 0,
        f"{out['non_finite']} non-finite scores among admitted rows",
    ))
    result.add(InvariantOutcome(
        "fast-burn-fires-within-window",
        out["first_shed_t"] is not None
        and out["first_fast_t"] is not None
        and out["first_fast_t"] - out["first_shed_t"]
        <= windows["5m"] + 1.0,
        "fast burn fired "
        + (
            f"{out['first_fast_t'] - out['first_shed_t']:.2f}s after the "
            f"first shed (window {windows['5m']}s)"
            if out["first_fast_t"] is not None
            and out["first_shed_t"] is not None
            else "never"
        ),
    ))
    result.add(InvariantOutcome(
        "budget-drops-under-burn",
        out["budget_after_burst"] < out["budget_before"],
        f"budget {out['budget_before']} -> {out['budget_after_burst']} "
        "across the burst",
    ))
    result.add(InvariantOutcome(
        "burn-clears-after-recovery",
        not out["fast_after_recovery"] and out["cleared_samples"] > 0,
        "fast-burn condition "
        + ("cleared" if not out["fast_after_recovery"] else "still firing")
        + f" after recovery ({out['cleared_samples']} clear samples)",
    ))
    result.add(flap.check())
    return result


# -- lifeboat scenarios ------------------------------------------------------

def _entity_batches(seed: int, n_batches: int, batch: int, t0: float):
    """Seeded entity-bearing traffic: rows + entity ids + strictly
    increasing timestamps, bitwise-identical across drives (the recovery
    parity invariants compare runs fed from this)."""
    rng = np.random.default_rng(seed + 77)
    batches = []
    t = t0 + 10.0
    for _b in range(n_batches):
        rows = rng.standard_normal((batch, D)).astype(np.float32)
        rows[:, -1] = np.abs(rows[:, -1]) * 40.0
        ents: list[str | None] = []
        for i in range(batch):
            if i % 9 == 0:
                ents.append(None)  # legacy rows ride the null slot
            else:
                ents.append(f"card-{int(rng.integers(0, 60))}")
        ts = np.asarray([t + i * 0.25 for i in range(batch)], np.float32)
        t += batch * 0.25
        batches.append((rows, ents, ts))
    return batches


def _drive_ledger_batches(mb, scorer, spec, batches, tables_out=None):
    """Push batches synchronously through the REAL flush body
    (``MicroBatcher._flush_device`` — staging, the lifeboat journal hook,
    the fused stateful dispatch); optionally capture the host table after
    every batch. Returns the scores."""
    tgt = mb._fused_target(scorer)
    scores: list[float] = []
    for rows, ents, ts in batches:
        items = []
        for i in range(rows.shape[0]):
            ent = None
            if ents[i] is not None:
                s, fp = spec.row_keys(ents[i])
                ent = (s, fp, float(ts[i]))
            items.append((rows[i], None, None, ent))
        out = mb._flush_device(scorer, tgt, items, False)
        scores.extend(np.asarray(out[0], np.float64).tolist())
        if tables_out is not None:
            tables_out.append(mb.watchtower.drift.ledger_snapshot())
    return scores


def _tables_equal(a, b) -> tuple[bool, str]:
    """Bitwise comparison over every LedgerState leaf."""
    if a is None or b is None:
        return False, "missing table"
    for name in ("acc", "last_ts", "fingerprint", "collisions", "evictions"):
        av = np.asarray(getattr(a, name))
        bv = np.asarray(getattr(b, name))
        if av.tobytes() != bv.tobytes():
            n_diff = int(np.sum(av != bv))
            return False, f"{name}: {n_diff} element(s) differ"
    return True, "bitwise equal on every leaf"


def scenario_crash_warm_restart(
    tmpdir: str, seed: int = 2026, n_batches: int = 12, batch: int = 64,
    snapshot_after: int = 6,
) -> ScenarioResult:
    """Kill the serving process mid-flush under live entity-bearing
    traffic, then warm-restart from the lifeboat's snapshot + journal.

    The kill lands at the ``lifeboat.journal`` injection point — AFTER the
    flush's entity triples are durably journaled (fsync-per-append here),
    BEFORE the fused dispatch folds them into the device table — the
    journal-ahead window a real SIGKILL can always hit. Invariants:

    - **recovery parity (bitwise)**: the warm-restarted table equals an
      independent replay of the same snapshot + journal bytes AND equals a
      clean uninterrupted drive over the identical traffic (the journaled
      kill-flush replays; nothing is lost, nothing is double-folded);
    - **two restarts agree**: a second, fully independent process (the
      REAL service app pointed at the same directory) recovers the SAME
      bytes to the SAME table — recovery is deterministic, not merely
      close;
    - **readiness gate**: while the app's recovery is in flight, /health
      and /predict answer 503 with Retry-After, then /health flips to 200
      once the replay binds (``recovering → ready``);
    - **zero unexpected compiles**: post-recovery scoring reuses the
      warmed fused executables — the recovered table binds with the same
      shapes/dtypes, so the compile-cache delta is 0.
    """
    import shutil
    import threading

    from fraud_detection_tpu.lifeboat import (
        Lifeboat,
        load_latest,
        read_tail,
        replay_records,
    )
    from fraud_detection_tpu.monitor import drift as drift_mod
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    lbdir = os.path.join(tmpdir, "lifeboat")
    result = ScenarioResult("crash_warm_restart")

    # -- clean reference: the same traffic, no lifeboat, no crash ----------
    rm_ref, spec_ref, state_ref, t0 = build_ledger_model(seed=seed)
    batches = _entity_batches(seed, n_batches, batch, t0)
    wt_ref = _watchtower(rm_ref.profile, halflife=50_000.0)
    wt_ref.drift.bind_ledger(spec_ref, state_ref)
    mb_ref = MicroBatcher(
        scorer=rm_ref.model.scorer, watchtower=wt_ref, telemetry=False,
        max_batch=batch,
    )
    ref_tables: list = []
    try:
        _drive_ledger_batches(
            mb_ref, rm_ref.model.scorer, spec_ref, batches, ref_tables
        )
    finally:
        wt_ref.close()

    # -- the crashing serve ------------------------------------------------
    rm, spec, state0, _ = build_ledger_model(seed=seed)
    wt = _watchtower(rm.profile, halflife=50_000.0)
    wt.drift.bind_ledger(spec, state0)
    boat = Lifeboat(
        lbdir, spec, drift=wt.drift, snapshot_s=1e9, fsync_s=0.0,
    )
    boat.recover()  # fresh directory: opens the journal, state -> ready
    mb = MicroBatcher(
        scorer=rm.model.scorer, watchtower=wt, telemetry=False,
        max_batch=batch, lifeboat=boat,
    )
    killed = False
    plan = faults.FaultPlan().kill("lifeboat.journal")
    try:
        _drive_ledger_batches(
            mb, rm.model.scorer, spec, batches[:snapshot_after]
        )
        boat.take_snapshot()
        _drive_ledger_batches(
            mb, rm.model.scorer, spec, batches[snapshot_after:-1]
        )
        with plan.armed():
            try:
                _drive_ledger_batches(
                    mb, rm.model.scorer, spec, batches[-1:]
                )
            except faults.ReplicaKilled:
                killed = True  # the crash: nothing closes cleanly
    finally:
        wt.close()
    result.add(
        InvariantOutcome(
            "killed-mid-flush",
            killed and plan.fired("lifeboat.journal") == 1,
            "ReplicaKilled after the journal append, before the dispatch",
        )
    )

    # -- warm restart (library level, on a copy of the bytes) --------------
    lbdir_b = os.path.join(tmpdir, "lifeboat-restart")
    shutil.copytree(lbdir, lbdir_b)
    rm2, spec2, state02, _ = build_ledger_model(seed=seed)
    wt2 = _watchtower(rm2.profile, halflife=50_000.0)
    wt2.drift.bind_ledger(spec2, state02)
    boat2 = Lifeboat(
        lbdir_b, spec2, drift=wt2.drift, snapshot_s=1e9, fsync_s=0.0,
    )
    mb2 = MicroBatcher(
        scorer=rm2.model.scorer, watchtower=wt2, telemetry=False,
        max_batch=batch, lifeboat=boat2,
    )
    try:
        # startup warmup with the train-time stamp — the ladder is warm
        # BEFORE recovery binds, exactly the app's startup order
        _drive_ledger_batches(
            mb2, rm2.model.scorer, spec2,
            _entity_batches(seed + 1, 1, batch, t0),
        )
        compiles_before = drift_mod._fused_flush._cache_size()
        rep = boat2.recover()
        recovered = wt2.drift.ledger_snapshot()

        # independent replay of the same disk bytes — no Lifeboat wiring
        snap, _skipped = load_latest(lbdir_b)
        tail = read_tail(lbdir_b, snap.seq if snap else 0)
        manual = replay_records(
            spec2, snap.ledger if snap else None, tail.records
        )
        ok_manual, detail_manual = _tables_equal(rep.state, manual)
        ok_ref, detail_ref = _tables_equal(recovered, ref_tables[-1])

        # post-recovery serving: finite scores, zero new executables,
        # journaling resumed past the recovered sequence number
        seq_at_recovery = boat2.journal.seq
        post_scores = _drive_ledger_batches(
            mb2, rm2.model.scorer, spec2,
            _entity_batches(
                seed + 2, 2, batch, t0 + (n_batches + 2) * batch * 0.25
            ),
        )
        compiles_delta = (
            drift_mod._fused_flush._cache_size() - compiles_before
        )
        journal_resumed = boat2.journal.seq == seq_at_recovery + 2
    finally:
        wt2.close()
        boat2.close()

    result.metrics = {
        "batches": n_batches,
        "snapshot_seq": rep.snapshot_seq,
        "replayed_rows": rep.replayed_rows,
        "torn_rows": rep.torn_rows,
        "recovery_duration_s": round(rep.duration_s, 4),
        "post_recovery_compiles": compiles_delta,
    }
    result.add(
        InvariantOutcome(
            "recovery-parity-vs-journal-bytes",
            rep.restored and ok_manual,
            "recovered table bitwise-equals an independent replay of the "
            f"same snapshot+journal bytes ({detail_manual})",
        )
    )
    result.add(
        InvariantOutcome(
            "recovery-parity-vs-clean-run",
            ok_ref,
            "recovered table bitwise-equals the uninterrupted clean drive "
            f"over identical traffic ({detail_ref})",
        )
    )
    result.add(
        InvariantOutcome(
            "kill-flush-replayed",
            rep.replayed_rows > 0 and rep.snapshot_seq == snapshot_after,
            f"{rep.replayed_rows} journaled rows past snapshot seq "
            f"{rep.snapshot_seq} replayed (incl. the killed flush)",
        )
    )
    result.add(
        InvariantOutcome(
            "no-recompile-storm",
            compiles_delta == 0,
            f"{compiles_delta} fused-flush executables compiled after "
            "recovery bound the restored table (must be 0)",
        )
    )
    result.add(
        InvariantOutcome(
            "post-recovery-scores-finite",
            bool(np.all(np.isfinite(np.asarray(post_scores))))
            and journal_resumed,
            f"{len(post_scores)} post-recovery rows scored finite, journal "
            "sequence resumed past the recovered point",
        )
    )

    # -- the REAL service edge: a second independent restart of the same
    # bytes, with the readiness gate observed through /health + /predict --
    from fraud_detection_tpu.monitor.baseline import save_profile
    from fraud_detection_tpu.service.app import create_app
    from fraud_detection_tpu.service.http import TestClient

    model_dir = os.path.join(tmpdir, "models")
    rm.model.save(model_dir, joblib_too=False)
    save_profile(model_dir, rm.profile)
    env_keys = {
        "MODEL_PATH": os.path.join(model_dir, "logistic_model.joblib"),
        "LIFEBOAT_DIR": lbdir,
        "MLFLOW_TRACKING_URI": f"file:{os.path.join(tmpdir, 'mlruns')}",
    }
    saved_env = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    gate = threading.Event()
    app_plan = faults.FaultPlan().call(
        "lifeboat.recover", lambda **ctx: gate.wait(timeout=60.0), times=1
    )
    client = None
    try:
        with app_plan.armed():
            app = create_app(
                database_url=f"sqlite:///{tmpdir}/fraud.db",
                broker_url=f"sqlite:///{tmpdir}/taskq.db",
            )
            client = TestClient(app)
            r_health = client.get("/health")
            r_predict = client.post(
                "/predict", json={"features": [0.1] * D}
            )
            gate.set()
            deadline = time.time() + 60.0
            r_ready = r_health
            while time.time() < deadline:
                r_ready = client.get("/health")
                if r_ready.status_code == 200:
                    break
                time.sleep(0.05)
        status = client.get("/lifeboat/status").json()
        app_table = app.state["watchtower"].drift.ledger_snapshot()
    finally:
        if client is not None:
            client.close()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    retry_after = {k.lower(): v for k, v in r_health.headers.items()}.get(
        "retry-after"
    )
    result.add(
        InvariantOutcome(
            "readiness-503-while-recovering",
            r_health.status_code == 503
            and retry_after is not None
            and float(retry_after) > 0
            and r_predict.status_code == 503
            and r_health.json().get("error") == "recovering",
            f"/health={r_health.status_code} (Retry-After={retry_after}), "
            f"/predict={r_predict.status_code} during replay",
        )
    )
    result.add(
        InvariantOutcome(
            "readiness-flips-ready",
            r_ready.status_code == 200
            and status.get("state") == "ready"
            and (status.get("last_recovery") or {}).get("restored") is True,
            f"/health flipped to {r_ready.status_code}, lifeboat state "
            f"{status.get('state')} after replaying "
            f"{(status.get('last_recovery') or {}).get('replayed_rows')} rows",
        )
    )
    ok_app, detail_app = _tables_equal(app_table, rep.state)
    result.add(
        InvariantOutcome(
            "independent-restarts-agree",
            ok_app,
            "the app's recovered table bitwise-equals the library "
            f"restart of the same bytes ({detail_app})",
        )
    )
    return result


def scenario_kill_mid_snapshot(
    tmpdir: str, seed: int = 2027, n_batches: int = 10, batch: int = 64,
    snapshot_after: int = 4,
) -> ScenarioResult:
    """Kill the snapshotter between the journal rotation and the
    generation file landing (the ``lifeboat.snapshot`` injection point),
    then fabricate a TORN newest generation on top — the two disk shapes a
    crash mid-snapshot can leave. Invariants:

    - **previous generation loads**: recovery skips exactly the torn file
      (``generations_skipped == 1``) and restores from the last good
      generation;
    - **nothing lost**: the journal was rotated AT the captured sequence
      number and synced before the kill, so replay lands the full table —
      bitwise equal to a clean uninterrupted drive;
    - **torn journal tail**: truncating the final journal record drops
      exactly that flush — CRC-skip, the loss counted on
      ``lifeboat_torn_tail_rows_total``, and the recovered table bitwise
      equals the clean drive one flush back (loss is bounded AND
      accounted, never silent corruption).
    """
    from fraud_detection_tpu import lifeboat as lb
    from fraud_detection_tpu.lifeboat import Lifeboat
    from fraud_detection_tpu.service import metrics as svc_metrics
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    lbdir = os.path.join(tmpdir, "lifeboat")
    result = ScenarioResult("kill_mid_snapshot")

    # clean reference with the table captured after every batch
    rm_ref, spec_ref, state_ref, t0 = build_ledger_model(seed=seed)
    batches = _entity_batches(seed, n_batches, batch, t0)
    wt_ref = _watchtower(rm_ref.profile, halflife=50_000.0)
    wt_ref.drift.bind_ledger(spec_ref, state_ref)
    mb_ref = MicroBatcher(
        scorer=rm_ref.model.scorer, watchtower=wt_ref, telemetry=False,
        max_batch=batch,
    )
    ref_tables: list = []
    try:
        _drive_ledger_batches(
            mb_ref, rm_ref.model.scorer, spec_ref, batches, ref_tables
        )
    finally:
        wt_ref.close()

    # serve with the lifeboat; second snapshot dies mid-write
    rm, spec, state0, _ = build_ledger_model(seed=seed)
    wt = _watchtower(rm.profile, halflife=50_000.0)
    wt.drift.bind_ledger(spec, state0)
    boat = Lifeboat(
        lbdir, spec, drift=wt.drift, snapshot_s=1e9, fsync_s=0.0,
    )
    boat.recover()
    mb = MicroBatcher(
        scorer=rm.model.scorer, watchtower=wt, telemetry=False,
        max_batch=batch, lifeboat=boat,
    )
    killed = False
    plan = faults.FaultPlan().kill("lifeboat.snapshot")
    try:
        _drive_ledger_batches(
            mb, rm.model.scorer, spec, batches[:snapshot_after]
        )
        boat.take_snapshot()  # generation 1 lands cleanly
        _drive_ledger_batches(
            mb, rm.model.scorer, spec, batches[snapshot_after:]
        )
        with plan.armed():
            try:
                boat.take_snapshot()  # rotated, then killed pre-write
            except faults.ReplicaKilled:
                killed = True
    finally:
        wt.close()
    result.add(
        InvariantOutcome(
            "killed-mid-snapshot",
            killed and plan.fired("lifeboat.snapshot") == 1,
            "ReplicaKilled after the journal rotation, before the "
            "generation file landed",
        )
    )

    # a torn newest generation on top: valid bytes truncated mid-payload
    # (the shape a crash mid-write leaves on a filesystem without the
    # atomic-rename guarantee, or plain disk damage)
    scratch = os.path.join(tmpdir, "scratch")
    full = lb.write_snapshot(
        scratch, n_batches, spec, state_ref, rows_seen=0
    )
    with open(full, "rb") as f:
        blob = f.read()
    torn_path = os.path.join(lbdir, f"lifeboat-{n_batches:012d}.snap")
    with open(torn_path, "wb") as f:
        f.write(blob[: int(len(blob) * 0.6)])

    # warm restart: the torn file is skipped, generation 1 + full journal
    # replay land the complete table
    rm2, spec2, state02, _ = build_ledger_model(seed=seed)
    wt2 = _watchtower(rm2.profile, halflife=50_000.0)
    wt2.drift.bind_ledger(spec2, state02)
    boat2 = Lifeboat(
        lbdir, spec2, drift=wt2.drift, snapshot_s=1e9, fsync_s=0.0,
    )
    try:
        rep = boat2.recover()
        recovered = wt2.drift.ledger_snapshot()
    finally:
        wt2.close()
        boat2.close()
    ok_full, detail_full = _tables_equal(recovered, ref_tables[-1])
    result.add(
        InvariantOutcome(
            "generation-fallback",
            rep.generations_skipped == 1
            and rep.snapshot_seq == snapshot_after,
            f"torn newest generation skipped ({rep.generations_skipped}), "
            f"restored from generation seq {rep.snapshot_seq}",
        )
    )
    result.add(
        InvariantOutcome(
            "nothing-lost-on-fallback",
            rep.restored and rep.torn_rows == 0 and ok_full,
            "rotated+synced journal replays the full table bitwise vs the "
            f"clean drive ({detail_full})",
        )
    )

    # torn journal TAIL: truncate the last record's CRC — the final flush
    # is lost, counted, and the table lands one flush back
    journals = lb.list_journals(lbdir)
    last_rows = int(
        np.sum([e is not None for e in batches[-1][1]])
    )
    # the rotated file carrying records past generation 1 is the one whose
    # base is the generation's sequence number
    tail_file = next(
        path for base, path in journals if base == snapshot_after
    )
    with open(tail_file, "rb") as f:
        jblob = f.read()
    with open(tail_file, "wb") as f:
        f.write(jblob[:-6])
    torn_before = svc_metrics.lifeboat_torn_tail_rows._value.get()
    boat3 = Lifeboat(lbdir, spec2, snapshot_s=1e9, fsync_s=0.0)
    try:
        rep2 = boat3.recover()
    finally:
        boat3.close()
    torn_delta = (
        svc_metrics.lifeboat_torn_tail_rows._value.get() - torn_before
    )
    ok_torn, detail_torn = _tables_equal(rep2.state, ref_tables[-2])
    result.metrics = {
        "batches": n_batches,
        "generation_seq": rep.snapshot_seq,
        "generations_skipped": rep.generations_skipped,
        "replayed_rows_full": rep.replayed_rows,
        "replayed_rows_torn_tail": rep2.replayed_rows,
        "torn_tail_rows": rep2.torn_rows,
    }
    result.add(
        InvariantOutcome(
            "torn-tail-bounded-loss",
            rep2.torn_rows == last_rows
            and torn_delta == last_rows
            and ok_torn,
            f"torn tail dropped exactly the final flush ({rep2.torn_rows} "
            f"rows, counted on lifeboat_torn_tail_rows_total), table lands "
            f"one flush back bitwise ({detail_torn})",
        )
    )
    return result


# -- longhaul: the multi-host switchyard -------------------------------------

def _keyed_batches(spec, batches):
    """Entity strings → ``(slot, fp, ts)`` triples, the form the front
    routes on and the micro-batcher stages."""
    out = []
    for rows, ents, ts in batches:
        ke = [
            None if e is None else (*spec.row_keys(e), float(ts[i]))
            for i, e in enumerate(ents)
        ]
        out.append((rows, ke))
    return out


def _longhaul_fleet(tmpdir: str, seed: int, dead_after_s: float = 1.0):
    """A 2-host localhost fleet: directory + two lifeboat-backed hosts +
    front, plus the single-host parity reference."""
    from fraud_detection_tpu.longhaul.front import LonghaulFront
    from fraud_detection_tpu.longhaul.host import (
        HostServer,
        build_seeded_backend,
    )
    from fraud_detection_tpu.longhaul.membership import DirectoryServer

    dirsrv = DirectoryServer(
        os.path.join(tmpdir, "dir"), n_hosts=2, dead_after_s=dead_after_s
    )
    dirsrv.start()
    fleet_dir = os.path.join(tmpdir, "fleet")
    b_a, t0 = build_seeded_backend(seed, fleet_dir, "host-a")
    b_b, _ = build_seeded_backend(seed, fleet_dir, "host-b")
    h_a = HostServer(
        "host-a", b_a, n_hosts=2, directory_addr=dirsrv.addr,
        heartbeat_s=0.2,
    )
    h_b = HostServer(
        "host-b", b_b, n_hosts=2, directory_addr=dirsrv.addr,
        heartbeat_s=0.2,
    )
    h_a.start()
    h_b.start()
    b_ref, _ = build_seeded_backend(seed, "", "ref")
    front = LonghaulFront(
        b_ref.spec, n_hosts=2, directory_addr=dirsrv.addr,
    )
    # both joins must be visible in every host's serving claim before
    # traffic flows (host-a momentarily ring-owns both segments)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if h_a.owned_segments == {0} and h_b.owned_segments == {1}:
            break
        time.sleep(0.05)
    return dirsrv, h_a, h_b, b_ref, front, fleet_dir, t0


def _wait_dead(dirsrv, rank: int, timeout_s: float = 6.0) -> float:
    """Block until the failure detector declares ``rank`` dead; returns
    the detection latency."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        m = dirsrv.view().member_by_rank(rank)
        if m is not None and not m.alive:
            return time.monotonic() - t0
        time.sleep(0.05)
    raise TimeoutError(f"rank {rank} never declared dead")


def scenario_ledger_owner_failover_mid_traffic(
    tmpdir: str, seed: int = 2027, n_batches: int = 8, batch: int = 32,
) -> ScenarioResult:
    """Kill one host of a 2-host fleet mid-traffic; the survivor inherits
    the dead peer's ledger segment from its journal generation.

    Invariants:

    - **routed-bitwise**: scores routed through the front (pre-kill AND
      post-failover) are bitwise equal to an uninterrupted single-host
      serve of the same batches;
    - **degraded-503**: between the kill and the completed inheritance,
      every request touching the dead owner's segment answers the typed
      503 with a positive Retry-After — never a silent misroute into a
      table that hasn't inherited the rows;
    - **failover-bitwise**: after inheritance + the remaining traffic,
      the survivor's FULL table (both segments, scalar counters included)
      is bitwise equal to the uninterrupted single-host table;
    - **zero-new-compiles**: inheritance rebinds the merged table with
      identical shapes/dtypes — the fused ledger-flush cache grows by 0.
    """
    from fraud_detection_tpu import config as config_mod
    from fraud_detection_tpu.longhaul import placement
    from fraud_detection_tpu.longhaul.codec import Unavailable
    from fraud_detection_tpu.monitor import drift as drift_mod

    result = ScenarioResult("ledger_owner_failover_mid_traffic")
    dirsrv, h_a, h_b, b_ref, front, fleet_dir, t0 = _longhaul_fleet(
        tmpdir, seed
    )
    spec = b_ref.spec
    try:
        batches = _keyed_batches(
            spec, _entity_batches(seed, n_batches, batch, t0)
        )
        half = n_batches // 2

        def ref_drive(rows, ke):
            return b_ref.score_items(
                [(rows[i], None, None, ke[i]) for i in range(rows.shape[0])]
            )

        pre_ok = True
        for rows, ke in batches[:half]:
            ref = ref_drive(rows, ke)
            routed = front.score(rows, ke, fmt="json")
            pre_ok = pre_ok and ref.tobytes() == routed.tobytes()
        result.add(
            InvariantOutcome(
                "routed-bitwise-pre-kill", pre_ok,
                f"{half} routed batches bitwise equal to the single-host "
                "serve (per-slot fold independence)",
            )
        )

        # -- the kill: abrupt, mid-traffic ---------------------------------
        h_b.kill()
        detect_s = _wait_dead(dirsrv, rank=1)

        # a probe carrying ONLY the dead owner's segment: every attempt
        # during the handoff must surface the typed 503 — a success here
        # would mean a silent serve from a table missing the rows
        rows_p, ke_p = batches[half]
        idx = [
            i for i, e in enumerate(ke_p)
            if e is not None and placement.host_of(int(e[0]), 2) == 1
        ]
        probe_rows = rows_p[idx]
        probe_ke = [ke_p[i] for i in idx]
        degraded, attempts = True, 0
        for _ in range(3):
            attempts += 1
            try:
                front.score(probe_rows, probe_ke, fmt="json")
                degraded = False
            except Unavailable as exc:
                degraded = degraded and exc.retry_after_s > 0.0
        result.add(
            InvariantOutcome(
                "degraded-503-with-retry-after", degraded,
                f"{attempts} mid-handoff attempts on the dead owner's "
                "segment all answered 503 + Retry-After "
                f"(retry_after_s={config_mod.longhaul_retry_after_s()})",
            )
        )

        compiles_before = drift_mod._fused_flush_ledger._cache_size()
        t_fo = time.monotonic()
        summary = front.drive_failover(
            1, os.path.join(fleet_dir, "host-b")
        )
        failover_s = time.monotonic() - t_fo
        restored = bool(summary and summary.get("restored"))
        result.add(
            InvariantOutcome(
                "failover-restores-segment",
                restored and summary["torn_rows"] == 0
                and summary["replayed_rows"] > 0,
                f"survivor replayed {summary and summary['replayed_rows']}"
                f" rows from the peer generation in "
                f"{summary and round(summary['duration_s'], 3)}s",
            )
        )

        # remaining traffic: everything routes to the survivor now
        post_ok = True
        for rows, ke in batches[half:]:
            ref = ref_drive(rows, ke)
            routed = front.score(rows, ke, fmt="json")
            post_ok = post_ok and ref.tobytes() == routed.tobytes()
        result.add(
            InvariantOutcome(
                "routed-bitwise-post-failover", post_ok,
                f"{n_batches - half} batches served by the survivor "
                "bitwise equal to the uninterrupted serve",
            )
        )

        compiles_delta = (
            drift_mod._fused_flush_ledger._cache_size() - compiles_before
        )
        t_ref = b_ref.table()
        t_srv = h_a.backend.table()
        eq, detail = placement.segments_equal(t_srv, t_ref, [0, 1], 2)
        scal_ok = (
            np.float32(t_srv.collisions).tobytes()
            == np.float32(t_ref.collisions).tobytes()
            and np.float32(t_srv.evictions).tobytes()
            == np.float32(t_ref.evictions).tobytes()
        )
        result.add(
            InvariantOutcome(
                "survivor-table-bitwise",
                eq and scal_ok
                and h_a.owned_segments == {0, 1},
                f"survivor owns both segments; full table {detail}; "
                f"scalar counters {'match' if scal_ok else 'DIFFER'}",
            )
        )
        result.add(
            InvariantOutcome(
                "zero-new-compiles", compiles_delta == 0,
                f"{compiles_delta} fused ledger-flush executables "
                "compiled across inherit + post-failover traffic",
            )
        )
        result.metrics = {
            "batches": n_batches,
            "detect_s": round(detect_s, 3),
            "failover_s": round(failover_s, 3),
            "replayed_rows": summary and summary["replayed_rows"],
            "replay_rows_per_sec": summary
            and round(summary["replay_rows_per_sec"], 1),
            "mid_handoff_503s": attempts,
            "compiles_delta": compiles_delta,
        }
        return result
    finally:
        front.close()
        h_a.close()
        h_b.kill()
        dirsrv.close()


def scenario_host_partition_mid_promotion(
    tmpdir: str, seed: int = 2028,
) -> ScenarioResult:
    """Partition a host from the directory mid-promotion: every finalize
    decided under the pre-partition epoch must die, and exactly the
    post-rejoin finalize under the fresh epoch lands.

    The epoch is the fence token: the partitioned host cannot REACH the
    directory (fail-safe — unreachable means un-finalizable), and a
    reachable host holding the old epoch sees the directory has moved on.
    """
    from fraud_detection_tpu.service import metrics as svc_metrics

    result = ScenarioResult("host_partition_mid_promotion")
    dirsrv, h_a, h_b, b_ref, front, _fleet, _t0 = _longhaul_fleet(
        tmpdir, seed
    )
    try:
        epoch_before = dirsrv.view().epoch
        fenced_before = (
            svc_metrics.longhaul_promotion_fenced.labels("host-b")._value.get()
            + svc_metrics.longhaul_promotion_fenced.labels("host-a")._value.get()
        )

        # the partition: control-plane packets stop routing for B
        h_b.partitioned = True
        detect_s = _wait_dead(dirsrv, rank=1)
        epoch_dead = dirsrv.view().epoch
        result.add(
            InvariantOutcome(
                "partition-detected",
                epoch_dead > epoch_before,
                f"detector declared the partitioned host dead in "
                f"{detect_s:.2f}s (epoch {epoch_before} -> {epoch_dead})",
            )
        )

        # fence 1: the partitioned host itself — directory unreachable
        res_b = h_b.finalize_promotion("v2", epoch_before)
        # fence 2: a reachable host holding the stale epoch
        res_a = h_a.finalize_promotion("v2", epoch_before)
        result.add(
            InvariantOutcome(
                "stale-finalizes-fenced",
                not res_b["applied"] and res_b.get("fenced")
                and not res_a["applied"] and res_a.get("fenced"),
                f"partitioned host: {res_b.get('reason', '')[:60]}; "
                f"stale-epoch host: {res_a.get('reason', '')[:60]}",
            )
        )

        # heal: B rejoins (its next heartbeat learns it was declared
        # dead and re-registers), epoch bumps again
        h_b.partitioned = False
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            m = dirsrv.view().member_by_rank(1)
            if m is not None and m.alive:
                break
            time.sleep(0.05)
        epoch_fresh = dirsrv.view().epoch
        res_a2 = h_a.finalize_promotion("v2", epoch_fresh)
        res_b2 = h_b.finalize_promotion("v2", epoch_fresh)
        result.add(
            InvariantOutcome(
                "fresh-finalize-lands",
                res_a2["applied"] and res_b2["applied"]
                and h_a.served_version == "v2"
                and h_b.served_version == "v2",
                f"both hosts finalized v2 under fresh epoch {epoch_fresh}",
            )
        )
        fenced_after = (
            svc_metrics.longhaul_promotion_fenced.labels("host-b")._value.get()
            + svc_metrics.longhaul_promotion_fenced.labels("host-a")._value.get()
        )
        result.add(
            InvariantOutcome(
                "fences-counted",
                fenced_after - fenced_before == 2,
                f"longhaul_promotion_fenced_total grew by "
                f"{fenced_after - fenced_before} (one per refused "
                "finalize)",
            )
        )
        result.metrics = {
            "detect_s": round(detect_s, 3),
            "epoch_before": epoch_before,
            "epoch_dead": epoch_dead,
            "epoch_fresh": epoch_fresh,
        }
        return result
    finally:
        front.close()
        h_a.close()
        h_b.close()
        dirsrv.close()


def scenario_split_brain_scrape(
    tmpdir: str, seed: int = 2029, n_batches: int = 4, batch: int = 32,
) -> ScenarioResult:
    """A partitioned host keeps serving and answering scrapes under its
    frozen epoch; the fleet merge must never double-count it.

    Invariants: the stale contribution is dropped and counted
    (``longhaul_scrape_stale_epoch``), the merged drift window equals the
    live host's window alone (not the sum), and after rejoin the merge
    re-admits both hosts under the fresh epoch.
    """
    from fraud_detection_tpu.longhaul import scrape as scrape_mod
    from fraud_detection_tpu.longhaul.front import HostHandle
    from fraud_detection_tpu.service import metrics as svc_metrics

    result = ScenarioResult("split_brain_scrape")
    dirsrv, h_a, h_b, b_ref, front, _fleet, t0 = _longhaul_fleet(
        tmpdir, seed
    )
    spec = b_ref.spec
    try:
        batches = _keyed_batches(
            spec, _entity_batches(seed, n_batches, batch, t0)
        )
        for rows, ke in batches:
            front.score(rows, ke, fmt="json")

        clients = [
            HostHandle("host-a", 0, h_a.addr, h_a.token),
            HostHandle("host-b", 1, h_b.addr, h_b.token),
        ]
        epoch0 = dirsrv.view().epoch
        # both hosts must have learned the current epoch before the
        # baseline scrape, or their stamps race the sweep
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if h_a.known_epoch == epoch0 and h_b.known_epoch == epoch0:
                break
            time.sleep(0.05)
        base = scrape_mod.fleet_scrape(clients, epoch0)
        both_counted = (
            sorted(base["accepted"]) == ["host-a", "host-b"]
            and base["window"] is not None
        )
        n_rows_both = float(np.sum(np.asarray(base["window"].n_rows)))
        result.add(
            InvariantOutcome(
                "healthy-scrape-merges-both", both_counted,
                f"pre-partition scrape merged 2 hosts, window n_rows="
                f"{n_rows_both:.1f}",
            )
        )

        # the partition: B's control plane freezes (epoch stays stale),
        # its DATA plane — including the scrape op — keeps answering
        h_b.partitioned = True
        _wait_dead(dirsrv, rank=1)
        epoch1 = dirsrv.view().epoch
        # the live host must learn the bumped epoch before the scrape,
        # or ITS contribution would read stale too
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if h_a.known_epoch == epoch1:
                break
            time.sleep(0.05)
        stale_before = svc_metrics.longhaul_scrape_stale_epoch.labels(
            "host-b"
        )._value.get()
        split = scrape_mod.fleet_scrape(clients, epoch1)
        stale_delta = (
            svc_metrics.longhaul_scrape_stale_epoch.labels(
                "host-b"
            )._value.get()
            - stale_before
        )
        # the no-double-count pin: the merged window is A's alone —
        # bitwise — not A + a stale copy of B
        a_only = scrape_mod.fleet_scrape(clients[:1], epoch1)
        merged_is_a = (
            split["window"] is not None
            and a_only["window"] is not None
            and all(
                np.asarray(x).tobytes() == np.asarray(y).tobytes()
                for x, y in zip(split["window"], a_only["window"])
            )
        )
        result.add(
            InvariantOutcome(
                "stale-epoch-dropped",
                split["stale"] == ["host-b"]
                and split["accepted"] == ["host-a"]
                and stale_delta == 1,
                f"split-brain contribution dropped and counted "
                f"(stale_epoch delta={stale_delta})",
            )
        )
        result.add(
            InvariantOutcome(
                "no-double-count", merged_is_a,
                "merged window under the split is bitwise the live "
                "host's window alone",
            )
        )

        # heal: B rejoins and the next scrape re-admits it
        h_b.partitioned = False
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            m = dirsrv.view().member_by_rank(1)
            if m is not None and m.alive:
                break
            time.sleep(0.05)
        epoch2 = dirsrv.view().epoch
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if h_a.known_epoch == epoch2 and h_b.known_epoch == epoch2:
                break
            time.sleep(0.05)
        healed = scrape_mod.fleet_scrape(clients, epoch2)
        result.add(
            InvariantOutcome(
                "rejoin-readmits",
                sorted(healed["accepted"]) == ["host-a", "host-b"],
                f"post-rejoin scrape merged both hosts under epoch "
                f"{epoch2}",
            )
        )
        result.metrics = {
            "epoch_baseline": epoch0,
            "epoch_split": epoch1,
            "epoch_healed": epoch2,
            "window_rows_baseline": round(n_rows_both, 1),
        }
        return result
    finally:
        for c in clients:
            c.close()
        front.close()
        h_a.close()
        h_b.close()
        dirsrv.close()


SCENARIOS = {
    "burst": scenario_burst,
    "drift_onset": scenario_drift_onset,
    "fraud_ring": scenario_fraud_ring,
    "label_delay": scenario_label_delay,
    "control_plane_chaos": scenario_control_plane_chaos,
    "hot_swap": scenario_hot_swap,
    "shard_kill_mid_swap": scenario_shard_kill_mid_swap,
    "replica_burst": scenario_replica_burst,
    "explain_under_burst": scenario_explain_under_burst,
    "gbt_explain_under_burst": scenario_gbt_explain_under_burst,
    "poison_entity_state": scenario_poison_entity_state,
    "ingest_storm": scenario_ingest_storm,
    "slo_burn_under_shed": scenario_slo_burn_under_shed,
    "crash_warm_restart": scenario_crash_warm_restart,
    "kill_mid_snapshot": scenario_kill_mid_snapshot,
    "ledger_owner_failover_mid_traffic": (
        scenario_ledger_owner_failover_mid_traffic
    ),
    "host_partition_mid_promotion": scenario_host_partition_mid_promotion,
    "split_brain_scrape": scenario_split_brain_scrape,
}

#: scenarios that need a scratch directory as their first argument
NEEDS_TMPDIR = (
    "label_delay",
    "control_plane_chaos",
    "crash_warm_restart",
    "kill_mid_snapshot",
    "ledger_owner_failover_mid_traffic",
    "host_partition_mid_promotion",
    "split_brain_scrape",
)


def run_scenario(name: str, tmpdir: str | None = None, **kw) -> ScenarioResult:
    fn = SCENARIOS[name]
    if name in NEEDS_TMPDIR:
        if tmpdir is None:
            import tempfile

            with tempfile.TemporaryDirectory(prefix=f"range-{name}-") as td:
                return fn(td, **kw)
        return fn(tmpdir, **kw)
    return fn(**kw)
