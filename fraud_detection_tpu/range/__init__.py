"""The fraud range: adversarial traffic simulation + closed-loop chaos.

Submodules (imported lazily — production code imports ``range.faults``
alone, which must stay stdlib-light because its ``fire()`` hook sits on
the serving flush):

- :mod:`fraud_detection_tpu.range.faults` — the :class:`FaultPlan`
  injector behind the named injection points in lifecycle/conductor.py,
  service/taskq.py, service/netclient.py, lifecycle/store.py, and
  service/microbatch.py;
- :mod:`fraud_detection_tpu.range.traffic` — seeded campaign generators
  (diurnal bursts, drift onsets, fraud rings, label delay/noise);
- :mod:`fraud_detection_tpu.range.invariants` — the end-to-end invariant
  checks + the alert-flap detector;
- :mod:`fraud_detection_tpu.range.scenarios` — the named scenario suite
  (``run_scenario``), shared by ``bench.py``'s ``scenarios`` section and
  the ``-m slow`` chaos test tier.

See docs/runbooks/ChaosDrills.md for how to drive a drill by hand.
"""

from __future__ import annotations

_LAZY = {
    "FaultPlan": ("fraud_detection_tpu.range.faults", "FaultPlan"),
    "ReplicaKilled": ("fraud_detection_tpu.range.faults", "ReplicaKilled"),
    "ScenarioResult": (
        "fraud_detection_tpu.range.invariants", "ScenarioResult"
    ),
    "SCENARIOS": ("fraud_detection_tpu.range.scenarios", "SCENARIOS"),
    "run_scenario": ("fraud_detection_tpu.range.scenarios", "run_scenario"),
}

__all__ = list(_LAZY)


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)
