"""End-to-end invariant checks the fraud range asserts after every scenario.

Each check returns an :class:`InvariantOutcome` (never raises) so one
scenario run reports ALL violated invariants, not just the first — chaos
failures tend to come in correlated clusters and the second failure is
usually the diagnostic one. ``ScenarioResult.raise_if_failed()`` is the
pytest/CI surface.

The named invariants (ISSUE 6):

- **drift-detected-within-N** — watchtower flags drift within a row budget
  of the campaign's known onset;
- **exactly-once-promotion** — the conductor's CAS machine converged, the
  ``@prod`` alias points at the challenger, exactly one promotion landed,
  and no duplicate model version was registered;
- **p99-holds** — p99 request latency during a hot swap stays within a
  multiple of the undisturbed baseline;
- **no-alert-flaps** — no alert condition fires and clears within one
  evaluation window (sampled each scenario step via
  :class:`AlertFlapDetector`);
- **bitwise-consistent** — two runs of the same seeded scenario leave the
  drift window (and the staging pool's allocation count) bitwise identical.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class InvariantOutcome:
    name: str
    ok: bool
    detail: str = ""

    def __bool__(self) -> bool:
        return self.ok


@dataclass
class ScenarioResult:
    name: str
    invariants: list[InvariantOutcome] = field(default_factory=list)
    metrics: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(inv.ok for inv in self.invariants)

    def add(self, outcome: InvariantOutcome) -> None:
        self.invariants.append(outcome)

    def to_dict(self) -> dict:
        def py(v):
            """JSON-native coercion: numpy scalars leak out of invariant
            predicates (``np.isfinite`` returns np.bool_) and json.dumps
            refuses them."""
            if isinstance(v, (bool, np.bool_)):
                return bool(v)
            if isinstance(v, np.integer):
                return int(v)
            if isinstance(v, np.floating):
                return float(v)
            if isinstance(v, dict):
                return {k: py(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [py(x) for x in v]
            return v

        return {
            "scenario": self.name,
            "ok": bool(self.ok),
            "invariants": {
                inv.name: {"ok": bool(inv.ok), "detail": inv.detail}
                for inv in self.invariants
            },
            "metrics": py(self.metrics),
        }

    def raise_if_failed(self) -> None:
        bad = [i for i in self.invariants if not i.ok]
        if bad:
            lines = "\n".join(f"  [{i.name}] {i.detail}" for i in bad)
            raise AssertionError(
                f"scenario {self.name!r} violated "
                f"{len(bad)} invariant(s):\n{lines}"
            )


# -- individual checks ------------------------------------------------------

def drift_detected_within(
    onset_row: int, detected_row: int | None, budget_rows: int
) -> InvariantOutcome:
    name = "drift-detected-within-N"
    if detected_row is None:
        return InvariantOutcome(
            name, False,
            f"drift never detected (onset at row {onset_row}, "
            f"budget {budget_rows} rows)",
        )
    delay = detected_row - onset_row
    ok = 0 <= delay <= budget_rows
    return InvariantOutcome(
        name, ok,
        f"detected at row {detected_row}, onset {onset_row} "
        f"(delay {delay}, budget {budget_rows})",
    )


def exactly_once_promotion(
    registry,
    store,
    model_name: str,
    challenger_version: int,
    versions_before: int,
    promotions_delta: float,
    prod_stage: str = "prod",
    shadow_stage: str = "shadow",
) -> InvariantOutcome:
    """The CAS state machine converged to exactly one applied promotion."""
    name = "exactly-once-promotion"
    problems: list[str] = []
    state = store.get_state(model_name)
    if state["state"] != "done":
        problems.append(f"state machine ended {state['state']!r}, not 'done'")
    prod = registry.get_version_by_alias(model_name, prod_stage)
    if prod != challenger_version:
        problems.append(
            f"@{prod_stage} is v{prod}, expected challenger v{challenger_version}"
        )
    shadow = registry.get_version_by_alias(model_name, shadow_stage)
    if shadow is not None:
        problems.append(f"@{shadow_stage} still set (v{shadow}) after promotion")
    latest = registry.latest_version(model_name)
    if latest != versions_before:
        problems.append(
            f"registry grew to v{latest} (expected v{versions_before}) — "
            "a resumed episode registered a duplicate challenger"
        )
    if promotions_delta != 1:
        problems.append(
            f"lifecycle_promotions_total advanced by {promotions_delta}, "
            "expected exactly 1"
        )
    return InvariantOutcome(
        name, not problems,
        "; ".join(problems) or
        f"one promotion, @{prod_stage}=v{prod}, no duplicate registrations",
    )


def p99_within(
    latencies_s,
    baseline_p99_s: float,
    *,
    factor: float = 5.0,
    absolute_floor_s: float = 0.05,
) -> InvariantOutcome:
    """p99 during the disturbance ≤ max(factor × baseline, floor).

    The floor keeps CI hosts honest: a 0.8 ms baseline p99 on a quiet CPU
    would otherwise fail the swap window on scheduler jitter alone.
    """
    name = "p99-holds"
    lat = np.asarray(list(latencies_s), np.float64)
    if lat.size == 0:
        return InvariantOutcome(name, False, "no latencies recorded")
    p99 = float(np.percentile(lat, 99))
    budget = max(factor * baseline_p99_s, absolute_floor_s)
    return InvariantOutcome(
        name, p99 <= budget,
        f"p99 {p99 * 1e3:.2f}ms vs budget {budget * 1e3:.2f}ms "
        f"(baseline {baseline_p99_s * 1e3:.2f}ms × {factor})",
    )


def windows_bitwise_equal(window_a, window_b) -> InvariantOutcome:
    """Two DriftWindow pytrees (or any named tuples of arrays) must match
    bit for bit — the determinism contract of a seeded scenario."""
    name = "bitwise-consistent"
    fields = getattr(window_a, "_fields", None) or range(len(window_a))
    for i, f in enumerate(fields):
        a = np.asarray(window_a[i] if isinstance(f, int) else getattr(window_a, f))
        b = np.asarray(window_b[i] if isinstance(f, int) else getattr(window_b, f))
        if a.shape != b.shape or a.dtype != b.dtype:
            return InvariantOutcome(
                name, False, f"field {f}: shape/dtype mismatch {a.shape}/{b.shape}"
            )
        ab, bb = a.tobytes(), b.tobytes()
        if ab != bb:
            diff = int(
                np.sum(
                    np.frombuffer(ab, np.uint8) != np.frombuffer(bb, np.uint8)
                )
            )
            return InvariantOutcome(
                name, False, f"field {f}: {diff} differing bytes"
            )
    return InvariantOutcome(name, True, "drift windows bitwise identical")


class AlertFlapDetector:
    """Samples boolean alert conditions once per scenario step and reports
    flaps: an episode that fires and fully clears within one evaluation
    window (``min_hold_samples``). Prometheus `for:` clauses suppress
    sub-window noise, but a condition that *oscillates* at the window
    boundary pages and un-pages — the operator experience the range
    guards against.
    """

    def __init__(self, min_hold_samples: int = 3):
        self.min_hold = min_hold_samples
        self._series: dict[str, list[bool]] = {}

    def sample(self, **conditions: bool) -> None:
        for k, v in conditions.items():
            self._series.setdefault(k, []).append(bool(v))

    def episodes(self, name: str) -> list[int]:
        """Lengths (in samples) of each firing episode of ``name``."""
        out: list[int] = []
        run = 0
        for v in self._series.get(name, []):
            if v:
                run += 1
            elif run:
                out.append(run)
                run = 0
        if run:
            out.append(run)
        return out

    def check(self) -> InvariantOutcome:
        name = "no-alert-flaps"
        flaps: list[str] = []
        for cond, series in self._series.items():
            eps = self.episodes(cond)
            # the last episode may still be open at scenario end — holding
            # at the end is not a flap
            closed = eps[:-1] if series and series[-1] else eps
            short = [e for e in closed if e < self.min_hold]
            if short:
                flaps.append(
                    f"{cond}: {len(short)} episode(s) shorter than "
                    f"{self.min_hold} samples {short}"
                )
        return InvariantOutcome(
            name, not flaps,
            "; ".join(flaps) or
            f"no condition fired-and-cleared within {self.min_hold} samples",
        )
