"""Adversarial traffic generators: synthetic fraud campaigns, seeded.

Every generator is a pure function of ``(spec, seed)`` — two runs with the
same seed produce bitwise-identical row streams, which is what lets the
invariant checker assert the drift window ends bitwise-consistent across
repeated scenario runs (range/invariants.py). All rows are Kaggle-schema
shaped (30 float32 features) so they flow through the real scorer,
watchtower, and feedback store unchanged.

Four campaign ingredients, composable per scenario:

- :class:`ArrivalProcess` — heavy-tailed diurnal arrivals: a sinusoidal
  base rate (the millions-of-users day/night shape compressed into the
  scenario's duration) modulated by Pareto-distributed burst multipliers,
  so batch sizes carry the 80/20 burstiness real fraud traffic has;
- :class:`DriftCampaign` — covariate and/or label drift switched on at a
  KNOWN onset row (mean shift + scale stretch on chosen features), so
  detection latency is measurable in rows, not vibes;
- :class:`FraudRing` — a coordinated ring: clusters of rows drawn tightly
  around a shared feature center (correlated feature clusters), injected
  as contiguous runs the way mule networks burst;
- :class:`LabelFeedback` — the label-delay + label-noise model: labels for
  scored rows settle only after ``delay_rows`` more traffic has passed,
  with a configurable flip rate (noisy human review).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

D = 30  # Kaggle schema width: Time + V1..V28 + Amount


def _logistic(z: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-z))


@dataclass(frozen=True)
class ArrivalProcess:
    """Heavy-tailed diurnal arrivals, quantized into micro-batches.

    ``rate_hz`` is the mean arrival rate; the instantaneous rate follows
    one diurnal sine period across ``total_rows`` (trough ``1 - depth``,
    peak ``1 + depth`` of the mean) and each collection window's count is
    further multiplied by a Pareto(``burst_alpha``) draw clipped at
    ``burst_cap`` — alpha ≤ 2 gives the infinite-variance burstiness that
    makes p99 meaningful.
    """

    rate_hz: float = 2000.0
    window_s: float = 0.01
    diurnal_depth: float = 0.6
    burst_alpha: float = 1.5
    burst_cap: float = 20.0

    def batch_sizes(self, total_rows: int, rng: np.random.Generator) -> list[int]:
        sizes: list[int] = []
        done = 0
        base = self.rate_hz * self.window_s
        # pre-draw in blocks for determinism independent of loop count
        while done < total_rows:
            phase = done / max(total_rows, 1)
            diurnal = 1.0 + self.diurnal_depth * np.sin(2 * np.pi * phase)
            burst = min(float(rng.pareto(self.burst_alpha)) + 1.0, self.burst_cap)
            n = int(round(base * diurnal * burst))
            n = max(1, min(n, total_rows - done))
            sizes.append(n)
            done += n
        return sizes


@dataclass(frozen=True)
class DriftCampaign:
    """Covariate (and optionally label) drift with a known onset row."""

    onset_row: int
    features: tuple[int, ...] = (0, 3, 7)
    mean_shift: float = 3.0
    scale_stretch: float = 1.0
    label_flip_rate: float = 0.0  # label drift: P(flip) after onset

    def apply(
        self, x: np.ndarray, y: np.ndarray, start_row: int,
        rng: np.random.Generator,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Shift the slice of this batch that falls after the onset."""
        n = x.shape[0]
        first = max(0, self.onset_row - start_row)
        if first >= n:
            return x, y
        x = x.copy()
        y = y.copy()
        idx = list(self.features)
        x[first:, idx] = (
            x[first:, idx] * self.scale_stretch + self.mean_shift
        )
        if self.label_flip_rate > 0.0:
            flips = rng.random(n - first) < self.label_flip_rate
            y[first:] = np.where(flips, 1 - y[first:], y[first:])
        return x, y


@dataclass(frozen=True)
class FraudRing:
    """Coordinated fraud ring: correlated feature clusters.

    ``n_rings`` centers are drawn once (far out in feature space along
    ``ring_features``); each injected run is ``ring_size`` consecutive rows
    sampled within ``ring_sigma`` of one center — tight clusters with
    pairwise feature correlation ≈ 1 - sigma², against a background of
    independent rows.
    """

    start_row: int
    n_rings: int = 3
    ring_size: int = 48
    every_rows: int = 512
    ring_features: tuple[int, ...] = (1, 2, 4, 9)
    center_scale: float = 4.0
    ring_sigma: float = 0.15

    def centers(self, rng: np.random.Generator) -> np.ndarray:
        c = np.zeros((self.n_rings, D), np.float32)
        c[:, list(self.ring_features)] = (
            rng.standard_normal((self.n_rings, len(self.ring_features)))
            * self.center_scale
        ).astype(np.float32)
        return c


@dataclass(frozen=True)
class LabelFeedback:
    """Label-delay + label-noise: labels settle ``delay_rows`` of traffic
    after scoring, with ``noise_rate`` of them flipped by review error."""

    delay_rows: int = 2048
    noise_rate: float = 0.0
    batch: int = 256  # rows per delivered feedback batch


@dataclass
class TrafficBatch:
    """One generated micro-batch plus its campaign bookkeeping."""

    rows: np.ndarray          # (n, 30) float32
    labels: np.ndarray        # (n,) int32 ground truth (pre-delay)
    start_row: int            # global index of rows[0]
    ring_mask: np.ndarray     # (n,) bool — True for fraud-ring rows
    drifted: bool             # any row at/after the drift onset


@dataclass
class CampaignSpec:
    """A full scenario's traffic recipe — everything seeded."""

    total_rows: int = 8192
    seed: int = 2026
    w_true: np.ndarray | None = None  # ground-truth boundary (default drawn)
    bias: float = -2.0
    arrivals: ArrivalProcess = field(default_factory=ArrivalProcess)
    drift: DriftCampaign | None = None
    ring: FraudRing | None = None
    feedback: LabelFeedback | None = None


class CampaignTraffic:
    """Iterator over a campaign's micro-batches (deterministic per seed)."""

    def __init__(self, spec: CampaignSpec):
        self.spec = spec
        self.rng = np.random.default_rng(spec.seed)
        self.w_true = (
            spec.w_true
            if spec.w_true is not None
            else self.rng.standard_normal(D).astype(np.float32)
        )
        self._ring_centers = (
            spec.ring.centers(self.rng) if spec.ring is not None else None
        )
        if self._ring_centers is not None:
            # orient each center into the fraud half-space: a coordinated
            # ring is a HIGH-RISK pattern by construction — flip the signs
            # of the cluster coordinates so the ground-truth logit
            # contribution is positive on every ring feature
            f = list(spec.ring.ring_features)
            sign = np.sign(self.w_true[f]).astype(np.float32)
            sign[sign == 0] = 1.0
            self._ring_centers[:, f] = (
                np.abs(self._ring_centers[:, f]) * sign
            )

    def _labels_for(self, x: np.ndarray, ring_mask: np.ndarray) -> np.ndarray:
        p = _logistic(x @ self.w_true + self.spec.bias)
        y = (self.rng.random(x.shape[0]) < p).astype(np.int32)
        y[ring_mask] = 1  # ring rows ARE fraud — that's the campaign
        return y

    def batches(self) -> Iterator[TrafficBatch]:
        spec = self.spec
        start = 0
        ring_budget = 0  # rows left in the currently-injected ring run
        ring_center = 0
        since_ring = spec.ring.every_rows if spec.ring is not None else 0
        for n in spec.arrivals.batch_sizes(spec.total_rows, self.rng):
            x = self.rng.standard_normal((n, D)).astype(np.float32)
            ring_mask = np.zeros(n, bool)
            if spec.ring is not None and start + n > spec.ring.start_row:
                i = 0
                while i < n:
                    if ring_budget > 0:
                        k = min(ring_budget, n - i)
                        c = self._ring_centers[ring_center]
                        x[i : i + k] = (
                            c
                            + self.rng.standard_normal((k, D)).astype(
                                np.float32
                            )
                            * spec.ring.ring_sigma
                        )
                        ring_mask[i : i + k] = True
                        ring_budget -= k
                        i += k
                        continue
                    since_ring += 1
                    if (
                        start + i >= spec.ring.start_row
                        and since_ring >= spec.ring.every_rows
                    ):
                        since_ring = 0
                        ring_budget = spec.ring.ring_size
                        ring_center = int(
                            self.rng.integers(spec.ring.n_rings)
                        )
                    else:
                        i += 1
            y = self._labels_for(x, ring_mask)
            drifted = False
            if spec.drift is not None:
                x, y = spec.drift.apply(x, y, start, self.rng)
                drifted = start + n > spec.drift.onset_row
            yield TrafficBatch(
                rows=x, labels=y, start_row=start, ring_mask=ring_mask,
                drifted=drifted,
            )
            start += n


class DelayedLabelJoiner:
    """The label-settlement model: buffers scored rows and releases labeled
    feedback batches once ``delay_rows`` of further traffic has passed —
    with ``noise_rate`` of labels flipped, the way human review is wrong."""

    def __init__(self, fb: LabelFeedback, seed: int):
        self.fb = fb
        self.rng = np.random.default_rng(seed ^ 0x5EED)
        self._pending: list[tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
        self.released_rows = 0
        self.flipped_rows = 0

    def observe(
        self, batch: TrafficBatch, scores: np.ndarray
    ) -> None:
        self._pending.append(
            (batch.start_row, batch.rows, np.asarray(scores, np.float32),
             batch.labels)
        )

    def due(self, current_row: int) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Yield (rows, scores, labels) feedback batches whose delay has
        elapsed by ``current_row``."""
        while self._pending and (
            current_row - self._pending[0][0] >= self.fb.delay_rows
        ):
            _, x, s, y = self._pending.pop(0)
            y = y.copy()
            if self.fb.noise_rate > 0.0:
                flips = self.rng.random(y.shape[0]) < self.fb.noise_rate
                y = np.where(flips, 1 - y, y).astype(np.int32)
                self.flipped_rows += int(flips.sum())
            self.released_rows += int(y.shape[0])
            # re-chunk to the feedback batch size the joiner would POST
            for lo in range(0, y.shape[0], self.fb.batch):
                hi = lo + self.fb.batch
                yield x[lo:hi], s[lo:hi], y[lo:hi]
