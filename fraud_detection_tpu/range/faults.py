"""Control-plane fault injection: the chaos half of the fraud range.

Production code carries *named injection points* — one ``fire()`` (or
``patched()``) call at each place the range needs to break things:

- ``conductor.promoting.pre_alias`` / ``.mid_alias`` / ``.pre_finalize`` —
  kill a replica mid-promotion (lifecycle/conductor.py);
- ``conductor.gated.pre_alias`` — crash between challenger registration and
  the ``@shadow`` write;
- ``taskq.claim`` / ``taskq.ack`` / ``taskq.visibility_timeout`` /
  ``taskq.countdown`` — delay, duplicate, or strand deliveries past the
  visibility window (service/taskq.py);
- ``netclient.call`` — stall or error the network store/registry client
  (service/netclient.py, riding the same failure surface wire.py's
  ``StalledPeerError`` machinery exposes);
- ``lifecycle.store`` — stall/error the durable lifecycle store
  (lifecycle/store.py), the /monitor/feedback + /lifecycle/status
  degradation scenario;
- ``microbatch.flush`` — add device-latency to the serving flush
  (service/microbatch.py).

Faults are **off by default with zero hot-path overhead**: every hook is a
module-global ``None`` check (one LOAD_GLOBAL + POP_JUMP — no allocation,
no attribute chase), which is why the hooks live in this tiny stdlib-only
module rather than behind a plugin interface. A scenario arms a
:class:`FaultPlan` via ``with plan.armed(): ...``; arming is process-global
(the points fire from worker/ingest/executor threads) and re-entrant
arming is rejected so two scenarios can't blur their blast radius.

This is injection-by-contract, not monkeypatching: the points are part of
the production source, so a refactor that deletes one breaks the chaos
tier loudly instead of silently un-testing the path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

__all__ = [
    "FaultPlan",
    "ReplicaKilled",
    "fire",
    "patched",
    "active_plan",
]


class ReplicaKilled(BaseException):
    """Raised at a kill point to simulate a replica dying mid-step.

    Deliberately a ``BaseException`` subclass: production ``except
    Exception`` ladders (the worker retry ladder, the conductor's
    fit-failure leg) must NOT absorb a simulated process death — a real
    SIGKILL wouldn't run them either. Scenario code catches it explicitly.
    """

    def __init__(self, point: str):
        super().__init__(f"replica killed at fault point {point!r}")
        self.point = point


@dataclass
class _Rule:
    kind: str  # kill | stall | error | patch | call
    point: str
    times: int  # remaining firings; <0 = unlimited
    seconds: float = 0.0
    value: Any = None
    factory: Callable[[], BaseException] | None = None
    fn: Callable[..., Any] | None = None
    fired: int = 0

    def consume(self) -> bool:
        """One firing if the budget allows; thread-safe under the plan lock."""
        if self.times == 0:
            return False
        if self.times > 0:
            self.times -= 1
        self.fired += 1
        return True


class FaultPlan:
    """A recipe of faults keyed by injection point.

    Builder methods return ``self`` so plans read like the scenario they
    implement::

        plan = (FaultPlan()
                .kill("conductor.promoting.pre_alias")
                .patch("taskq.visibility_timeout", 0.05)
                .stall("netclient.call", seconds=0.5, times=3))
        with plan.armed():
            ...drive the service...
    """

    def __init__(self):
        self._rules: dict[str, list[_Rule]] = {}
        self._lock = threading.Lock()
        self.log: list[tuple[str, str]] = []  # (point, kind) firing history

    # -- builders ----------------------------------------------------------
    def _add(self, rule: _Rule) -> "FaultPlan":
        self._rules.setdefault(rule.point, []).append(rule)
        return self

    def kill(self, point: str, times: int = 1) -> "FaultPlan":
        """Raise :class:`ReplicaKilled` at ``point`` (default: once)."""
        return self._add(_Rule("kill", point, times))

    def stall(
        self, point: str, seconds: float, times: int = -1
    ) -> "FaultPlan":
        """Sleep ``seconds`` at ``point`` — a stalled peer/store/device."""
        return self._add(_Rule("stall", point, times, seconds=seconds))

    def error(
        self,
        point: str,
        factory: Callable[[], BaseException],
        times: int = -1,
    ) -> "FaultPlan":
        """Raise ``factory()`` at ``point`` — e.g. a ``StoreError`` whose
        retry budget the client has already exhausted."""
        return self._add(_Rule("error", point, times, factory=factory))

    def patch(self, point: str, value: Any, times: int = -1) -> "FaultPlan":
        """Override the value flowing through a ``patched()`` hook (e.g.
        shrink ``taskq.visibility_timeout`` so claims expire immediately)."""
        return self._add(_Rule("patch", point, times, value=value))

    def call(
        self, point: str, fn: Callable[..., Any], times: int = -1
    ) -> "FaultPlan":
        """Invoke ``fn(**ctx)`` at ``point`` (observation/poisoning hook —
        e.g. corrupt a feedback batch in flight)."""
        return self._add(_Rule("call", point, times, fn=fn))

    # -- firing ------------------------------------------------------------
    def _fire(self, point: str, ctx: dict) -> None:
        actions: list[_Rule] = []
        with self._lock:
            for rule in self._rules.get(point, ()):
                if rule.kind != "patch" and rule.consume():
                    self.log.append((point, rule.kind))
                    actions.append(rule)
        # side effects OUTSIDE the lock: a stall must not serialize every
        # other point behind it
        for rule in actions:
            if rule.kind == "stall":
                time.sleep(rule.seconds)
            elif rule.kind == "call" and rule.fn is not None:
                rule.fn(**ctx)
            elif rule.kind == "error" and rule.factory is not None:
                raise rule.factory()
            elif rule.kind == "kill":
                raise ReplicaKilled(point)

    def _patched(self, point: str, value: Any) -> Any:
        with self._lock:
            for rule in self._rules.get(point, ()):
                if rule.kind == "patch" and rule.consume():
                    self.log.append((point, "patch"))
                    return rule.value
        return value

    def fired(self, point: str | None = None) -> int:
        """How many faults fired (optionally at one point) — scenarios
        assert the fault actually landed, so a refactor that silently
        removes an injection point fails the chaos tier."""
        with self._lock:
            return sum(
                1 for p, _ in self.log if point is None or p == point
            )

    # -- arming ------------------------------------------------------------
    def armed(self) -> "_Armed":
        return _Armed(self)


class _Armed:
    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        global _PLAN
        with _ARM_LOCK:
            if _PLAN is not None:
                raise RuntimeError(
                    "a FaultPlan is already armed — scenarios must not overlap"
                )
            _PLAN = self.plan
        return self.plan

    def __exit__(self, *exc) -> None:
        global _PLAN
        with _ARM_LOCK:
            _PLAN = None


_ARM_LOCK = threading.Lock()
_PLAN: FaultPlan | None = None


def active_plan() -> FaultPlan | None:
    return _PLAN


def fire(point: str, **ctx) -> None:
    """Production-side injection point. Disarmed (the default) this is one
    global load and a jump — zero allocation, zero measurable overhead on
    the serving flush (guarded by the bench.py ≤5% telemetry gate)."""
    plan = _PLAN
    if plan is None:
        return
    plan._fire(point, ctx)


def patched(point: str, value):
    """Value-override injection point (visibility timeouts, countdowns).
    Disarmed it returns ``value`` after one global load."""
    plan = _PLAN
    if plan is None:
        return value
    return plan._patched(point, value)
