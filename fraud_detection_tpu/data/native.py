"""ctypes bindings for the native C++ CSV loader.

The framework's own native data tier (fraud_detection_tpu/native/
csvloader.cpp): mmap + parallel float parsing straight into a numpy buffer.
Replaces the role pandas' C parser plays for the reference (train_model.py:22)
with code we own — and keeps pandas as the transparent fallback when the
toolchain is unavailable (``load_csv_native`` returns None and the caller
falls through).

Build-on-demand: the shared library compiles at first use via the Makefile
(g++ only; no pybind11 — plain C ABI through ctypes).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("fraud_detection_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libfraudcsv.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "csvloader.cpp")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _stale() -> bool:
    try:
        so_mtime = os.path.getmtime(_SO_PATH)
    except OSError:
        return True  # no built library yet
    try:
        src_mtime = os.path.getmtime(_SRC_PATH)
    except OSError:
        return False  # sources absent (e.g. binary-only deploy); use the .so
    return so_mtime < src_mtime


def ensure_built() -> bool:
    """Compile the shared library if missing/stale; False when no toolchain."""
    if not _stale():
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native csv loader build failed (%s); using pandas", e)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        if not ensure_built():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            _bind(lib)
        except (OSError, AttributeError) as e:
            # AttributeError: a stale prebuilt .so missing the current ABI
            # (e.g. binary-only deploy of an old build) — fall back, don't
            # crash data loading.
            log.warning("native csv loader load failed (%s); using pandas", e)
            _lib_failed = True
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.csv_open.argtypes = [ctypes.c_char_p]
    lib.csv_open.restype = ctypes.c_void_p
    lib.csv_close.argtypes = [ctypes.c_void_p]
    lib.csv_close.restype = None
    lib.csv_dims_h.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.csv_dims_h.restype = ctypes.c_int
    lib.csv_header_h.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_long,
    ]
    lib.csv_header_h.restype = ctypes.c_int
    lib.csv_read_h.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_int,
    ]
    lib.csv_read_h.restype = ctypes.c_int


def native_available() -> bool:
    return _load() is not None


def load_csv_native(
    path: str, n_threads: int = 0
) -> tuple[np.ndarray, list[str]] | None:
    """Parse a numeric CSV → (float32 (rows, cols) matrix, column names), or
    None when the native library is unavailable or the file doesn't parse
    (caller falls back to pandas)."""
    lib = _load()
    if lib is None:
        return None
    handle = lib.csv_open(path.encode())
    if not handle:
        return None
    try:
        rows, cols = ctypes.c_long(), ctypes.c_long()
        if lib.csv_dims_h(handle, ctypes.byref(rows), ctypes.byref(cols)) != 0:
            return None
        if rows.value <= 0 or cols.value <= 0:
            return None
        hdr = ctypes.create_string_buffer(1 << 20)
        if lib.csv_header_h(handle, hdr, len(hdr)) != 0:
            return None
        # Match pandas: unwrap CSV double-quoting only — whitespace in names
        # is preserved, so both code paths freeze identical feature_names.
        names = [
            c[1:-1] if len(c) >= 2 and c[0] == '"' and c[-1] == '"' else c
            for c in hdr.value.decode().split(",")
        ]
        out = np.empty((rows.value, cols.value), dtype=np.float32)
        rc = lib.csv_read_h(
            handle,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.value,
            cols.value,
            n_threads,
        )
        if rc != 0:
            log.warning(
                "native csv parse of %s failed (rc=%d); using pandas", path, rc
            )
            return None
        return out, names
    finally:
        lib.csv_close(handle)
