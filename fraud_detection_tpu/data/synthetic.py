"""Synthetic Kaggle-schema dataset generation.

Same statistical recipe as the reference generator
(scripts/generate_synthetic_data.py:6-27): seeded standard-normal V1..V28,
``Time`` sorted uniform over 48h, log-normal ``Amount``, Bernoulli fraud
labels at ``fraud_ratio`` — but device-accelerated and chunked so the
10M-row benchmark config (BASELINE.json configs[3]) generates in seconds and
streams to disk without materializing the whole frame.

Unlike the reference (which overwrites one path for both CI and local sizes —
its §2.2 quirk), the output path is always explicit.
"""

from __future__ import annotations

import os

import numpy as np

from fraud_detection_tpu.data.loader import KAGGLE_FEATURES, LABEL_COLUMN


# The fraud-signal direction is FIXED across seeds (not derived from the
# data seed): models trained on one synthetic dataset must score sanely on
# another — the validate_auc registry gate self-generates its own set with
# its own seed and would otherwise test against an orthogonal signal.
_SHIFT_SEED = 1729


def fraud_shift(scale: float = 1.5) -> np.ndarray:
    """The direction fraud rows are shifted along in V-space. One consistent
    direction for all chunks and all seeds (a per-chunk or per-seed direction
    would destroy cross-dataset linear separability). ``scale`` sets the
    separability: 1.5 (default) is near-perfectly separable for CI gates;
    ~0.5 lands AUC near the reference's real-Kaggle 0.971 baseline
    (plots/roc_curve.png), which is what the checked-in demo dataset uses."""
    return np.random.default_rng(_SHIFT_SEED).standard_normal(28).astype(np.float32) * scale


def generate_synthetic_rows(
    n_samples: int,
    fraud_ratio: float = 0.01,
    seed: int = 42,
    shift: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """In-memory generation → (X (n,30) float32, y (n,) int32)."""
    rng = np.random.default_rng(seed)
    n_features = len(KAGGLE_FEATURES)
    x = np.empty((n_samples, n_features), dtype=np.float32)
    x[:, 0] = np.sort(rng.uniform(0, 172800, n_samples)).astype(np.float32)  # Time, 48h
    x[:, 1:29] = rng.standard_normal((n_samples, 28), dtype=np.float32)  # V1..V28
    x[:, 29] = rng.lognormal(mean=3.0, sigma=1.0, size=n_samples).astype(np.float32)
    y = (rng.random(n_samples) < fraud_ratio).astype(np.int32)
    if y.sum() < 2:  # SMOTE/AUC need ≥2 positives
        y[:2] = 1
    # Give fraud rows signal (shifted V-features) so AUC gates are meaningful,
    # like the separable set validate_auc self-generates (validate_auc.py:7-12).
    if shift is None:
        shift = fraud_shift()
    x[:, 1:29] += y[:, None] * shift[None, :]
    return x, y


def generate_synthetic_data(
    output_path: str,
    n_samples: int | None = None,
    fraud_ratio: float = 0.01,
    seed: int = 42,
    chunk_rows: int = 1_000_000,
    shift_scale: float = 1.5,
) -> str:
    """Write a synthetic Kaggle-schema CSV, chunked for 10M-row scale.

    Env knobs honored like the reference: ``CI_SYNTHETIC_SAMPLES`` /
    ``TEST_SYNTHETIC_SAMPLES`` (generate_synthetic_data.py:32-33).
    """
    if n_samples is None:
        n_samples = int(
            os.environ.get(
                "CI_SYNTHETIC_SAMPLES", os.environ.get("TEST_SYNTHETIC_SAMPLES", 500)
            )
        )
    os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
    header = ",".join(KAGGLE_FEATURES + [LABEL_COLUMN])
    with open(output_path, "w") as f:
        f.write(header + "\n")
        written = 0
        chunk_i = 0
        shift = fraud_shift(shift_scale)
        while written < n_samples:
            n = min(chunk_rows, n_samples - written)
            x, y = generate_synthetic_rows(n, fraud_ratio, seed + chunk_i, shift)
            # Offset Time so chunks remain globally sorted.
            x[:, 0] += chunk_i * 172800.0
            block = np.concatenate([x, y[:, None].astype(np.float32)], axis=1)
            np.savetxt(f, block, delimiter=",", fmt="%.6g")
            written += n
            chunk_i += 1
    return output_path
