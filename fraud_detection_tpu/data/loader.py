"""Dataset loading and index-level split utilities.

The on-disk contract is the Kaggle credit-card schema the reference trains on
(``Time, V1..V28, Amount, Class`` — reference train_model.py:22-29,
preprocess.py:15-22; frozen feature order in models/feature_names.json).

Split/fold index generation runs on host (tiny, data-dependent shapes); the
heavy numerics downstream are device programs.
"""

from __future__ import annotations

import numpy as np

KAGGLE_FEATURES: list[str] = ["Time"] + [f"V{i}" for i in range(1, 29)] + ["Amount"]
LABEL_COLUMN = "Class"


def load_creditcard_csv(path: str) -> tuple[np.ndarray, np.ndarray, list[str]]:
    """Load a Kaggle-schema CSV → (X float32 (n,30), y int32 (n,), names).

    Column order follows the file header (the reference freezes whatever
    order training saw — preprocess.py:54-57); ``Class`` is the label.

    Parsing goes through the native C++ loader (fraud_detection_tpu/native,
    mmap + parallel float parse) when available — set ``NATIVE_CSV=0`` to
    force pandas; any native failure falls back to pandas transparently.
    """
    import os

    if os.environ.get("NATIVE_CSV", "1") != "0":
        from fraud_detection_tpu.data.native import load_csv_native

        native = load_csv_native(path)
        if native is not None:
            mat, names = native
            if LABEL_COLUMN in names:
                li = names.index(LABEL_COLUMN)
                feature_names = [c for c in names if c != LABEL_COLUMN]
                y = mat[:, li].astype(np.int32)
                x = np.ascontiguousarray(np.delete(mat, li, axis=1))
                return x, y, feature_names
            raise ValueError(f"{path} has no '{LABEL_COLUMN}' column")

    import pandas as pd

    df = pd.read_csv(path)
    if LABEL_COLUMN not in df.columns:
        raise ValueError(f"{path} has no '{LABEL_COLUMN}' column")
    feature_names = [c for c in df.columns if c != LABEL_COLUMN]
    x = df[feature_names].to_numpy(dtype=np.float32)
    y = df[LABEL_COLUMN].to_numpy(dtype=np.int32)
    return x, y, feature_names


def stratified_split(
    y: np.ndarray, test_size: float = 0.2, seed: int = 42
) -> tuple[np.ndarray, np.ndarray]:
    """Per-class shuffled index split (sklearn train_test_split(stratify=y)
    semantics — reference train_model.py:31-33). Returns (train_idx, test_idx)."""
    rng = np.random.default_rng(seed)
    train_parts, test_parts = [], []
    for cls in np.unique(y):
        idx = np.where(y == cls)[0]
        rng.shuffle(idx)
        n_test = int(round(len(idx) * test_size))
        test_parts.append(idx[:n_test])
        train_parts.append(idx[n_test:])
    train_idx = np.concatenate(train_parts)
    test_idx = np.concatenate(test_parts)
    rng.shuffle(train_idx)
    rng.shuffle(test_idx)
    return train_idx, test_idx


def stratified_kfold_indices(
    y: np.ndarray, n_splits: int = 5, seed: int = 42, shuffle: bool = True
):
    """Yield (train_idx, val_idx) preserving class ratios per fold
    (sklearn StratifiedKFold semantics — reference train_model.py:49-58)."""
    rng = np.random.default_rng(seed)
    per_class = {}
    for cls in np.unique(y):
        idx = np.where(y == cls)[0]
        if shuffle:
            rng.shuffle(idx)
        per_class[cls] = np.array_split(idx, n_splits)
    for fold in range(n_splits):
        val = np.concatenate([per_class[c][fold] for c in per_class])
        train = np.concatenate(
            [per_class[c][f] for c in per_class for f in range(n_splits) if f != fold]
        )
        yield np.sort(train), np.sort(val)
