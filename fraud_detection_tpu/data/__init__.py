"""Data loading, splits, and synthetic generation."""

from fraud_detection_tpu.data.loader import (  # noqa: F401
    KAGGLE_FEATURES,
    load_creditcard_csv,
    stratified_kfold_indices,
    stratified_split,
)
from fraud_detection_tpu.data.synthetic import generate_synthetic_data  # noqa: F401
