"""Single-prediction client.

Rebuild of predict_single.py:1-78: the ``FraudDetector`` class loads the
artifacts once, validates dict/list/DataFrame-row input, reorders to the
training feature order, and returns (label, probability) — scoring through
the scaler-folded jitted scorer instead of sklearn.
"""

from __future__ import annotations

import argparse
import logging

from fraud_detection_tpu import config
from fraud_detection_tpu.models.logistic import FraudLogisticModel
from fraud_detection_tpu.service.loading import load_production_model

log = logging.getLogger("fraud_detection_tpu.predict_single")


class FraudDetector:
    """Load-once scoring facade (predict_single.py's class of the same
    name)."""

    def __init__(self, model: FraudLogisticModel | None = None):
        if model is None:
            model, source = load_production_model()
            log.info("FraudDetector using model from %s", source)
        self.model = model

    def predict(self, features) -> int:
        label, _ = self.model.score_one(self._coerce(features))
        return label

    def predict_proba(self, features) -> float:
        _, proba = self.model.score_one(self._coerce(features))
        return proba

    def _coerce(self, features):
        # Accept a pandas Series/single-row DataFrame as the reference does
        # (predict_single.py:22-27) without requiring pandas.
        if hasattr(features, "to_dict"):
            d = features.to_dict()
            if d and isinstance(next(iter(d.values())), dict):  # 1-row frame
                d = {k: list(v.values())[0] for k, v in d.items()}
            return d
        return features


# A genuine Kaggle-schema row for the __main__ demo (the reference embeds a
# real dataset row at predict_single.py:43-74; this one is synthetic but
# schema-identical).
_DEMO_ROW = {
    "Time": 406.0, "V1": -2.31, "V2": 1.95, "V3": -1.61, "V4": 4.0,
    "V5": -0.52, "V6": -1.43, "V7": -2.54, "V8": 1.39, "V9": -2.77,
    "V10": -2.77, "V11": 3.2, "V12": -2.9, "V13": -0.6, "V14": -4.29,
    "V15": 0.39, "V16": -1.14, "V17": -2.83, "V18": -0.02, "V19": 0.42,
    "V20": 0.13, "V21": 0.52, "V22": -0.04, "V23": -0.47, "V24": 0.32,
    "V25": 0.04, "V26": 0.18, "V27": 0.26, "V28": -0.14, "Amount": 0.0,
}


def main(argv=None):
    config.apply_device_backend()  # DEVICE=cpu runs without the TPU tunnel
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default=None, help="JSON object of features")
    a = ap.parse_args(argv)
    import json as _json

    features = _json.loads(a.json) if a.json else _DEMO_ROW
    det = FraudDetector()
    label = det.predict(features)
    proba = det.predict_proba(features)
    print(f"prediction: {label} ({'FRAUD' if label else 'legitimate'}), "
          f"P(fraud) = {proba:.6f}")


if __name__ == "__main__":
    main()
