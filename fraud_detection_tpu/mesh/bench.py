"""Switchyard bench probe: sharded-flush scaling over virtual CPU shards.

Run as a SUBPROCESS by ``bench.py``'s ``mesh_serving`` section with
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``
(the backend device count is fixed at init, so the scaling curve needs its
own process). Measures fused-flush throughput at mesh sizes 1/2/4/8 on one
bucket shape and asserts single-device parity: the N-shard program's
scores must bitwise-match the single-device fastlane flush on the same
batch. Prints exactly one JSON line.

Virtual shards share the host's cores, so the curve reports what the
mechanism delivers on THIS machine (XLA runs per-device computations on
separate threads — small GEMVs overlap); ``monotone`` applies a noise
margin rather than demanding strict growth, and the hard CI gate is
parity + the curve existing, mirroring the CPU-fallback honesty rules of
the other bench sections.
"""

from __future__ import annotations

import json
import time

import numpy as np

#: throughput may dip within this factor step-to-step before the curve
#: stops counting as monotone — virtual shards share cores, so ulp-level
#: scheduling noise must not fail a mechanism gate.
MONOTONE_SLACK = 0.85


def _build(seed: int = 7, n_rows: int = 4096):
    from fraud_detection_tpu.monitor.baseline import build_baseline_profile
    from fraud_detection_tpu.ops.logistic import LogisticParams
    from fraud_detection_tpu.ops.scaler import ScalerParams
    from fraud_detection_tpu.ops.scorer import BatchScorer

    d = 30
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((n_rows, d)).astype(np.float32)
    params = LogisticParams(
        coef=rng.standard_normal(d).astype(np.float32),
        intercept=np.float32(-1.0),
    )
    scaler = ScalerParams(
        mean=np.zeros(d, np.float32), scale=np.ones(d, np.float32),
        var=np.ones(d, np.float32), n_samples=np.float32(1),
    )
    scorer = BatchScorer(params, scaler)
    quant_scorer = BatchScorer(params, scaler, io_dtype="int8")
    profile = build_baseline_profile(
        data, scorer.predict_proba(data),
        feature_names=[f"f{i}" for i in range(d)],
    )
    return data, scorer, quant_scorer, profile


def _flush_once(scorer, monitor, rows) -> np.ndarray:
    import jax.numpy as jnp

    from fraud_detection_tpu.ops.scorer import _bucket

    n = len(rows)
    spec = scorer.fused_spec()
    slot = scorer.staging.acquire(_bucket(n, scorer.min_bucket))
    try:
        hx = scorer.stage_rows(slot, list(rows))
        out = monitor.fused_flush(
            jnp.asarray(hx), jnp.asarray(slot.valid), n,
            spec.score_args, spec.score_fn,
            dequant_scale=spec.dequant_scale, score_codes=spec.score_codes,
        )
        return np.asarray(out, np.float32)[:n]
    finally:
        scorer.staging.release(slot)


def run(bucket: int = 65536, reps: int = 8, sizes=(1, 2, 4, 8)) -> dict:
    import jax

    from fraud_detection_tpu.mesh.shardflush import MeshDriftMonitor
    from fraud_detection_tpu.mesh.topology import serving_mesh
    from fraud_detection_tpu.monitor.drift import DriftMonitor

    avail = jax.device_count()
    sizes = tuple(s for s in sizes if s <= avail)
    data, scorer, quant_scorer, profile = _build(n_rows=bucket)
    rows = [data[i] for i in range(bucket)]

    # single-device fastlane reference: the parity target (f32 and the
    # quickwire int8 wire — the quantized mesh flush must bitwise-match
    # the single-device quantized flush, ISSUE 8 acceptance bar)
    ref = _flush_once(scorer, DriftMonitor(profile), rows)
    quant_ref = _flush_once(quant_scorer, DriftMonitor(profile), rows)

    rates: dict[str, float] = {}
    parity = True
    quant_parity = True
    for n_sh in sizes:
        monitor = MeshDriftMonitor(profile, serving_mesh(n_sh))
        scores = _flush_once(scorer, monitor, rows)  # warm/compile + parity
        parity = parity and bool(
            np.array_equal(scores.view(np.uint32), ref.view(np.uint32))
        )
        q_monitor = MeshDriftMonitor(profile, serving_mesh(n_sh))
        q_scores = _flush_once(quant_scorer, q_monitor, rows)
        quant_parity = quant_parity and bool(
            np.array_equal(q_scores.view(np.uint32), quant_ref.view(np.uint32))
        )
        best = 0.0
        for _ in range(3):  # max-of-rounds damps shared-core noise
            t0 = time.perf_counter()
            for _ in range(reps):
                _flush_once(scorer, monitor, rows)
            np.asarray(monitor.shard_window.n_rows)  # drain the chain
            best = max(best, reps / (time.perf_counter() - t0))
        rates[str(n_sh)] = best

    # quantized throughput at the top size only (the parity loop above is
    # the gate; one rate shows the quantized mesh flush is in family)
    top_sh = sizes[-1]
    q_monitor = MeshDriftMonitor(profile, serving_mesh(top_sh))
    _flush_once(quant_scorer, q_monitor, rows)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        _flush_once(quant_scorer, q_monitor, rows)
    np.asarray(q_monitor.shard_window.n_rows)
    quant_top_rate = reps / (time.perf_counter() - t0)

    order = [rates[str(s)] for s in sizes]
    monotone = all(
        b >= a * MONOTONE_SLACK for a, b in zip(order, order[1:])
    )
    top = str(sizes[-1])
    return {
        "device_count": avail,
        "bucket": bucket,
        "mesh_flushes_per_sec": {k: round(v, 2) for k, v in rates.items()},
        "mesh_rows_per_sec_top": round(rates[top] * bucket),
        "mesh_speedup_top_vs_1": round(rates[top] / max(rates["1"], 1e-9), 3),
        "mesh_parity_ok": parity,
        "mesh_quant_parity_ok": quant_parity,
        "mesh_quant_flushes_per_sec_top": round(quant_top_rate, 2),
        "mesh_scaling_monotone": monotone,
        "mesh_sizes_measured": list(sizes),
    }


def main() -> int:
    print(json.dumps(run()), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
