"""The fastlane fused flush as ONE ``shard_map``-mapped program.

``monitor/drift._fused_flush`` collapsed the serving flush to a single
device dispatch (scores + drift-window fold, window donated through); this
module spreads that exact program across the serving mesh's data axis:

- the staged batch rows shard over ``data`` (each device scores 1/N of the
  bucket), the scorer params ride replicated (``score_args`` is a pytree —
  a tensor-parallel family would carry sharded leaves there instead);
- every shard folds ITS rows into ITS OWN drift window: the window pytree
  gains a leading shard axis sharded over ``data``, donated through every
  flush exactly like the single-device window, and **merged only at scrape
  time** (:func:`merge_window`) — no cross-shard collective ever rides the
  hot path, so a flush still costs each shard exactly one dispatch and
  zero communication;
- scrape-time merging is exact for the histogram fields: bin masses are
  sums of {0,1} validity weights, so per-shard partial sums are
  integer-valued f32 — addition order cannot change the merged counts
  until exponential decay (< 1) makes them fractional, at which point the
  divergence vs a single window is one ulp-scale reassociation.

One module-level jitted function (``_sharded_flush``) with the mesh and
score body static: the compile sentinel wraps it (entrypoint
``mesh.sharded_flush``), meshcheck abstractly evaluates it at every
virtual mesh size, and jit caches one executable per (bucket, mesh,
scorer-family) — the bucket ladder discipline is unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fraud_detection_tpu.monitor.baseline import BaselineProfile
from fraud_detection_tpu.monitor.drift import (
    N_CALIB_BINS,
    DriftMonitor,
    DriftWindow,
    _fold_serving_batch,
    _narrow_reasons,
    _narrow_scores,
    _topk_attributions,
)
from fraud_detection_tpu.parallel.compat import shard_map
from fraud_detection_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

#: Row-type inputs (staged rows, validity, per-shard windows/sub-tables)
#: shard over the FLATTENED (data × model) grid: on the historical 1-D
#: mesh the model axis is 1 and this is exactly the old ``P(data)``
#: layout; with MESH_MODEL_DEVICES>1 every device still receives a row
#: block, so narrow families keep full data parallelism on the 2-D mesh.
#: Only the WIDE program (``_sharded_flush_wide``) row-shards over data
#: alone — its rows must be replicated over the model axis so each model
#: shard can contribute its column slice of the cross-weight table.
ROW_SPEC = P((DATA_AXIS, MODEL_AXIS))


def _canonical_row_spec(mesh) -> P:
    """The NORMALIZED form of :data:`ROW_SPEC` on this mesh: shard_map
    drops size-1 axes from its output shardings, so donated state seeded
    by ``device_put`` must use the same normalized spec — otherwise the
    first flush of every bucket sees a different arg sharding than steady
    state and the executable compiles twice (the sentinel-exactness tests
    would catch the duplicate)."""
    shape = dict(mesh.shape)
    axes = tuple(
        ax for ax in (DATA_AXIS, MODEL_AXIS) if int(shape.get(ax, 1)) > 1
    )
    if not axes:
        return P()
    # a single surviving axis must be the BARE name, not a 1-tuple:
    # PartitionSpec(('data',)) != PartitionSpec('data') for sharding
    # equality even though they partition identically
    return P(axes if len(axes) > 1 else axes[0])


def init_sharded_window(
    n_shards: int,
    n_features: int,
    n_feature_bins: int,
    n_score_bins: int,
    mesh=None,
    n_calib_bins: int = N_CALIB_BINS,
) -> DriftWindow:
    """Per-shard drift windows: every :class:`DriftWindow` leaf gains a
    leading ``(n_shards,)`` axis, laid out over the mesh's data axis when a
    mesh is given (so donation keeps each shard's slice on its device)."""
    sharding = (
        NamedSharding(mesh, _canonical_row_spec(mesh))
        if mesh is not None
        else None
    )

    def z(*shape):
        buf = np.zeros((n_shards, *shape), np.float32)
        if sharding is None:
            return jnp.asarray(buf)
        return jax.device_put(buf, sharding)

    return DriftWindow(
        feature_counts=z(n_features, n_feature_bins),
        score_counts=z(n_score_bins),
        calib_count=z(n_calib_bins),
        calib_conf=z(n_calib_bins),
        calib_label=z(n_calib_bins),
        n_rows=z(),
    )


@jax.jit
def _merge_window(shard_window: DriftWindow) -> DriftWindow:
    """Scrape-time reduce: sum the per-shard windows over the shard axis."""
    return jax.tree.map(lambda t: jnp.sum(t, axis=0), shard_window)


def merge_window(shard_window: DriftWindow) -> DriftWindow:
    return _merge_window(shard_window)


@jax.jit
def _merge_total(
    shard_window: DriftWindow, base_window: DriftWindow
) -> DriftWindow:
    """Merged shard evidence + the host-side window (calibration state from
    labeled feedback replays lives there) — the window stats() reads."""
    merged = jax.tree.map(lambda t: jnp.sum(t, axis=0), shard_window)
    return jax.tree.map(lambda a, b: a + b, merged, base_window)


def _shard_body(
    window: DriftWindow,
    x: jax.Array,
    valid: jax.Array,
    decay: jax.Array,
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,
    dequant_scale=None,
    explain_args=None,
    *,
    score_fn,
    score_codes: bool = True,
    explain_k: int = 0,
    out_dtype=jnp.float32,
):
    """Per-shard flush body under shard_map: identical math to
    ``drift._fused_flush`` (``drift._fused_flush_quant`` when a
    ``dequant_scale`` rides along — the quickwire quantized wire;
    ``drift._fused_flush_explain``/``_quant_explain`` when ``explain_k >
    0`` adds the lantern reason-code leg) over this shard's rows and THIS
    shard's window (the leading shard axis arrives as size 1 inside the
    block view). The global ``decay`` applies to every shard, so the
    merged window evolves exactly as the single-device window would for
    the same batch stream. Reason codes are per-row over the full feature
    axis (columns are unsharded), so each shard emits ITS rows' top-k with
    no collective — row-sharded exactly like the scores."""
    w = jax.tree.map(lambda t: t[0], window)
    xf = x.astype(jnp.float32)
    if dequant_scale is not None:
        xf = xf * dequant_scale
    scores = score_fn(score_args, x if score_codes else xf).astype(jnp.float32)
    new = _fold_serving_batch(
        w, xf, scores, valid, decay, feature_edges, score_edges
    )
    shard_window = jax.tree.map(lambda t: t[None], new)
    if explain_k > 0:
        idx, val = _topk_attributions(xf, explain_args, explain_k)
        idx, val = _narrow_reasons(idx, val, x.shape[1], out_dtype)
        return _narrow_scores(scores, out_dtype), idx, val, shard_window
    return _narrow_scores(scores, out_dtype), shard_window


def _shard_body_explain(
    window, x, valid, decay, feature_edges, score_edges, score_args,
    explain_args, *, score_fn, explain_k, out_dtype,
):
    """Positional adapter for the plain-wire explain shard body (shard_map
    maps arguments positionally against ``in_specs``, so the optional
    ``dequant_scale`` slot cannot simply be skipped)."""
    return _shard_body(
        window, x, valid, decay, feature_edges, score_edges, score_args,
        None, explain_args,
        score_fn=score_fn, explain_k=explain_k, out_dtype=out_dtype,
    )


@partial(
    jax.jit, static_argnames=("score_fn", "mesh", "out_dtype"),
    donate_argnums=(0,),
)
def _sharded_flush(
    window: DriftWindow,  # per-shard windows, leading axis = shard
    x: jax.Array,  # (b, d) staged bucket, b % n_shards == 0
    valid: jax.Array,  # (b,)
    decay: jax.Array,  # () global drift forgetting factor
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree, replicated (linear family) — TP-sharded leaves OK
    *,
    score_fn,
    mesh,
    out_dtype=jnp.float32,
):
    """The switchyard flush program: ONE dispatch executes the fused
    score+drift-fold on every shard of the serving mesh. Registered in
    meshcheck (``mesh.sharded_flush``) and the compile sentinel."""
    mapped = shard_map(
        partial(_shard_body, score_fn=score_fn, out_dtype=out_dtype),
        mesh=mesh,
        in_specs=(
            ROW_SPEC,      # window: shard axis (flattened grid)
            ROW_SPEC,      # x: rows
            ROW_SPEC,      # valid: rows
            P(),           # decay
            P(),           # feature_edges
            P(),           # score_edges
            P(),           # score_args (replicated pytree prefix)
        ),
        out_specs=(ROW_SPEC, ROW_SPEC),
        check_vma=False,
    )
    return mapped(
        window, x, valid, decay, feature_edges, score_edges, score_args
    )


@partial(
    jax.jit,
    static_argnames=("score_fn", "mesh", "score_codes", "out_dtype"),
    donate_argnums=(0,),
)
def _sharded_flush_quant(
    window: DriftWindow,  # per-shard windows, leading axis = shard
    x: jax.Array,  # (b, d) int8 quantization codes, b % n_shards == 0
    valid: jax.Array,  # (b,)
    decay: jax.Array,  # () global drift forgetting factor
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree, replicated
    dequant_scale: jax.Array,  # (d,) replicated per-feature dequant scale
    *,
    score_fn,
    mesh,
    score_codes: bool,
    out_dtype=jnp.float32,
):
    """The quickwire mesh flush: the fused dequant·score·drift program as
    ONE shard_map dispatch over the data axis — ``MESH_FLUSH_DEVICES>1``
    keeps the quantized wire. Same shard body as :func:`_sharded_flush`
    (so N-shard quantized scores bitwise-match the single-device quantized
    flush), with the codes dequantized per shard for the drift fold.
    Registered in meshcheck (``mesh.quickwire_flush``) and the compile
    sentinel."""
    mapped = shard_map(
        partial(
            _shard_body,
            score_fn=score_fn,
            score_codes=score_codes,
            out_dtype=out_dtype,
        ),
        mesh=mesh,
        in_specs=(
            ROW_SPEC,      # window: shard axis (flattened grid)
            ROW_SPEC,      # x: rows
            ROW_SPEC,      # valid: rows
            P(),           # decay
            P(),           # feature_edges
            P(),           # score_edges
            P(),           # score_args (replicated pytree prefix)
            P(),           # dequant_scale (replicated)
        ),
        out_specs=(ROW_SPEC, ROW_SPEC),
        check_vma=False,
    )
    return mapped(
        window, x, valid, decay, feature_edges, score_edges, score_args,
        dequant_scale,
    )


@partial(
    jax.jit,
    static_argnames=("score_fn", "mesh", "explain_k", "out_dtype"),
    donate_argnums=(0,),
)
def _sharded_flush_explain(
    window: DriftWindow,  # per-shard windows, leading axis = shard
    x: jax.Array,  # (b, d) staged bucket, b % n_shards == 0
    valid: jax.Array,  # (b,)
    decay: jax.Array,  # () global drift forgetting factor
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree, replicated
    explain_args,  # (coef (d,), background_mean (d,)), replicated
    *,
    score_fn,
    mesh,
    explain_k: int,
    out_dtype=jnp.float32,
):
    """The lantern mesh flush: fused score+explain+drift as ONE shard_map
    dispatch over the data axis. Reason codes are row-sharded like the
    scores (each shard top-k's its own rows over the replicated explain
    params — no new collective on the hot path), so N-shard fused explain
    output bitwise-matches the single-device lantern flush. Registered in
    meshcheck (``mesh.lantern_flush``) and the compile sentinel."""
    mapped = shard_map(
        partial(
            _shard_body_explain,
            score_fn=score_fn,
            explain_k=explain_k,
            out_dtype=out_dtype,
        ),
        mesh=mesh,
        in_specs=(
            ROW_SPEC,      # window: shard axis (flattened grid)
            ROW_SPEC,      # x: rows
            ROW_SPEC,      # valid: rows
            P(),           # decay
            P(),           # feature_edges
            P(),           # score_edges
            P(),           # score_args (replicated pytree prefix)
            P(),           # explain_args (replicated)
        ),
        out_specs=(ROW_SPEC, ROW_SPEC, ROW_SPEC, ROW_SPEC),
        check_vma=False,
    )
    return mapped(
        window, x, valid, decay, feature_edges, score_edges, score_args,
        explain_args,
    )


@partial(
    jax.jit,
    static_argnames=(
        "score_fn", "mesh", "score_codes", "explain_k", "out_dtype"
    ),
    donate_argnums=(0,),
)
def _sharded_flush_quant_explain(
    window: DriftWindow,  # per-shard windows, leading axis = shard
    x: jax.Array,  # (b, d) int8 quantization codes, b % n_shards == 0
    valid: jax.Array,  # (b,)
    decay: jax.Array,  # () global drift forgetting factor
    feature_edges: jax.Array,
    score_edges: jax.Array,
    score_args,  # pytree, replicated
    dequant_scale: jax.Array,  # (d,) replicated per-feature dequant scale
    explain_args,  # (coef (d,), background_mean (d,)), replicated
    *,
    score_fn,
    mesh,
    score_codes: bool,
    explain_k: int,
    out_dtype=jnp.float32,
):
    """The lantern mesh flush on the quantized wire: fused
    dequant·score·explain·drift as ONE shard_map dispatch — each shard
    attributes over ITS dequantized rows (the multiply already paid for
    the drift fold), reason codes row-sharded, no new collectives."""
    mapped = shard_map(
        partial(
            _shard_body,
            score_fn=score_fn,
            score_codes=score_codes,
            explain_k=explain_k,
            out_dtype=out_dtype,
        ),
        mesh=mesh,
        in_specs=(
            ROW_SPEC,      # window: shard axis (flattened grid)
            ROW_SPEC,      # x: rows
            ROW_SPEC,      # valid: rows
            P(),           # decay
            P(),           # feature_edges
            P(),           # score_edges
            P(),           # score_args (replicated pytree prefix)
            P(),           # dequant_scale (replicated)
            P(),           # explain_args (replicated)
        ),
        out_specs=(ROW_SPEC, ROW_SPEC, ROW_SPEC, ROW_SPEC),
        check_vma=False,
    )
    return mapped(
        window, x, valid, decay, feature_edges, score_edges, score_args,
        dequant_scale, explain_args,
    )


def init_sharded_ledger(n_shards: int, state, slots: int, mesh=None):
    """Per-shard ledger sub-tables: every :class:`LedgerState` leaf gains a
    leading ``(n_shards,)`` axis over the data axis. Shard ``s`` only ever
    touches slots with ``slot mod n_shards == s`` (the batcher's
    hash-mod-shard row placement — ledger/placement), so the sub-tables
    have disjoint slot support and the scrape-time merge is an exact sum.
    A host snapshot seeds shard ``slot mod n_shards``'s sub-table with its
    own slots and zeros elsewhere, so restore round-trips bitwise."""
    from fraud_detection_tpu.ledger.state import LedgerState, init_state

    base = state if state is not None else init_state(slots)
    sharding = (
        NamedSharding(mesh, _canonical_row_spec(mesh))
        if mesh is not None
        else None
    )
    slot_shard = np.arange(slots) % n_shards

    def split(leaf, owner_split: bool):
        leaf = np.asarray(leaf)
        out = np.zeros((n_shards, *leaf.shape), leaf.dtype)
        if owner_split and leaf.ndim >= 1:
            for s in range(n_shards):
                mask = slot_shard == s
                out[s][mask] = leaf[mask]
        else:
            out[0] = leaf  # scalars (collision/eviction totals) on shard 0
        if sharding is None:
            return jnp.asarray(out)
        return jax.device_put(out, sharding)

    return LedgerState(
        acc=split(base.acc, True),
        last_ts=split(base.last_ts, True),
        fingerprint=split(base.fingerprint, True),
        collisions=split(base.collisions, False),
        evictions=split(base.evictions, False),
    )


@jax.jit
def _merge_ledger(shard_ledger):
    """Scrape-time reduce of the per-shard sub-tables. Disjoint slot
    support (hash-mod-shard placement) makes the sums exact; the
    fingerprint merges by max (a uint32 sum could wrap)."""
    from fraud_detection_tpu.ledger.state import LedgerState

    return LedgerState(
        acc=jnp.sum(shard_ledger.acc, axis=0),
        last_ts=jnp.max(shard_ledger.last_ts, axis=0),
        fingerprint=jnp.max(shard_ledger.fingerprint, axis=0),
        collisions=jnp.sum(shard_ledger.collisions, axis=0),
        evictions=jnp.sum(shard_ledger.evictions, axis=0),
    )


def _shard_body_ledger(
    window, ledger, x, valid, decay, feature_edges, score_edges, score_args,
    slot_idx, fp, ts, has_entity, null_features, halflife_s,
    dequant_scale=None, explain_args=None,
    *, score_fn, explain_k=0, amount_col=-1, out_dtype=jnp.float32,
):
    """Per-shard ledger flush body under shard_map: traces the SAME
    ``drift._ledger_serving_body`` expression the single-device program
    runs — identical math by construction (the ``_fold_serving_batch``
    discipline) — over this shard's rows, ITS window slice AND its ledger
    sub-table. The batcher places rows so a shard only sees entities whose
    slot it owns (``slot mod n_shards == shard``) — the sub-tables stay
    disjoint and no collective ever rides the flush."""
    from fraud_detection_tpu.monitor.drift import _ledger_serving_body

    w = jax.tree.map(lambda t: t[0], window)
    led = jax.tree.map(lambda t: t[0], ledger)
    out = _ledger_serving_body(
        w, led, x, valid, decay, feature_edges, score_edges, score_args,
        slot_idx, fp, ts, has_entity, null_features, halflife_s,
        dequant_scale, explain_args,
        score_fn=score_fn, explain_k=explain_k, amount_col=amount_col,
        out_dtype=out_dtype,
    )
    lead = lambda tree: jax.tree.map(lambda t: t[None], tree)  # noqa: E731
    if explain_k > 0:
        scores, idx, val, new_w, new_led = out
        return scores, idx, val, lead(new_w), lead(new_led)
    scores, new_w, new_led = out
    return scores, lead(new_w), lead(new_led)


@partial(
    jax.jit,
    static_argnames=("score_fn", "mesh", "explain_k", "amount_col",
                     "out_dtype", "has_dequant", "has_explain"),
    donate_argnums=(0, 1),
)
def _sharded_flush_ledger(
    window: DriftWindow,  # per-shard windows, leading axis = shard
    ledger,  # per-shard ledger sub-tables, leading axis = shard
    x: jax.Array,  # (b, d_base) staged bucket, b % n_shards == 0
    valid: jax.Array,  # (b,)
    decay: jax.Array,  # () global drift forgetting factor
    feature_edges: jax.Array,  # (d_base + K, bins - 1) widened edges
    score_edges: jax.Array,
    score_args,  # pytree, replicated (raw-space widened params)
    slot_idx: jax.Array,  # (b,) int32, placement-aligned (slot%N == shard)
    fp: jax.Array,  # (b,) uint32
    ts: jax.Array,  # (b,) f32
    has_entity: jax.Array,  # (b,) f32
    null_features: jax.Array,  # (K,) replicated
    halflife_s: jax.Array,  # () replicated
    dequant_scale=None,  # (d_base,) replicated, int8 wire only
    explain_args=None,  # replicated lantern params, explain_k > 0 only
    *,
    score_fn,
    mesh,
    explain_k: int = 0,
    amount_col: int = -1,
    out_dtype=jnp.float32,
    has_dequant: bool = False,
    has_explain: bool = False,
):
    """The switchyard ledger flush: the widened stateful program as ONE
    shard_map dispatch over the data axis — rows, reason codes, per-shard
    windows AND per-shard ledger sub-tables all row/shard-local, no
    collectives. Registered in meshcheck (``mesh.ledger_flush``) and the
    compile sentinel. ``has_dequant``/``has_explain`` are static so the
    in_specs tuple matches the (pytree-None) optional params."""
    in_specs = [
        ROW_SPEC,      # window: shard axis (flattened grid)
        ROW_SPEC,      # ledger: shard axis (flattened grid)
        ROW_SPEC,      # x: rows
        ROW_SPEC,      # valid: rows
        P(),           # decay
        P(),           # feature_edges
        P(),           # score_edges
        P(),           # score_args (replicated pytree prefix)
        ROW_SPEC,      # slot_idx: rows
        ROW_SPEC,      # fp: rows
        ROW_SPEC,      # ts: rows
        ROW_SPEC,      # has_entity: rows
        P(),           # null_features
        P(),           # halflife_s
        P(),           # dequant_scale (replicated; pytree-None when f32)
        P(),           # explain_args (replicated; pytree-None when off)
    ]
    out_specs = (
        (ROW_SPEC,) * 5 if explain_k > 0 else (ROW_SPEC, ROW_SPEC, ROW_SPEC)
    )
    mapped = shard_map(
        partial(
            _shard_body_ledger,
            score_fn=score_fn,
            explain_k=explain_k,
            amount_col=amount_col,
            out_dtype=out_dtype,
        ),
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    return mapped(
        window, ledger, x, valid, decay, feature_edges, score_edges,
        score_args, slot_idx, fp, ts, has_entity, null_features, halflife_s,
        dequant_scale, explain_args,
    )


def _wide_shard_body(
    window, x, valid, decay, feature_edges, score_edges, score_args,
    wide_local, fp, has_entity, dequant_scale=None, explain_args=None,
    *, cross_spec, explain_k=0, out_dtype=jnp.float32,
):
    """Per-(data,model)-shard broadside body under shard_map: traces the
    SAME ``drift._wide_serving_body`` expression the single-device program
    runs, with ``model_axis`` bound — the local column slice of the
    cross-weight table gathers its in-range buckets and ONE ``psum`` over
    the model axis assembles the widened block (the only collective on the
    wide hot path). Rows are replicated over the model axis; the body
    masks the drift fold to model-rank 0, so the per-shard windows still
    merge exactly at scrape time."""
    from fraud_detection_tpu.monitor.drift import _wide_serving_body

    w = jax.tree.map(lambda t: t[0], window)
    out = _wide_serving_body(
        w, x, valid, decay, feature_edges, score_edges, score_args,
        wide_local, fp, has_entity, dequant_scale, explain_args,
        cross_spec=cross_spec, explain_k=explain_k, out_dtype=out_dtype,
        model_axis=MODEL_AXIS,
    )
    lead = lambda tree: jax.tree.map(lambda t: t[None], tree)  # noqa: E731
    if explain_k > 0:
        scores, ridx, rval, new_w = out
        return scores, ridx, rval, lead(new_w)
    scores, new_w = out
    return scores, lead(new_w)


@partial(
    jax.jit,
    static_argnames=(
        "cross_spec", "mesh", "explain_k", "out_dtype", "has_dequant",
        "has_explain",
    ),
    donate_argnums=(0,),
)
def _sharded_flush_wide(
    window: DriftWindow,  # per-(data,model)-shard windows, leading axis
    x: jax.Array,  # (b, n_base) staged bucket, b % n_data == 0
    valid: jax.Array,  # (b,)
    decay: jax.Array,  # () global drift forgetting factor
    feature_edges: jax.Array,  # (n_base + n_cross, bins - 1) widened edges
    score_edges: jax.Array,
    score_args,  # (widened raw-space coef, intercept), replicated
    wide_table: jax.Array,  # (buckets,) column-sharded over the MODEL axis
    fp: jax.Array,  # (b,) uint32 entity fingerprint, row-sharded over data
    has_entity: jax.Array,  # (b,) f32
    dequant_scale=None,  # (n_base,) replicated, int8 wire only
    explain_args=None,  # replicated lantern params, explain_k > 0 only
    *,
    cross_spec,  # static ops/crosses.CrossSpec
    mesh,
    explain_k: int = 0,
    out_dtype=jnp.float32,
    has_dequant: bool = False,
    has_explain: bool = False,
):
    """The broadside mesh flush: the tensor-parallel wide program as ONE
    shard_map dispatch over the 2-D (data × model) serving mesh. Rows
    shard over ``data`` (replicated over ``model``), the ``WIDE_BUCKETS``
    cross-weight table column-shards over ``model`` (``score_args`` leaves
    sharded over the model axis — the TP the topology always promised),
    and exactly ONE ``psum`` over the model axis assembles the per-row
    widened block — scores and reason codes then compute replicated per
    model group, bitwise the single-device wide flush. Per-(data,model)-
    shard windows are donated through and merged only at scrape, exactly
    like every other mesh flush. Registered in meshcheck
    (``mesh.broadside_flush``) and the compile sentinel."""
    in_specs = (
        ROW_SPEC,        # window: shard axis (flattened grid)
        P(DATA_AXIS),    # x: rows (replicated over model)
        P(DATA_AXIS),    # valid: rows
        P(),             # decay
        P(),             # feature_edges
        P(),             # score_edges
        P(),             # score_args (replicated pytree prefix)
        P(MODEL_AXIS),   # wide_table: column-sharded over model
        P(DATA_AXIS),    # fp: rows
        P(DATA_AXIS),    # has_entity: rows
        P(),             # dequant_scale (replicated; pytree-None when f32)
        P(),             # explain_args (replicated; pytree-None when off)
    )
    out_specs = (
        (P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), ROW_SPEC)
        if explain_k > 0
        else (P(DATA_AXIS), ROW_SPEC)
    )
    mapped = shard_map(
        partial(
            _wide_shard_body,
            cross_spec=cross_spec,
            explain_k=explain_k,
            out_dtype=out_dtype,
        ),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return mapped(
        window, x, valid, decay, feature_edges, score_edges, score_args,
        wide_table, fp, has_entity, dequant_scale, explain_args,
    )


class MeshDriftMonitor(DriftMonitor):
    """Drift monitoring for the sharded serving mesh.

    Drop-in for :class:`~fraud_detection_tpu.monitor.drift.DriftMonitor`
    behind the micro-batcher's fused target: ``fused_flush`` dispatches the
    shard_map program instead of the single-device one, keeping the
    one-dispatch-per-flush contract while the batch spans the mesh. Live
    drift evidence accumulates in the per-shard windows; labeled feedback
    replays keep using the inherited host-side ``update()`` path (they fold
    into the base window's calibration state), and ``stats()`` reads the
    merged total — per-shard windows are reduced only at scrape time."""

    def __init__(
        self,
        profile: BaselineProfile,
        mesh,
        halflife_rows: float | None = None,
        min_bucket: int = 8,
    ):
        shape = dict(mesh.shape)
        n_data = int(shape[DATA_AXIS])
        n_model = int(shape.get(MODEL_AXIS, 1))
        n_shards = n_data * n_model
        if n_shards & (n_shards - 1):
            raise ValueError(
                f"mesh grid must be a power of two, got {n_data}×{n_model}"
            )
        if n_shards > min_bucket:
            # The micro-batcher buckets and warms by the SCORER's
            # min_bucket, not this monitor's — a shard count above the
            # smallest bucket would fail every lone-request flush (8 rows
            # cannot shard over 16 devices). Narrow families row-shard
            # over the FLATTENED grid, so the bound covers data×model.
            # Refuse loudly at construction instead of crashing the
            # warmup ladder.
            raise ValueError(
                f"{n_data}×{n_model} = {n_shards} flush shards exceed the "
                f"smallest flush bucket ({min_bucket}) — every bucket must "
                "hand each shard at least one row (see "
                "topology.MAX_FLUSH_SHARDS)"
            )
        super().__init__(
            profile,
            halflife_rows=halflife_rows,
            min_bucket=min_bucket,
        )
        self.mesh = mesh
        self.n_data = n_data
        self.n_model = n_model
        self.n_shards = n_shards
        # broadside: the model-axis-placed cross table cache (one
        # device_put per swap — see _placed_wide_table)
        self._wide_placed = None
        self._wide_src = None
        self.shard_window = init_sharded_window(
            n_shards,
            profile.n_features,
            profile.feature_counts.shape[1],
            profile.score_counts.shape[0],
            mesh=mesh,
        )

    def fused_flush(
        self,
        x: jax.Array,
        valid: jax.Array,
        n_live: int,
        score_args,
        score_fn,
        dequant_scale=None,
        score_codes: bool = True,
        out_dtype=jnp.float32,
        explain_args=None,
        explain_k: int = 0,
        ledger_rows=None,
        wide_args=None,
        wide_rows=None,
    ):
        """Score one staged bucket across every shard AND fold each shard's
        rows into its own window — one dispatch, no hot-path collectives
        except the wide family's single model-axis ``psum`` (the quickwire
        ``_sharded_flush_quant`` program when ``dequant_scale`` rides
        along for a quantized wire; the lantern
        ``_sharded_flush_explain``/``_quant_explain`` when ``explain_k >
        0`` adds the row-sharded reason-code leg; the stateful
        ``_sharded_flush_ledger`` when the ledger is bound and
        ``ledger_rows`` rides along — per-shard entity sub-tables donated
        through beside the per-shard windows; the broadside
        ``_sharded_flush_wide`` when ``wide_args``/``wide_rows`` ride
        along — the cross-weight table column-sharded over the model
        axis). Same locking contract as the base class: the critical
        section is the async dispatch plus the donated-state store."""
        # graftcheck: hot-path
        decay = self._decay_for(n_live)
        if wide_args is not None and wide_rows is not None:
            return self._wide_flush(
                x, valid, decay, n_live, score_args, dequant_scale,
                out_dtype, explain_args, explain_k, wide_args, wide_rows,
            )
        if ledger_rows is not None and self.ledger is not None:
            return self._ledger_flush(
                x, valid, decay, n_live, score_args, score_fn,
                dequant_scale, out_dtype, explain_args, explain_k,
                ledger_rows,
            )
        explain_k = min(int(explain_k), int(x.shape[1]))  # k ≥ d clamps to d
        with self._lock:
            if explain_k > 0 and explain_args is not None:
                if dequant_scale is None:
                    scores, eidx, eval_, self.shard_window = (
                        _sharded_flush_explain(
                            self.shard_window,
                            x,
                            valid,
                            decay,
                            self._feature_edges,
                            self._score_edges,
                            score_args,
                            explain_args,
                            score_fn=score_fn,
                            mesh=self.mesh,
                            explain_k=explain_k,
                            out_dtype=out_dtype,
                        )
                    )
                else:
                    scores, eidx, eval_, self.shard_window = (
                        _sharded_flush_quant_explain(
                            self.shard_window,
                            x,
                            valid,
                            decay,
                            self._feature_edges,
                            self._score_edges,
                            score_args,
                            dequant_scale,
                            explain_args,
                            score_fn=score_fn,
                            mesh=self.mesh,
                            score_codes=score_codes,
                            explain_k=explain_k,
                            out_dtype=out_dtype,
                        )
                    )
                self.rows_seen += n_live
                return scores, eidx, eval_
            if dequant_scale is None:
                scores, self.shard_window = _sharded_flush(
                    self.shard_window,
                    x,
                    valid,
                    decay,
                    self._feature_edges,
                    self._score_edges,
                    score_args,
                    score_fn=score_fn,
                    mesh=self.mesh,
                    out_dtype=out_dtype,
                )
            else:
                scores, self.shard_window = _sharded_flush_quant(
                    self.shard_window,
                    x,
                    valid,
                    decay,
                    self._feature_edges,
                    self._score_edges,
                    score_args,
                    dequant_scale,
                    score_fn=score_fn,
                    mesh=self.mesh,
                    score_codes=score_codes,
                    out_dtype=out_dtype,
                )
            self.rows_seen += n_live
        return scores

    def _window_for_stats(self) -> DriftWindow:
        return _merge_total(self.shard_window, self.window)

    # -- lifeboat: the per-shard windows are durable state too -------------
    def shard_window_snapshot(self) -> DriftWindow:
        """Host copy of the per-shard windows (leading shard axis),
        materialized under the lock — the lifeboat snapshot carries them
        so a warm restart restores per-shard drift evidence exactly, not a
        merged approximation."""
        with self._lock:
            return DriftWindow(
                *(np.asarray(leaf) for leaf in self.shard_window)
            )

    def _restore_windows_locked(self, window, shard_window) -> bool:
        ok = super()._restore_windows_locked(window, shard_window)
        if shard_window is None:
            # snapshot from a single-device run: base window restored,
            # per-shard evidence starts cold — degraded, not broken
            return ok
        shapes = tuple(np.shape(np.asarray(leaf)) for leaf in shard_window)
        want = tuple(tuple(leaf.shape) for leaf in self.shard_window)
        if shapes != want:
            import logging

            logging.getLogger("fraud_detection_tpu.lifeboat").warning(
                "per-shard window restore skipped: snapshot shard shapes "
                "%s != live %s (mesh geometry changed since the snapshot)",
                shapes, want,
            )
            return ok
        sharding = NamedSharding(self.mesh, _canonical_row_spec(self.mesh))
        self.shard_window = DriftWindow(
            *(
                jax.device_put(np.asarray(leaf, np.float32), sharding)
                for leaf in shard_window
            )
        )
        return ok

    def _placed_wide_table(self, wide_table):
        """The cross-weight table laid out with the model-axis sharding
        the wide shard_map expects, cached per table identity — without
        this every flush would reshard the full WIDE_BUCKETS vector from
        its single-device layout inside the dispatch (the same per-call
        layout cost ``_canonical_row_spec`` exists to avoid for donated
        windows). One ``device_put`` per swap, then pure reads."""
        placed = getattr(self, "_wide_placed", None)
        if placed is None or self._wide_src is not wide_table:
            placed = jax.device_put(
                wide_table, NamedSharding(self.mesh, P(MODEL_AXIS))
            )
            self._wide_placed = placed
            self._wide_src = wide_table
        return placed

    def _wide_flush(
        self, x, valid, decay, n_live, score_args, dequant_scale,
        out_dtype, explain_args, explain_k, wide_args, wide_rows,
    ):
        """Dispatch the 2-D broadside flush (``_sharded_flush_wide``) —
        rows over data, the cross-weight table column-sharded over model,
        per-(data,model)-shard windows donated through, exactly one
        model-axis ``psum``."""
        # graftcheck: hot-path
        cross_spec, wide_table = wide_args
        if cross_spec.buckets % self.n_model:
            # must precede _placed_wide_table: the device_put with
            # P(MODEL_AXIS) raises an opaque XLA uneven-sharding error on
            # the same condition
            raise ValueError(
                f"wide table of {cross_spec.buckets} buckets does not "
                f"column-shard over {self.n_model} model devices"
            )
        wide_table = self._placed_wide_table(wide_table)
        fp, has_entity = wide_rows
        explain_k = min(int(explain_k), int(x.shape[1]) + cross_spec.n_cross)
        explain_k = explain_k if explain_args is not None else 0
        with self._lock:
            out = _sharded_flush_wide(
                self.shard_window,
                x,
                valid,
                decay,
                self._feature_edges,
                self._score_edges,
                score_args,
                wide_table,
                fp,
                has_entity,
                dequant_scale,
                explain_args if explain_k > 0 else None,
                cross_spec=cross_spec,
                mesh=self.mesh,
                explain_k=explain_k,
                out_dtype=out_dtype,
                has_dequant=dequant_scale is not None,
                has_explain=explain_k > 0,
            )
            if explain_k > 0:
                scores, eidx, eval_, self.shard_window = out
                self.rows_seen += n_live
                return scores, eidx, eval_
            scores, self.shard_window = out
            self.rows_seen += n_live
        return scores

    # -- ledger: per-shard sub-tables --------------------------------------
    def bind_ledger(self, spec, state=None) -> None:
        """Shard the entity table over the data axis: shard ``s`` owns the
        slots with ``slot mod n_shards == s`` (the batcher's placement
        contract — ledger/placement.shard_placement), donated through every
        sharded flush and merged only at scrape/snapshot time."""
        with self._lock:
            self.ledger_spec = spec
            self.ledger = init_sharded_ledger(
                self.n_shards, state, spec.slots, mesh=self.mesh
            )
            self._ledger_null = jnp.asarray(spec.null_features)
            self._ledger_halflife = jnp.float32(spec.halflife_s)

    def _ledger_for_stats(self):
        return _merge_ledger(self.ledger)

    def _ledger_flush(
        self, x, valid, decay, n_live, score_args, score_fn,
        dequant_scale, out_dtype, explain_args, explain_k, ledger_rows,
    ):
        # graftcheck: hot-path
        slot_idx, fp, ts, has_entity = ledger_rows
        spec = self.ledger_spec
        explain_k = min(
            int(explain_k), int(x.shape[1]) + len(spec.null_features)
        )
        explain_k = explain_k if explain_args is not None else 0
        with self._lock:
            out = _sharded_flush_ledger(
                self.shard_window,
                self.ledger,
                x,
                valid,
                decay,
                self._feature_edges,
                self._score_edges,
                score_args,
                slot_idx,
                fp,
                ts,
                has_entity,
                self._ledger_null,
                self._ledger_halflife,
                dequant_scale,
                explain_args if explain_k > 0 else None,
                score_fn=score_fn,
                mesh=self.mesh,
                explain_k=explain_k,
                amount_col=spec.amount_col,
                out_dtype=out_dtype,
                has_dequant=dequant_scale is not None,
                has_explain=explain_k > 0,
            )
            if explain_k > 0:
                scores, eidx, eval_, self.shard_window, self.ledger = out
                self.rows_seen += n_live
                return scores, eidx, eval_
            scores, self.shard_window, self.ledger = out
            self.rows_seen += n_live
        return scores
