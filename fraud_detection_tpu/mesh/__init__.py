"""Switchyard: the sharded serving mesh — one logical scorer across N shards.

The serving tier scaled only at the process level until this package: the
numerics are mesh-proven (meshcheck's virtual meshes, the multichip DP+TP
dry-run), but a serving process flushed to ONE device and routed to ONE
micro-batcher. Switchyard is the scale-out tier:

- :mod:`~fraud_detection_tpu.mesh.topology` — the serving mesh: a data-axis
  device mesh over real devices when present, virtual CPU shards otherwise
  (the same ``--xla_force_host_platform_device_count`` trick meshcheck
  uses, promoted from a static gate to the live topology);
- :mod:`~fraud_detection_tpu.mesh.shardflush` — the fastlane fused flush as
  one ``shard_map``-mapped program: rows row-sharded over the data axis,
  params replicated, per-shard drift windows donated through and merged at
  scrape time — each shard still pays exactly ONE device dispatch per
  flush;
- :mod:`~fraud_detection_tpu.mesh.front` — the shard front: a router that
  balances micro-batches across replica shards with health tracking and
  draining, so a dead shard sheds load instead of stalling the flush;
- :mod:`~fraud_detection_tpu.mesh.retrain` — the cross-replica-sharded
  weight update (arxiv 2004.13336: shard the update, don't replicate it)
  and MapReduce-style sharded feedback-pool aggregation (arxiv 2403.07128).
"""

from fraud_detection_tpu.mesh.front import NoHealthyShards, ShardFront
from fraud_detection_tpu.mesh.shardflush import (
    MeshDriftMonitor,
    init_sharded_window,
    merge_window,
)
from fraud_detection_tpu.mesh.topology import serving_mesh, serving_mesh_size

__all__ = [
    "MeshDriftMonitor",
    "NoHealthyShards",
    "ShardFront",
    "init_sharded_window",
    "merge_window",
    "serving_mesh",
    "serving_mesh_size",
]
