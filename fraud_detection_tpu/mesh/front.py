"""The shard front: route, balance, drain — a dead shard sheds load.

N replica shards (each one micro-batcher with its own flush pipeline) sit
behind one router. Requests go to the healthy shard with the least
in-flight rows (least-loaded routing — with micro-batching this also keeps
buckets full on fewer shards under light traffic instead of scattering
lone rows across all of them). A shard whose flushes fail repeatedly is
marked DEAD and sheds its load: the failed request retries on another
healthy shard in the same call, so a dying shard costs a retry, not an
error, and never stalls the collector of a healthy one.

All shards share the lifecycle :class:`ModelSlot`: a promotion's slot swap
lands on EVERY shard between its in-flight flushes (each flush re-reads
the slot — the existing zero-downtime contract), and because the shards
share the scorer object they also share its pre-warmed bucket ladder, so
a swap is recompile-free on all shards at once.

Draining is first-class (``drain()`` → no new picks, in-flight completes;
``revive()`` re-admits): the ShardOutage runbook's safe-restart primitive,
and what the ``replica_burst`` chaos scenario exercises under load.

Metrics note (panopticon): the scorer gauges/counters
(``scorer_queue_depth``, ``scorer_effective_wait_seconds``,
``scorer_device_calls_per_flush``, ``scorer_flushes_total``) carry a
``shard`` label written by each shard's own micro-batcher — the PR-7
"last-shard per-flush sample" limitation is gone. A shard transitioning
to DEAD/DRAINING drops its per-shard GAUGE series
(``metrics.drop_shard_gauges``) so dashboards never read a dead shard's
last sample as live; a revive re-binds them. The front also feeds the
fleet SLO engine: every routed attempt records availability (+ latency on
success) under ``shard<N>``, so ``slo_burn_rate{slo="availability:shard1"}``
attributes an outage to the shard that caused it. Admission backpressure
(AdmissionFull) is flow control, not failure — it burns neither the
shard's error budget nor its SLO; the client-visible shed is recorded at
the LANE level where the 429/busy frame happens.
"""

from __future__ import annotations

import logging
import time

from fraud_detection_tpu import config
from fraud_detection_tpu.range.faults import fire
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.telemetry import slo

log = logging.getLogger("fraud_detection_tpu.mesh")

HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"
#: a dead shard under its single half-open probe: excluded from routing
#: (not HEALTHY), so exactly ONE request — the one that opened the probe —
#: rides it; concurrent traffic keeps seeing the outage instead of
#: flooding a possibly-still-broken shard.
HALF_OPEN = "half_open"


class NoHealthyShards(RuntimeError):
    """Every shard is dead or draining — the front cannot place the row."""


class ShardHandle:
    """One shard's batcher plus its health bookkeeping."""

    def __init__(self, shard_id: int, batcher, max_consecutive_errors: int):
        self.shard_id = shard_id
        self.batcher = batcher
        self.state = HEALTHY
        self.inflight = 0
        self.rows_total = 0
        self.errors_total = 0
        self.consecutive_errors = 0
        self.dead_since: float | None = None
        # half-open probe: a shard revived because nothing else was
        # healthy re-dies on its FIRST failure instead of getting a fresh
        # error budget
        self.probation = False
        self._max_errors = max_consecutive_errors
        label = str(shard_id)
        self._g_healthy = metrics.mesh_shard_healthy.labels(label)
        self._g_inflight = metrics.mesh_shard_inflight.labels(label)
        self._c_rows = metrics.mesh_shard_rows.labels(label)
        self._c_errors = metrics.mesh_shard_errors.labels(label)
        self._g_healthy.set(1)
        self._g_inflight.set(0)

    def note_ok(self, rows: int = 1) -> bool:
        """Record one scoring success (``rows`` > 1 for an ingest block —
        a frame counts its rows, so ShardLoadSkew reads true row rates);
        returns True when this success was a half-open probe resolving —
        the shard revives (the caller refreshes the health gauge)."""
        self.consecutive_errors = 0
        self.probation = False
        self.rows_total += rows
        self._c_rows.inc(rows)
        if self.state == HALF_OPEN:
            self.set_state(HEALTHY)
            return True
        return False

    def note_error(self, exc: BaseException) -> bool:
        """Record one scoring failure; returns True when this crossed the
        death threshold (the caller logs the shed). A probation shard
        (half-open probe) dies on its first failure."""
        self.errors_total += 1
        self.consecutive_errors += 1
        self._c_errors.inc()
        if self.state in (HEALTHY, HALF_OPEN) and (
            self.probation or self.consecutive_errors >= self._max_errors
        ):
            self.set_state(DEAD)
            return True
        return False

    def set_state(self, state: str) -> None:
        prev = self.state
        self.state = state
        self.dead_since = time.monotonic() if state == DEAD else None
        if state != HEALTHY:
            self.probation = False
        self._g_healthy.set(1 if state == HEALTHY else 0)
        # panopticon stale-series discipline: a dead/draining shard's
        # per-shard scorer GAUGES drop from the registry (its last
        # queue-depth/wait/dispatch sample must not read as live); a
        # revive re-binds the batcher's children (the dropped ones are
        # orphaned from the registry and would export nothing).
        shard_label = str(getattr(self.batcher, "shard_id", self.shard_id))
        if state in (DEAD, DRAINING):
            metrics.drop_shard_gauges(shard_label)
        elif state == HEALTHY and prev != HEALTHY:
            rebind = getattr(self.batcher, "rebind_shard_gauges", None)
            if rebind is not None:
                rebind()

    def to_dict(self) -> dict:
        return {
            "shard": self.shard_id,
            "state": self.state,
            "inflight": self.inflight,
            "rows_total": self.rows_total,
            "errors_total": self.errors_total,
        }


class ShardFront:
    """Router over N shard batchers; same surface as one MicroBatcher
    (``start``/``stop``/``score``), so the serving app swaps it in behind
    ``/predict`` untouched."""

    def __init__(
        self,
        batchers,
        max_consecutive_errors: int | None = None,
        reopen_after: float | None = None,
        on_revive=None,
    ):
        # ``on_revive(shard_id)`` fires when a shard rejoins the rotation
        # (operator revive, or a half-open probe resolving). The lifeboat
        # wires a snapshot request here: a revive follows an outage, and
        # capturing a durable generation NOW beats waiting out a full
        # snapshot interval with freshly-recovered capacity at risk.
        self._on_revive = on_revive
        if not batchers:
            raise ValueError("ShardFront needs at least one shard batcher")
        max_err = (
            max_consecutive_errors
            if max_consecutive_errors is not None
            else config.mesh_shard_max_errors()
        )
        # half-open window: how long a dead shard rests before it may be
        # probed when nothing else is healthy (self-healing — a transient
        # failure correlated across shards must not need N manual revives)
        self.reopen_after = (
            reopen_after
            if reopen_after is not None
            else config.mesh_shard_reopen_s()
        )
        # panopticon: the front OWNS shard identity — assign it by index
        # so batchers constructed without an explicit shard_id still get
        # distinct per-shard series (all defaulting to "0" would let one
        # shard's stale-series drop orphan every survivor's gauges)
        for i, b in enumerate(batchers):
            setter = getattr(b, "set_shard_id", None)
            if setter is not None:
                setter(i)
        self.shards = [
            ShardHandle(i, b, max_err) for i, b in enumerate(batchers)
        ]
        metrics.mesh_shards.set(len(self.shards))
        metrics.mesh_shards_healthy.set(len(self.shards))
        # panopticon: materialize the per-shard SLO series up front so the
        # burn/budget gauges exist (at 0) from the first scrape
        eng = slo.engine()
        if eng is not None:
            eng.declare_shards(len(self.shards))

    # -- MicroBatcher-compatible surface ------------------------------------
    @property
    def telemetry(self) -> bool:
        return self.shards[0].batcher.telemetry

    @property
    def explain(self) -> bool:
        """Serve-time reason codes configured (lantern) — the shards share
        the config, so shard 0 speaks for the front."""
        return bool(getattr(self.shards[0].batcher, "explain", False))

    async def start(self) -> None:
        # Shards share the slot's scorer and the watchtower's drift
        # monitor, so ONE bucket-ladder warmup covers every shard —
        # shard 0 warms, the rest skip straight to collecting.
        for i, h in enumerate(self.shards):
            await h.batcher.start(warm=(i == 0))

    async def stop(self) -> None:
        for h in self.shards:
            await h.batcher.stop()

    # -- routing ------------------------------------------------------------
    def _healthy(self) -> list[ShardHandle]:
        return [h for h in self.shards if h.state == HEALTHY]

    def pick(
        self, exclude: set[int] | None = None, entity=None
    ) -> ShardHandle:
        """Least-in-flight healthy shard (optionally excluding shards this
        request already failed on — a fast-failing shard has the LOWEST
        in-flight count, so without the exclusion a retry would re-pick
        exactly the shard that just failed it).

        ``entity`` (the ledger's ``(slot, fingerprint, ts)`` triple) makes
        routing sticky: an entity's rows prefer shard ``fingerprint mod
        N`` — hash-mod-shard placement, so one replica's batcher sees an
        entity's whole stream (its flushes then stage the entity into one
        device shard's ledger sub-table, and batch locality improves).
        A dead/draining/excluded preferred shard falls back to
        least-in-flight: availability beats stickiness — the ledger
        tolerates it (the tables are per-process state either way)."""
        healthy = [
            h for h in self._healthy()
            if not exclude or h.shard_id not in exclude
        ]
        if not healthy:
            probe = self._half_open_candidate(exclude)
            if probe is not None:
                return probe
            raise NoHealthyShards(
                f"all {len(self.shards)} shards dead, draining, or already "
                "tried by this request"
            )
        if entity is not None:
            preferred = self.shards[int(entity[1]) % len(self.shards)]
            if preferred in healthy:
                return preferred
        return min(healthy, key=lambda h: h.inflight)

    def _half_open_candidate(self, exclude: set[int] | None) -> (
        ShardHandle | None
    ):
        """Self-healing when every shard is dead: probe the longest-dead
        shard whose rest window (``reopen_after``) has elapsed. The shard
        moves to HALF_OPEN — still excluded from routing, so ONLY the
        request that opened the probe rides it; concurrent traffic keeps
        seeing NoHealthyShards (→ 503) instead of flooding a possibly
        still-broken shard. One failure re-kills it instantly, a success
        fully revives it. Without this, a transient failure correlated
        across shards (shared device blip, one poisoned burst) would turn
        into a permanent outage needing a manual revive per shard."""
        now = time.monotonic()
        rested = [
            h for h in self.shards
            if h.state == DEAD
            and (not exclude or h.shard_id not in exclude)
            and h.dead_since is not None
            and now - h.dead_since >= self.reopen_after
        ]
        if not rested:
            return None
        probe = min(rested, key=lambda h: h.dead_since)
        dead_for = now - probe.dead_since
        probe.set_state(HALF_OPEN)
        probe.probation = True
        log.warning(
            "shard %d half-open probe after %.1fs dead",
            probe.shard_id, dead_for,
        )
        return probe

    def _refresh_health_gauge(self) -> None:
        metrics.mesh_shards_healthy.set(len(self._healthy()))

    async def score(self, row, timeline=None, entity=None) -> float:
        """Route one row; a failing shard is retried elsewhere in the same
        call (at most once per shard), so callers see a score or one final
        error — never a dead shard's exception."""
        return await self._route("score", row, timeline, entity)

    async def score_ex(self, row, timeline=None, entity=None):
        """Route one row through the explain surface: ``(score, reasons)``
        with the lantern reason codes from whichever shard scored it —
        same shed/retry semantics as :meth:`score`, so a shard dying
        mid-burst re-routes the row WITH its explain output intact."""
        return await self._route("score_ex", row, timeline, entity)

    async def score_block(self, block, timeline=None, entity=None):
        """Route one hyperloop ingest block (the binary lane / packed POST
        frame) as a unit: the whole frame lands on ONE shard's forming
        bucket (frames keep buckets full instead of scattering), with the
        same shed/retry semantics as :meth:`score`. A shard whose
        admission queue is full is NOT an error — the block tries the
        other shards and sheds (AdmissionFull → 429/busy at the edge)
        only when every healthy shard is saturated."""
        return await self._route("score_block", block, timeline, entity)

    async def _route(self, method: str, row, timeline=None, entity=None):
        from fraud_detection_tpu.service.microbatch import AdmissionFull

        last_exc: BaseException | None = None
        tried: set[int] = set()
        n_rows = row.n if method == "score_block" else 1
        for _ in range(len(self.shards)):
            try:
                h = self.pick(exclude=tried, entity=entity)
            except NoHealthyShards:
                if last_exc is not None:
                    raise last_exc
                raise
            tried.add(h.shard_id)
            h.inflight += n_rows
            h._g_inflight.set(h.inflight)
            t_attempt = time.perf_counter()
            try:
                # fraud-range injection point: a chaos plan fails a named
                # shard's scoring here (the kill-a-shard drill). Disarmed
                # this is one global load.
                fire("mesh.shard_flush", shard=h.shard_id)
                out = await getattr(h.batcher, method)(
                    row, timeline, entity
                )
            except AdmissionFull as e:
                # backpressure, not failure: the shard is healthy but
                # saturated — try the others without burning its error
                # budget (or its SLO), and surface the shed if all are
                # full; the client-visible shed records at the lane edge
                last_exc = e
                continue
            except Exception as e:
                last_exc = e
                slo.record_shard(h.shard_id, False)
                if h.note_error(e):
                    self._refresh_health_gauge()
                    log.error(
                        "shard %d marked dead after %d consecutive "
                        "errors — shedding load (%s)",
                        h.shard_id, h.consecutive_errors, e,
                    )
                continue
            else:
                slo.record_shard(
                    h.shard_id, True, time.perf_counter() - t_attempt
                )
                # a half-open probe resolved: shard revived
                if h.note_ok(n_rows):
                    self._refresh_health_gauge()
                    log.warning(
                        "shard %d revived by half-open probe", h.shard_id
                    )
                    self._notify_revive(h.shard_id)
                return out
            finally:
                h.inflight -= n_rows
                h._g_inflight.set(h.inflight)
        raise last_exc if last_exc is not None else NoHealthyShards(
            "no healthy shards"
        )

    # -- operations ---------------------------------------------------------
    def drain(self, shard_id: int) -> None:
        """Stop routing new rows to ``shard_id``; in-flight rows finish.

        Refuses to drain the LAST healthy shard: draining is the
        safe-restart primitive, and a drain that silently turned every
        request into NoHealthyShards would be a self-inflicted outage —
        the operator gets the error at drain time instead."""
        h = self.shards[shard_id]
        if h.state != HEALTHY:
            return
        if len(self._healthy()) <= 1:
            raise ValueError(
                f"refusing to drain shard {shard_id}: it is the last "
                "healthy shard — revive another shard first"
            )
        h.set_state(DRAINING)
        self._refresh_health_gauge()
        log.warning("shard %d draining", shard_id)

    def wait_drained(self, shard_id: int, timeout: float = 10.0) -> bool:
        """Block until a draining shard's in-flight count reaches zero.
        Poll-based so operators can call it from a sync admin path."""
        deadline = time.monotonic() + timeout
        h = self.shards[shard_id]
        while h.inflight > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    def revive(self, shard_id: int) -> None:
        """Re-admit a drained/dead shard (post-restart, post-fix)."""
        h = self.shards[shard_id]
        h.consecutive_errors = 0
        h.probation = False  # an operator revive grants a full error budget
        h.set_state(HEALTHY)
        self._refresh_health_gauge()
        log.warning("shard %d revived", shard_id)
        self._notify_revive(shard_id)

    def _notify_revive(self, shard_id: int) -> None:
        if self._on_revive is None:
            return
        try:
            self._on_revive(shard_id)
        except Exception:
            log.debug("on_revive hook failed", exc_info=True)

    def status(self) -> dict:
        healthy = self._healthy()
        return {
            "shards": len(self.shards),
            "healthy": len(healthy),
            "per_shard": [h.to_dict() for h in self.shards],
        }
