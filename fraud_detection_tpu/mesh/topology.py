"""Serving-mesh topology: the data axis the switchyard shards over.

The training tier already has a ``(data, model)`` mesh
(:mod:`fraud_detection_tpu.parallel.mesh`); serving reuses the same axis
names so the sharded flush and the sharded retrain update compose with the
existing collectives. The serving mesh is 1-D over ``data``: the scaling
axis of a fraud scorer is rows, and the 30-feature linear flagship has
nothing worth tensor-sharding (the mechanism generalizes through
``score_args`` being an arbitrary pytree — a TP-sharded family would carry
sharded params there).

Real accelerators when present; otherwise the *virtual CPU shards*
meshcheck proves shapes on (``--xla_force_host_platform_device_count``)
become the live topology — the promotion of that static gate to the real
serving path that ISSUE 7 names.
"""

from __future__ import annotations

import logging

import jax
from jax.sharding import Mesh

from fraud_detection_tpu import config
from fraud_detection_tpu.parallel.mesh import MeshSpec, create_mesh

log = logging.getLogger("fraud_detection_tpu.mesh")

#: Hard ceiling on the flush shard count: every flush bucket must divide
#: across the shards, and the smallest bucket the serving scorers emit is
#: their min_bucket (ops/scorer default 8) — a lone-request flush pads to
#: it. More shards than that cannot receive a row each.
MAX_FLUSH_SHARDS = 8


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def serving_mesh_size(requested: int | None = None) -> int:
    """Resolve the serving mesh's data-axis size.

    ``requested`` (default: the ``MESH_FLUSH_DEVICES`` knob) is clamped to
    the local device count AND :data:`MAX_FLUSH_SHARDS` (the smallest
    flush bucket — a lone-request flush pads to the scorer's min_bucket
    and must still hand every shard a row), then floored to a power of
    two — flush buckets are powers of two, and every bucket must divide
    evenly across shards (the row-sharded ``shard_map`` needs equal
    per-shard rows). 0 resolves to 1 (single-device fastlane)."""
    n = config.mesh_flush_devices() if requested is None else requested
    if n <= 1:
        return 1
    avail = jax.device_count()
    if n > avail:
        log.warning(
            "MESH_FLUSH_DEVICES=%d but only %d device(s) present — "
            "clamping (set XLA_FLAGS=--xla_force_host_platform_device_"
            "count=N for virtual CPU shards)", n, avail,
        )
        n = avail
    if n > MAX_FLUSH_SHARDS:
        log.warning(
            "MESH_FLUSH_DEVICES=%d exceeds the smallest flush bucket "
            "(%d) — clamping; a lone-request flush could not hand every "
            "shard a row", n, MAX_FLUSH_SHARDS,
        )
        n = MAX_FLUSH_SHARDS
    while not _is_pow2(n):
        n -= 1
    return max(n, 1)


def serving_mesh(n_shards: int | None = None, devices=None) -> Mesh:
    """Build the 1-D ``data`` serving mesh over the first ``n_shards``
    devices (resolved via :func:`serving_mesh_size` when None)."""
    if devices is None:
        devices = jax.devices()
    # an explicit size is validated strictly below; only the knob-resolved
    # default gets the clamp-and-floor treatment
    n = serving_mesh_size() if n_shards is None else n_shards
    if n > len(devices):
        raise ValueError(
            f"serving mesh needs {n} devices, have {len(devices)} — run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{n} for virtual CPU shards"
        )
    if not _is_pow2(n):
        raise ValueError(
            f"serving mesh size must be a power of two (flush buckets "
            f"must divide evenly across shards), got {n}"
        )
    return create_mesh(MeshSpec(data=n), devices=devices[:n])
