"""Serving-mesh topology: the (data × model) grid the switchyard shards over.

The training tier already has a ``(data, model)`` mesh
(:mod:`fraud_detection_tpu.parallel.mesh`); serving reuses the same axis
names so the sharded flush and the sharded retrain update compose with the
existing collectives. Until broadside the serving mesh was effectively 1-D
over ``data`` (the model axis pinned at 1 — 30-feature families have
nothing worth tensor-sharding); ``MESH_MODEL_DEVICES`` now grows the
second axis for the WIDE family, whose hashed-cross weight table
(``WIDE_BUCKETS`` columns) column-shards over ``model`` with exactly one
hot-path ``psum``. Narrow families on a 2-D mesh simply row-shard over the
FLATTENED grid — every device still scores rows, nothing is wasted, and
the per-(data,model)-shard drift windows merge only at scrape exactly as
on the 1-D mesh.

Real accelerators when present; otherwise the *virtual CPU shards*
meshcheck proves shapes on (``--xla_force_host_platform_device_count``)
become the live topology — the promotion of that static gate to the real
serving path that ISSUE 7 names.
"""

from __future__ import annotations

import logging

import jax
from jax.sharding import Mesh

from fraud_detection_tpu import config
from fraud_detection_tpu.parallel.mesh import MeshSpec, create_mesh

log = logging.getLogger("fraud_detection_tpu.mesh")

#: Hard ceiling on the flush shard count: every flush bucket must divide
#: across the shards, and the smallest bucket the serving scorers emit is
#: their min_bucket (ops/scorer default 8) — a lone-request flush pads to
#: it. More shards than that cannot receive a row each.
MAX_FLUSH_SHARDS = 8


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def serving_mesh_size(requested: int | None = None) -> int:
    """Resolve the serving mesh's data-axis size.

    ``requested`` (default: the ``MESH_FLUSH_DEVICES`` knob) is clamped to
    the local device count AND :data:`MAX_FLUSH_SHARDS` (the smallest
    flush bucket — a lone-request flush pads to the scorer's min_bucket
    and must still hand every shard a row), then floored to a power of
    two — flush buckets are powers of two, and every bucket must divide
    evenly across shards (the row-sharded ``shard_map`` needs equal
    per-shard rows). 0 resolves to 1 (single-device fastlane)."""
    n = config.mesh_flush_devices() if requested is None else requested
    if n <= 1:
        return 1
    avail = jax.device_count()
    if n > avail:
        log.warning(
            "MESH_FLUSH_DEVICES=%d but only %d device(s) present — "
            "clamping (set XLA_FLAGS=--xla_force_host_platform_device_"
            "count=N for virtual CPU shards)", n, avail,
        )
        n = avail
    if n > MAX_FLUSH_SHARDS:
        log.warning(
            "MESH_FLUSH_DEVICES=%d exceeds the smallest flush bucket "
            "(%d) — clamping; a lone-request flush could not hand every "
            "shard a row", n, MAX_FLUSH_SHARDS,
        )
        n = MAX_FLUSH_SHARDS
    while not _is_pow2(n):
        n -= 1
    return max(n, 1)


def serving_mesh_model_size(requested: int | None = None) -> int:
    """Resolve the serving mesh's model-axis size (``MESH_MODEL_DEVICES``).
    0 resolves to 1 (no tensor parallelism); must be a power of two —
    the wide family's bucket table (power-of-two wide) column-shards
    evenly only then."""
    m = config.mesh_model_devices() if requested is None else requested
    if m <= 1:
        return 1
    if not _is_pow2(m):
        raise ValueError(
            f"MESH_MODEL_DEVICES must be a power of two (the wide bucket "
            f"table must column-shard evenly), got {m}"
        )
    return m


def serving_mesh(
    n_shards: int | None = None, devices=None, model_devices: int | None = None
) -> Mesh:
    """Build the ``(data × model)`` serving mesh over the first
    ``data·model`` devices. ``n_shards`` is the data-axis size (resolved
    via :func:`serving_mesh_size` when None); ``model_devices`` the model
    axis (``MESH_MODEL_DEVICES`` when None, default 1 — the historical
    1-D mesh). The flattened grid must stay within
    :data:`MAX_FLUSH_SHARDS`: narrow families row-shard over BOTH axes, so
    every flush bucket must still hand each device a row."""
    if devices is None:
        devices = jax.devices()
    m = serving_mesh_model_size(model_devices)
    # an explicit size is validated strictly below; only the knob-resolved
    # default gets the clamp-and-floor treatment
    n = serving_mesh_size() if n_shards is None else n_shards
    if m > 1 and n_shards is None and n * m > MAX_FLUSH_SHARDS:
        log.warning(
            "MESH_FLUSH_DEVICES×MESH_MODEL_DEVICES = %d×%d exceeds the "
            "smallest flush bucket (%d) — clamping the data axis",
            n, m, MAX_FLUSH_SHARDS,
        )
        n = max(MAX_FLUSH_SHARDS // m, 1)
    total = n * m
    if total > len(devices):
        raise ValueError(
            f"serving mesh needs {n}×{m} = {total} devices, have "
            f"{len(devices)} — run under XLA_FLAGS=--xla_force_host_"
            f"platform_device_count={total} for virtual CPU shards"
        )
    if not _is_pow2(n):
        raise ValueError(
            f"serving mesh data-axis size must be a power of two (flush "
            f"buckets must divide evenly across shards), got {n}"
        )
    if total > MAX_FLUSH_SHARDS:
        raise ValueError(
            f"serving mesh {n}×{m} = {total} shards exceed the smallest "
            f"flush bucket ({MAX_FLUSH_SHARDS}) — every bucket must hand "
            "each device a row"
        )
    return create_mesh(MeshSpec(data=n, model=m), devices=devices[:total])
