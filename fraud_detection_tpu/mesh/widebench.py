"""Broadside bench probe: the wide family's 2-D flush over virtual shards.

Run as a SUBPROCESS by ``bench.py``'s ``wide_flush`` section with
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``
(the backend device count is fixed at init, so the 2-D grid needs its own
process). Hard gates, all backend-independent except the ratio floor:

- **2-D parity**: the (data × model)-sharded wide flush's scores AND top-k
  reason codes bitwise-match the single-device wide flush at 2×2, 4×2 and
  2×4 — the ISSUE 13 acceptance bar (each cross index lives on exactly one
  model shard, so the single ``psum`` adds one real value and M−1 exact
  zeros);
- **zero-alloc staging**: steady-state wide flushes draw the same pooled
  slot (fingerprint lanes included) — allocations exactly 0;
- **cost ratio**: the wide flush (hash + 2¹⁴-bucket gather + widened fold
  + explain leg) vs the narrow fastlane flush on the same bucket. On CPU
  the gather and the widened (34-column) histogram fold are serial and the
  floor is :data:`WIDE_CPU_FLOOR` — the ≥0.5× figure is the accelerator
  claim (the gather is one HBM lookup per cross riding the same dispatch);
- **model-axis scaling**: at a fixed data axis, growing the model axis
  must (a) shard the table EXACTLY — per-device cross-weight bytes halve
  as M doubles, asserted mechanically from the live sharding — and (b)
  not collapse throughput below a documented floor vs M=1
  (:data:`WIDE_MODEL_CPU_FLOOR`). On virtual CPU shards the model axis
  cannot be throughput-monotone for the serving flush: rows are
  REPLICATED over it (each model shard re-scores the batch so the single
  psum can assemble the widened block), so M shards add shard_map +
  collective overhead while sharding only the gather. The monotone-
  throughput claim is the ACCELERATOR claim — there the model axis buys
  HBM capacity for d≫10⁴ tables and the psum rides ICI — and the curve is
  published, floor-gated, never silently dropped.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

#: wide-vs-narrow flush cost floor on a CPU runner: the 4-cross hashed
#: gather + the 34-column drift fold + the widened explain leg measured
#: ~0.16-0.25× the 30-column narrow flush on shared-core CI hosts (XLA
#: CPU runs the gather serially). The ≥0.5× budget is the ACCELERATOR
#: claim, honestly documented — see docs/OBSERVABILITY.md (broadside).
WIDE_CPU_FLOOR = 0.10

#: model-axis non-collapse floor on virtual CPU shards: rate(data=2, M) /
#: rate(data=2, M=1) — shared-core virtual shards replicate the row work
#: over the model axis (see module docstring), measured ~0.15-0.35 at
#: M=4 on CI-class hosts. Guards the mechanism against a collapse (a
#: stray collective, a re-layout per flush), not a speedup.
WIDE_MODEL_CPU_FLOOR = 0.08


def _build(seed: int = 9, n_rows: int = 16384):
    from fraud_detection_tpu.monitor.baseline import build_baseline_profile
    from fraud_detection_tpu.ops.crosses import (
        CrossSpec,
        widen_with_crosses,
    )
    from fraud_detection_tpu.ops.logistic import LogisticParams
    from fraud_detection_tpu.ops.scaler import ScalerParams
    from fraud_detection_tpu.ops.scorer import BatchScorer, WideBatchScorer

    d = 30
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((n_rows, d)).astype(np.float32)
    data[:, 0] = np.abs(data[:, 0]) * 50_000  # Time
    data[:, -1] = np.abs(data[:, -1]) * 120  # Amount
    fps = rng.integers(1, 1 << 32, n_rows, dtype=np.uint64).astype(np.uint32)
    spec = CrossSpec(n_base=d, log2_buckets=14, amount_col=d - 1, time_col=0)
    table = (rng.standard_normal(spec.buckets) * 0.05).astype(np.float32)

    def eye_scaler(width):
        return ScalerParams(
            mean=np.zeros(width, np.float32), scale=np.ones(width, np.float32),
            var=np.ones(width, np.float32), n_samples=np.float32(1),
        )

    wide_params = LogisticParams(
        coef=np.concatenate(
            [rng.standard_normal(d).astype(np.float32) * 0.3,
             np.ones(spec.n_cross, np.float32)]
        ),
        intercept=np.float32(-1.0),
    )
    wide = WideBatchScorer(
        wide_params, eye_scaler(spec.n_features), spec, table
    )
    narrow = BatchScorer(
        LogisticParams(
            coef=np.asarray(wide_params.coef)[:d], intercept=np.float32(-1.0)
        ),
        eye_scaler(d),
    )
    xw = widen_with_crosses(data, fps, table, spec)
    wide_profile = build_baseline_profile(
        xw, wide.predict_proba(xw),
        feature_names=[f"f{i}" for i in range(d)] + list(spec.cross_names),
    )
    narrow_profile = build_baseline_profile(
        data, narrow.predict_proba(data),
        feature_names=[f"f{i}" for i in range(d)],
    )
    return data, fps, wide, narrow, wide_profile, narrow_profile


def _wide_flush_once(scorer, monitor, rows, fps, explain_k: int = 3):
    import jax.numpy as jnp

    from fraud_detection_tpu.ops.scorer import _bucket

    n = rows.shape[0]
    spec = scorer.fused_spec()
    slot = scorer.staging.acquire(_bucket(n, scorer.min_bucket))
    try:
        hx = scorer.stage_items(slot, [(rows, None, None, None)])
        slot.ensure_ledger()
        slot.lf[:n] = fps
        slot.lf[n:] = 0
        slot.lh[:n] = 1.0
        slot.lh[n:] = 0.0
        out = monitor.fused_flush(
            jnp.asarray(hx), jnp.asarray(slot.valid), n,
            spec.score_args, spec.score_fn,
            dequant_scale=spec.dequant_scale, score_codes=spec.score_codes,
            explain_args=spec.explain_args if explain_k else None,
            explain_k=explain_k,
            wide_args=spec.wide,
            wide_rows=(jnp.asarray(slot.lf), jnp.asarray(slot.lh)),
        )
        if explain_k:
            s, ei, ev = out
            return (
                np.asarray(s, np.float32)[:n],
                np.asarray(ei)[:n],
                np.asarray(ev, np.float32)[:n],
            )
        return np.asarray(out, np.float32)[:n]
    finally:
        scorer.staging.release(slot)


def _narrow_flush_once(scorer, monitor, rows):
    import jax.numpy as jnp

    from fraud_detection_tpu.ops.scorer import _bucket

    n = rows.shape[0]
    spec = scorer.fused_spec()
    slot = scorer.staging.acquire(_bucket(n, scorer.min_bucket))
    try:
        hx = scorer.stage_items(slot, [(rows, None, None, None)])
        out = monitor.fused_flush(
            jnp.asarray(hx), jnp.asarray(slot.valid), n,
            spec.score_args, spec.score_fn,
        )
        return np.asarray(out, np.float32)[:n]
    finally:
        scorer.staging.release(slot)


def run(bucket: int = 16384, reps: int = 6) -> dict:
    import jax

    from fraud_detection_tpu.mesh.shardflush import MeshDriftMonitor
    from fraud_detection_tpu.mesh.topology import serving_mesh
    from fraud_detection_tpu.monitor.drift import DriftMonitor

    avail = jax.device_count()
    data, fps, wide, narrow, wide_profile, narrow_profile = _build(
        n_rows=bucket
    )
    rows = data[:bucket]
    f = fps[:bucket]

    # single-device wide reference: the 2-D parity target (scores + codes)
    ref_s, ref_ei, ref_ev = _wide_flush_once(
        wide, DriftMonitor(wide_profile), rows, f
    )

    shapes = [(d, m) for d, m in ((2, 2), (4, 2), (2, 4)) if d * m <= avail]
    parity = True
    for d_ax, m_ax in shapes:
        mon = MeshDriftMonitor(
            wide_profile, serving_mesh(d_ax, model_devices=m_ax)
        )
        s, ei, ev = _wide_flush_once(wide, mon, rows, f)
        parity = parity and bool(
            np.array_equal(s.view(np.uint32), ref_s.view(np.uint32))
            and np.array_equal(ei, ref_ei)
            and np.array_equal(ev.view(np.uint32), ref_ev.view(np.uint32))
        )

    # zero-alloc steady state: after the warm flushes above on the
    # single-device monitor, more flushes must draw the same pooled slot
    mono = DriftMonitor(wide_profile)
    _wide_flush_once(wide, mono, rows, f)
    base_allocs = wide.staging.allocations
    for _ in range(4):
        _wide_flush_once(wide, mono, rows, f)
    steady_allocs = wide.staging.allocations - base_allocs

    # cost ratio vs the narrow fastlane flush (single device, same bucket)
    n_mon = DriftMonitor(narrow_profile)
    _narrow_flush_once(narrow, n_mon, rows)  # warm

    def rate(fn) -> float:
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(reps):
                fn()
            best = max(best, reps / (time.perf_counter() - t0))
        return best

    narrow_rate = rate(lambda: _narrow_flush_once(narrow, n_mon, rows))
    wide_rate = rate(lambda: _wide_flush_once(wide, mono, rows, f))
    ratio = wide_rate / max(narrow_rate, 1e-9)

    # model-axis scaling at a fixed data axis (2 × {1, 2, 4}): mechanical
    # table sharding asserted exactly, throughput floor-gated vs M=1
    model_rates: dict[str, float] = {}
    shard_bytes: dict[str, int] = {}
    for m_ax in (1, 2, 4):
        if 2 * m_ax > avail:
            continue
        mesh = serving_mesh(2, model_devices=m_ax)
        mon = MeshDriftMonitor(wide_profile, mesh)
        _wide_flush_once(wide, mon, rows, f)  # warm/compile
        # per-device cross-weight bytes from the LIVE sharding: lay the
        # table out as the flush program does and read one addressable
        # shard's footprint
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as _P

        from fraud_detection_tpu.parallel.mesh import MODEL_AXIS

        t_dev = _jax.device_put(
            np.asarray(wide.wide_table),
            NamedSharding(mesh, _P(MODEL_AXIS)),
        )
        shard_bytes[str(m_ax)] = int(
            t_dev.addressable_shards[0].data.nbytes
        )
        model_rates[str(m_ax)] = rate(
            lambda mon=mon: _wide_flush_once(wide, mon, rows, f)
        )
    keys = sorted(model_rates, key=int)
    bytes_halve = all(
        shard_bytes[a] == shard_bytes[b] * (int(b) // int(a))
        for a, b in zip(keys, keys[1:])
    )
    base_rate = model_rates.get("1", 0.0)
    model_ratio = (
        min(model_rates[k] for k in keys if k != "1") / max(base_rate, 1e-9)
        if len(keys) > 1
        else 1.0
    )

    return {
        "device_count": avail,
        "bucket": bucket,
        "wide_buckets": 1 << 14,
        "wide_parity_ok": parity,
        "wide_shapes_measured": [f"{d}x{m}" for d, m in shapes],
        "wide_staging_steady_allocations": int(steady_allocs),
        "wide_flushes_per_sec": round(wide_rate, 2),
        "narrow_flushes_per_sec": round(narrow_rate, 2),
        "wide_cost_ratio": round(ratio, 3),
        "wide_cost_ok": ratio >= WIDE_CPU_FLOOR,
        "wide_cpu_floor": WIDE_CPU_FLOOR,
        "wide_model_axis_flushes_per_sec": {
            k: round(v, 2) for k, v in model_rates.items()
        },
        "wide_model_shard_bytes": shard_bytes,
        "wide_model_shards_exact": bytes_halve,
        "wide_model_ratio": round(model_ratio, 3),
        "wide_model_ratio_ok": model_ratio >= WIDE_MODEL_CPU_FLOOR,
        "wide_model_cpu_floor": WIDE_MODEL_CPU_FLOOR,
    }


def main() -> int:
    print(json.dumps(run()), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
