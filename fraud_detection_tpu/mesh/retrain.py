"""Cross-replica-sharded weight update + MapReduce feedback aggregation.

Two idioms from the papers behind ISSUE 7:

- **Sharded weight update** (arxiv 2004.13336): plain data-parallel SGD
  allreduces the gradient and has every replica redundantly apply the
  same update to a full replicated copy of the weights and optimizer
  state. Here the update itself is sharded: each replica owns 1/N of the
  parameter vector and its optimizer state, the per-batch gradient is
  ``psum_scatter``-reduced straight into that shard (one collective doing
  reduce+shard in one hop), the shard applies the momentum update to its
  slice only, and the full vector is ``all_gather``-ed just-in-time for
  the next forward pass. For a 30-feature logistic this is a mechanism
  proof, not a memory win — but it is the exact program shape that makes
  optimizer state O(P/N) for the wide-model families ``score_args``
  generalizes to.
- **MapReduce pool aggregation** (arxiv 2403.07128, DrJAX): the conductor's
  feedback pools are aggregated as mapped-then-reduced per-shard
  computation — each shard summarizes ITS rows (map), a ``psum`` reduces
  the summaries (reduce) — instead of hauling every row to one host loop.

``_sharded_update_epoch`` is a module-level jit (mesh static) so the
compile sentinel wraps it (entrypoint ``mesh.sharded_update``) and
meshcheck abstractly evaluates it at every virtual mesh size.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fraud_detection_tpu.ops.logistic import (
    LogisticParams,
    _cap_batch_size,
    _resolve_sample_weight,
)
from fraud_detection_tpu.parallel.compat import shard_map
from fraud_detection_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshSpec,
    create_mesh,
    default_mesh,
)
from fraud_detection_tpu.parallel.sharding import (
    pad_to_multiple,
    shard_batch,
    sync_fetch,
)


def _pad_features(d: int, ndev: int) -> int:
    """Parameter length padded so the shard axis divides it evenly (the
    padded coefficients start at zero, see zero gradient, and stay zero)."""
    return ((d + ndev - 1) // ndev) * ndev


def _update_body(c: float, n_total: int, n_devices: int, momentum: float,
                 batch: int):
    """Per-shard epoch under shard_map: sharded params/velocity in, sharded
    out. Each step all_gathers the full weight vector for the forward,
    psum_scatters the gradient back onto the owning shards, and updates the
    local slice + local momentum state only (2004.13336)."""

    def epoch(coef_l, vel_l, intercept, vel_b, x_local, y_pm_local, sw_local,
              valid_local, perm, lr):
        n_local = x_local.shape[0]
        n_batches = n_local // batch

        def body(carry, i):
            coef_l, vel_l, b, vel_b = carry
            w = jax.lax.all_gather(coef_l, DATA_AXIS, axis=0, tiled=True)
            idx = jax.lax.dynamic_slice_in_dim(perm, i * batch, batch)
            xb = x_local[idx]
            yb = y_pm_local[idx]
            swb = sw_local[idx]
            b_valid = jnp.maximum(
                jax.lax.psum(jnp.sum(valid_local[idx]), DATA_AXIS), 1.0
            )

            def loss(w, b):
                z = xb @ w + b
                data = jnp.sum(swb * jax.nn.softplus(-yb * z)) * (c / b_valid)
                # reg split across devices so the psum reconstitutes it once
                reg = 0.5 * jnp.dot(w, w) / (n_total * n_devices)
                return data + reg

            gw, gb = jax.grad(loss, argnums=(0, 1))(w, b)
            # reduce + shard in ONE collective: each shard receives the
            # summed gradient of ITS parameter slice only
            gw_l = jax.lax.psum_scatter(
                gw, DATA_AXIS, scatter_dimension=0, tiled=True
            )
            gb = jax.lax.psum(gb, DATA_AXIS)
            vel_l = momentum * vel_l - lr * gw_l
            coef_l = coef_l + vel_l
            vel_b = momentum * vel_b - lr * gb
            b = b + vel_b
            return (coef_l, vel_l, b, vel_b), None

        (coef_l, vel_l, intercept, vel_b), _ = jax.lax.scan(
            body, (coef_l, vel_l, intercept, vel_b), jnp.arange(n_batches)
        )
        return coef_l, vel_l, intercept, vel_b

    return epoch


@partial(
    jax.jit,
    static_argnames=("mesh", "c", "n_total", "momentum", "batch"),
    donate_argnums=(0, 1),
)
def _sharded_update_epoch(
    coef_sh,  # (d_pad,) sharded over data — each shard owns its slice
    vel_sh,   # (d_pad,) sharded — optimizer state is sharded too
    intercept,  # () replicated
    vel_b,      # () replicated
    x,        # (n, d_pad) row-sharded
    y_pm,     # (n,) ±1 labels, row-sharded
    sw,       # (n,) sample weights (0 on padding), row-sharded
    valid,    # (n,) row validity, row-sharded
    perm,     # (n_local,) per-shard minibatch permutation, replicated
    lr,       # () replicated
    *,
    mesh,
    c: float,
    n_total: int,
    momentum: float,
    batch: int,
):
    """One epoch of the cross-replica-sharded weight update. Registered in
    meshcheck (``mesh.sharded_update``) and the compile sentinel."""
    mapped = shard_map(
        _update_body(c, n_total, mesh.shape[DATA_AXIS], momentum, batch),
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS), P(DATA_AXIS), P(), P(),
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
            P(), P(),
        ),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        check_vma=False,
    )
    return mapped(
        coef_sh, vel_sh, intercept, vel_b, x, y_pm, sw, valid, perm, lr
    )


def mesh_sgd_fit(
    x,
    y,
    c: float = 1.0,
    epochs: int = 5,
    batch_size: int = 4096,
    lr: float = 0.3,
    momentum: float = 0.9,
    class_weight: dict | str | None = None,
    sample_weight=None,
    seed: int = 0,
    mesh=None,
    warm_start: LogisticParams | None = None,
) -> LogisticParams:
    """Data-parallel minibatch SGD whose weight update is sharded across
    the mesh instead of replicated. Same objective scaling as
    :func:`~fraud_detection_tpu.ops.logistic.logistic_fit_sgd` (1/n-scaled
    sklearn primal, cosine-decayed lr); ``warm_start`` seeds the sharded
    params from the incumbent champion — the conductor's retrain path."""
    mesh = mesh or default_mesh()
    ndev = int(mesh.shape[DATA_AXIS])
    x_np = np.asarray(x, np.float32)
    y_np = np.asarray(y)
    n, d = x_np.shape
    d_pad = _pad_features(d, ndev)
    if d_pad != d:
        x_np = np.pad(x_np, ((0, 0), (0, d_pad - d)))
    sw = _resolve_sample_weight(y_np, sample_weight, class_weight)
    batch_size = _cap_batch_size(n, ndev, batch_size)

    mult = ndev * batch_size
    x_pad, _ = pad_to_multiple(x_np, mult)
    y_pad, _ = pad_to_multiple(y_np, mult)
    sw_pad, _ = pad_to_multiple(sw, mult)
    valid = np.zeros((x_pad.shape[0],), np.float32)
    valid[:n] = 1.0
    y_pm = np.where(y_pad > 0, 1.0, -1.0).astype(np.float32)

    x_dev, _ = shard_batch(x_pad, mesh)
    y_dev, _ = shard_batch(y_pm, mesh)
    sw_dev, _ = shard_batch(sw_pad, mesh)
    valid_dev, _ = shard_batch(valid, mesh)

    param_sharding = NamedSharding(mesh, P(DATA_AXIS))
    coef0 = np.zeros((d_pad,), np.float32)
    b0 = np.float32(0.0)
    if warm_start is not None:
        coef0[:d] = np.asarray(warm_start.coef, np.float32)
        b0 = np.float32(warm_start.intercept)
    coef_sh = jax.device_put(coef0, param_sharding)
    vel_sh = jax.device_put(np.zeros((d_pad,), np.float32), param_sharding)
    intercept = jnp.float32(b0)
    vel_b = jnp.float32(0.0)

    n_local = x_pad.shape[0] // ndev
    rng = np.random.default_rng(seed)
    for e in range(epochs):
        lr_e = jnp.float32(lr * 0.5 * (1.0 + np.cos(np.pi * e / max(epochs, 1))))
        coef_sh, vel_sh, intercept, vel_b = _sharded_update_epoch(
            coef_sh, vel_sh, intercept, vel_b,
            x_dev, y_dev, sw_dev, valid_dev,
            jnp.asarray(rng.permutation(n_local)), lr_e,
            mesh=mesh, c=float(c), n_total=int(n),
            momentum=float(momentum), batch=int(batch_size),
        )
    params = sync_fetch(
        LogisticParams(coef=coef_sh, intercept=intercept)
    )
    return LogisticParams(
        coef=jnp.asarray(np.asarray(params.coef)[:d]),
        intercept=params.intercept,
    )


# --------------------------------------------------------------------------
# Broadside: the 2-D (data × model) wide-family update (2004.13336 in 2-D)
# --------------------------------------------------------------------------


def wide_training_mesh(model_devices: int | None = None):
    """The 2-D retrain mesh for the wide family: all local devices,
    ``MESH_MODEL_DEVICES`` (or the override) on the model axis, the rest
    on data. Falls back to a pure data mesh when the model knob is off —
    the 1×1-model degenerate case is still the same program."""
    from fraud_detection_tpu import config

    m = model_devices if model_devices is not None else (
        config.mesh_model_devices() or 1
    )
    m = max(int(m), 1)
    n_dev = jax.device_count()
    if n_dev % m:
        raise ValueError(
            f"MESH_MODEL_DEVICES={m} does not divide the {n_dev} local "
            "devices"
        )
    return create_mesh(MeshSpec(data=n_dev // m, model=m))


#: w_wide layout on the 2-D mesh: the MODEL axis owns contiguous column
#: blocks (buckets/M each — the serving flush's column sharding), and the
#: DATA axis subdivides each block so the optimizer state is O(P/(D·M))
#: per device (2004.13336 extended to 2-D).
WIDE_PARAM_SPEC = P((MODEL_AXIS, DATA_AXIS))


def _wide_update_body(c: float, n_total: int, momentum: float, batch: int):
    """Per-(data,model)-shard epoch for the wide family under shard_map.

    Each step: the DATA axis ``all_gather``s the model group's column
    slice of w_wide just-in-time for the forward (each data shard owns
    1/D of its column block — params AND momentum state stay sharded);
    the forward's widened logit assembles with ONE ``psum`` over the
    MODEL axis (the serving flush's partial-dot idiom); the wide gradient
    is ``psum_scatter``'d over the DATA axis straight onto the owning
    slices — reduce + reshard in one hop, no model-axis gradient
    collective at all, because each model group's columns receive
    gradient only from its own cross indices. The 30-float base params
    stay replicated (sharding them buys nothing; the WIDE table is the
    O(P/N) article)."""

    def epoch(coef, vel, wl, wvl, intercept, vel_b,
              x_local, idx_local, has_local, y_pm_local, sw_local,
              valid_local, perm, lr):
        n_batches = x_local.shape[0] // batch

        def body(carry, i):
            coef, vel, wl, wvl, b, vel_b = carry
            # the model group's full column block, gathered over data
            w_col = jax.lax.all_gather(wl, DATA_AXIS, axis=0, tiled=True)
            n_col = w_col.shape[0]
            lo = (jax.lax.axis_index(MODEL_AXIS) * n_col).astype(jnp.int32)
            sel = jax.lax.dynamic_slice_in_dim(perm, i * batch, batch)
            xb = x_local[sel]
            ib = idx_local[sel]
            hb = has_local[sel]
            yb = y_pm_local[sel]
            swb = sw_local[sel]
            b_valid = jnp.maximum(
                jax.lax.psum(jnp.sum(valid_local[sel]), DATA_AXIS), 1.0
            )
            rel = ib - lo
            inb = (rel >= 0) & (rel < n_col)
            gathered = jnp.where(
                inb, w_col[jnp.clip(rel, 0, n_col - 1)], 0.0
            ) * hb[:, None]
            # THE model-axis collective: assemble the widened logit
            z_wide = jax.lax.psum(jnp.sum(gathered, axis=1), MODEL_AXIS)
            z = xb @ coef + z_wide + b
            # logistic gradient wrt z, 1/n-scaled sklearn primal like
            # logistic_fit_sgd (manual — differentiating through the psum
            # would double-count the model axis)
            g = swb * (-yb) * jax.nn.sigmoid(-yb * z) * (c / b_valid)
            gw = jax.lax.psum(xb.T @ g, DATA_AXIS) + coef / n_total
            gb = jax.lax.psum(jnp.sum(g), DATA_AXIS)
            # wide grads: scatter this shard's rows onto the column block,
            # then reduce+reshard over data in ONE psum_scatter hop
            vals = jnp.where(inb, (g * hb)[:, None], 0.0)
            g_col = jnp.zeros((n_col,), jnp.float32).at[
                jnp.clip(rel, 0, n_col - 1).ravel()
            ].add(vals.ravel())
            g_loc = jax.lax.psum_scatter(
                g_col, DATA_AXIS, scatter_dimension=0, tiled=True
            )
            g_loc = g_loc + wl / n_total
            vel = momentum * vel - lr * gw
            coef = coef + vel
            wvl_n = momentum * wvl - lr * g_loc
            wl_n = wl + wvl_n
            vel_b = momentum * vel_b - lr * gb
            b = b + vel_b
            return (coef, vel, wl_n, wvl_n, b, vel_b), None

        (coef, vel, wl, wvl, intercept, vel_b), _ = jax.lax.scan(
            body, (coef, vel, wl, wvl, intercept, vel_b),
            jnp.arange(n_batches),
        )
        return coef, vel, wl, wvl, intercept, vel_b

    return epoch


@partial(
    jax.jit,
    static_argnames=("mesh", "c", "n_total", "momentum", "batch"),
    donate_argnums=(0, 1, 2, 3),
)
def _wide_update_epoch(
    coef,      # (d_base,) replicated base coef
    vel,       # (d_base,) replicated base momentum
    wl,        # (buckets,) wide table, sharded (model-major, data-minor)
    wvl,       # (buckets,) wide momentum, sharded to match
    intercept,  # () replicated
    vel_b,      # () replicated
    x,         # (n, d_base) row-sharded over data (replicated over model)
    idx,       # (n, n_cross) int32 cross indices, row-sharded over data
    has,       # (n,) f32 has-entity mask, row-sharded over data
    y_pm,      # (n,) ±1 labels, row-sharded over data
    sw,        # (n,) sample weights (0 on padding), row-sharded over data
    valid,     # (n,) row validity, row-sharded over data
    perm,      # (n_local,) per-shard minibatch permutation, replicated
    lr,        # () replicated
    *,
    mesh,
    c: float,
    n_total: int,
    momentum: float,
    batch: int,
):
    """One epoch of the 2-D wide-family update: grads ``psum_scatter`` on
    the data axis, params already column-owned on the model axis
    (2004.13336 extended to the tensor-parallel mesh). Registered in
    meshcheck (``mesh.wide_update``) and the compile sentinel."""
    mapped = shard_map(
        _wide_update_body(c, n_total, momentum, batch),
        mesh=mesh,
        in_specs=(
            P(), P(), WIDE_PARAM_SPEC, WIDE_PARAM_SPEC, P(), P(),
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
            P(DATA_AXIS), P(DATA_AXIS), P(), P(),
        ),
        out_specs=(
            P(), P(), WIDE_PARAM_SPEC, WIDE_PARAM_SPEC, P(), P(),
        ),
        check_vma=False,
    )
    return mapped(
        coef, vel, wl, wvl, intercept, vel_b,
        x, idx, has, y_pm, sw, valid, perm, lr,
    )


def wide_sgd_fit(
    x,
    idx,
    has,
    y,
    cross_spec,
    c: float = 1.0,
    epochs: int = 5,
    batch_size: int = 4096,
    lr: float = 0.3,
    momentum: float = 0.9,
    class_weight: dict | str | None = None,
    sample_weight=None,
    seed: int = 0,
    mesh=None,
    warm_start: tuple | None = None,
) -> tuple[LogisticParams, np.ndarray]:
    """Fit the wide family on the 2-D (data × model) mesh.

    ``x`` is the (scaled) base block, ``idx`` the per-row hashed cross
    indices (``ops/crosses.cross_indices`` over the RAW rows — the values
    serving hashes), ``has`` the has-entity mask. ``warm_start`` is the
    champion's ``(base LogisticParams in this scaler's space, wide
    table)`` pair. Returns ``(widened LogisticParams, wide table)``: the
    widened coef is the base coef followed by one 1.0 per cross template
    (the contribution columns enter the logit with unit weight — the
    learned mass lives in the table), exactly the parametrization the
    fused wide flush scores."""
    mesh = mesh or wide_training_mesh()
    shape = dict(mesh.shape)
    n_data = int(shape[DATA_AXIS])
    n_model = int(shape.get(MODEL_AXIS, 1))
    buckets = cross_spec.buckets
    if buckets % (n_data * n_model):
        raise ValueError(
            f"WIDE_BUCKETS={buckets} does not shard over the "
            f"{n_data}×{n_model} mesh"
        )
    x_np = np.asarray(x, np.float32)
    idx_np = np.asarray(idx, np.int32)
    has_np = np.asarray(has, np.float32)
    y_np = np.asarray(y)
    n, d = x_np.shape
    sw = _resolve_sample_weight(y_np, sample_weight, class_weight)
    batch_size = _cap_batch_size(n, n_data, batch_size)

    mult = n_data * batch_size
    x_pad, _ = pad_to_multiple(x_np, mult)
    idx_pad, _ = pad_to_multiple(idx_np, mult)
    has_pad, _ = pad_to_multiple(has_np, mult)
    y_pad, _ = pad_to_multiple(y_np, mult)
    sw_pad, _ = pad_to_multiple(sw, mult)
    valid = np.zeros((x_pad.shape[0],), np.float32)
    valid[:n] = 1.0
    y_pm = np.where(y_pad > 0, 1.0, -1.0).astype(np.float32)

    row_sharding = NamedSharding(mesh, P(DATA_AXIS))
    put = lambda a: jax.device_put(a, row_sharding)  # noqa: E731
    wide_sharding = NamedSharding(mesh, WIDE_PARAM_SPEC)

    coef0 = np.zeros((d,), np.float32)
    table0 = np.zeros((buckets,), np.float32)
    b0 = np.float32(0.0)
    if warm_start is not None:
        base_params, warm_table = warm_start
        if base_params is not None:
            coef0[:] = np.asarray(base_params.coef, np.float32)[:d]
            b0 = np.float32(base_params.intercept)
        if warm_table is not None:
            table0[:] = np.asarray(warm_table, np.float32)
    coef = jnp.asarray(coef0)
    vel = jnp.zeros_like(coef)
    wl = jax.device_put(table0, wide_sharding)
    wvl = jax.device_put(np.zeros((buckets,), np.float32), wide_sharding)
    intercept = jnp.float32(b0)
    vel_b = jnp.float32(0.0)

    x_dev = put(x_pad)
    idx_dev = put(idx_pad)
    has_dev = put(has_pad)
    y_dev = put(y_pm)
    sw_dev = put(sw_pad)
    valid_dev = put(valid)

    n_local = x_pad.shape[0] // n_data
    rng = np.random.default_rng(seed)
    for e in range(epochs):
        lr_e = jnp.float32(
            lr * 0.5 * (1.0 + np.cos(np.pi * e / max(epochs, 1)))
        )
        coef, vel, wl, wvl, intercept, vel_b = _wide_update_epoch(
            coef, vel, wl, wvl, intercept, vel_b,
            x_dev, idx_dev, has_dev, y_dev, sw_dev, valid_dev,
            jnp.asarray(rng.permutation(n_local)), lr_e,
            mesh=mesh, c=float(c), n_total=int(n),
            momentum=float(momentum), batch=int(batch_size),
        )
    base_coef = np.asarray(jax.device_get(coef), np.float32)
    table = np.asarray(jax.device_get(wl), np.float32)
    widened = np.concatenate(
        [base_coef, np.ones(cross_spec.n_cross, np.float32)]
    )
    params = LogisticParams(
        coef=jnp.asarray(widened), intercept=jnp.asarray(jax.device_get(intercept)),
    )
    return params, table


# --------------------------------------------------------------------------
# MapReduce feedback-pool aggregation (2403.07128)
# --------------------------------------------------------------------------


def _pool_body(x, y, s, v):
    """Map: this shard's pool summary. Reduce: psum over the data axis."""
    red = lambda t: jax.lax.psum(t, DATA_AXIS)  # noqa: E731
    n = red(jnp.sum(v))
    n_pos = red(jnp.sum(v * y))
    s_sum = red(jnp.sum(v * s))
    fx = red(v @ x)
    fx2 = red(v @ (x * x))
    return n, n_pos, s_sum, fx, fx2


@partial(jax.jit, static_argnames=("mesh",))
def _pool_stats(x, y, scores, valid, *, mesh):
    mapped = shard_map(
        _pool_body,
        mesh=mesh,
        in_specs=(P(DATA_AXIS),) * 4,
        out_specs=(P(),) * 5,
        check_vma=False,
    )
    return mapped(x, y, scores, valid)


def mapreduce_pool_stats(x, y, scores, mesh=None) -> dict:
    """Aggregate a (possibly sharded-origin) labeled feedback pool into the
    summary the retrain executor logs and gates on: row/positive counts,
    score mean, per-feature mean/std — computed map-side per shard, reduced
    with one psum, never concatenated on host."""
    x_np = np.asarray(x, np.float32)
    if x_np.ndim == 1:
        x_np = x_np[None, :]
    n, d = x_np.shape
    if n == 0:
        zeros = np.zeros((d,), np.float64)
        return {
            "rows": 0, "positives": 0, "label_rate": 0.0,
            "score_mean": 0.0, "feature_mean": zeros, "feature_std": zeros,
        }
    mesh = mesh or default_mesh()
    x_dev, _ = shard_batch(x_np, mesh)
    y_dev, _ = shard_batch(np.asarray(y, np.float32), mesh)
    s_dev, _ = shard_batch(np.asarray(scores, np.float32), mesh)
    valid = np.zeros((x_dev.shape[0],), np.float32)
    valid[:n] = 1.0
    v_dev, _ = shard_batch(valid, mesh)
    cnt, n_pos, s_sum, fx, fx2 = _pool_stats(
        x_dev, y_dev, s_dev, v_dev, mesh=mesh
    )
    cnt_f = max(float(cnt), 1.0)
    mean = np.asarray(fx, np.float64) / cnt_f
    var = np.maximum(np.asarray(fx2, np.float64) / cnt_f - mean**2, 0.0)
    return {
        "rows": int(round(float(cnt))),
        "positives": int(round(float(n_pos))),
        "label_rate": float(n_pos) / cnt_f,
        "score_mean": float(s_sum) / cnt_f,
        "feature_mean": mean,
        "feature_std": np.sqrt(var),
    }
