"""The longhaul front: multi-format ingress, per-host routing, PR-6/7
degradation contracts at fleet scope.

Routing is the two-moduli placement applied to each row: the entity's
ledger slot names a segment (``slot mod N_hosts``), the membership view
names the segment's live owner (ring inheritance), and the front ships
each owner ONE framed sub-batch — one flush on the owning host. Rows of
the same slot always share a segment, so they always travel together,
which is the invariant that keeps routed scores bitwise equal to a
single-host serve (the ledger fold is per-slot; see
:mod:`fraud_detection_tpu.longhaul.placement`).

The health machine is the shard front's, lifted per-host:

- transport/handler failures strike; ``death_threshold`` consecutive
  strikes flip a handle HEALTHY → DEAD — **unless it is the last live
  host** (last-healthy-host protection: a front that can talk to nobody
  must keep trying somebody);
- a DEAD handle sits out ``probation_s``, then HALF_OPEN admits exactly
  ONE probe; success revives, failure re-arms probation;
- an owner that answers the explicit 503 (``{"unavailable": true}`` —
  inheriting, or its lifeboat mid-recovery) is **backpressure, not
  failure**: no strike, the caller gets 503 + Retry-After in its own
  format. The data plane never answers worse than that.

Stale views self-heal: a routing failure forces a view refresh; if the
segment's owner changed under us (failover completed), the front retries
the new owner once before surfacing the 503.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.longhaul import codec, placement
from fraud_detection_tpu.longhaul.codec import Unavailable
from fraud_detection_tpu.longhaul.membership import (
    DirectoryClient,
    MembershipView,
)
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.service.wire import (
    attach_auth,
    parse_hostport,
    recv_frame,
    send_frame,
)

log = logging.getLogger("fraud_detection_tpu.longhaul")

HEALTHY = "healthy"
DEAD = "dead"
HALF_OPEN = "half_open"

#: minimum seconds between implicit view refreshes on the hot path
_VIEW_TTL_S = 0.25


class HostHandle:
    """One member's data-plane connection + health state."""

    def __init__(self, host_id: str, rank: int, addr: str, token: str):
        self.host_id = host_id
        self.rank = rank
        self.addr = addr
        self.token = token
        self.state = HEALTHY
        self.consecutive_errors = 0
        self.dead_since = 0.0
        self._probe_inflight = False
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self, timeout: float) -> socket.socket:
        if self._sock is None:
            host, port = parse_hostport(self.addr, 7400)
            self._sock = socket.create_connection(
                (host, port), timeout=timeout
            )
            self._sock.settimeout(timeout)
        return self._sock

    def call(self, op: str, args: dict, timeout: float = 30.0):
        """One request/response over the persistent connection. Raises
        OSError/RuntimeError on transport or handler failure (a strike);
        the caller interprets the result dict."""
        with self._lock:
            try:
                sock = self._connect(timeout)
                req = {"op": op, "args": args}
                if self.token:
                    req = attach_auth(req, self.token)
                send_frame(sock, req)
                resp = recv_frame(sock)
            except OSError:
                self._drop_conn()
                raise
            if resp is None:
                self._drop_conn()
                raise ConnectionError(f"{self.host_id} closed connection")
            if not resp.get("ok"):
                raise RuntimeError(
                    f"{self.host_id} {op}: {resp.get('error')}"
                )
            return resp["result"]

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop_conn()


class LonghaulFront:
    """Routes scoring traffic to segment owners under the current
    membership view; the fleet-scope twin of ``mesh/front.ShardFront``."""

    def __init__(
        self,
        spec,
        n_hosts: int,
        directory_addr: str | None = None,
        view: MembershipView | None = None,
        token: str | None = None,
        death_threshold: int = 3,
        probation_s: float | None = None,
        retry_after_s: float | None = None,
        call_timeout: float = 30.0,
    ):
        self.spec = spec
        self.n_hosts = int(n_hosts)
        self.directory_addr = directory_addr
        self.token = token if token is not None else config.store_token()
        self.death_threshold = int(death_threshold)
        self.probation_s = (
            probation_s
            if probation_s is not None
            else config.longhaul_probation_s()
        )
        self.retry_after_s = (
            retry_after_s
            if retry_after_s is not None
            else config.longhaul_retry_after_s()
        )
        self.call_timeout = call_timeout
        self.view: MembershipView | None = view
        self.handles: dict[int, HostHandle] = {}
        self._view_lock = threading.Lock()
        self._last_refresh = 0.0
        if view is not None:
            self._bind_view(view)
        elif directory_addr is not None:
            self.refresh_view(force=True)
        else:
            raise ValueError("need directory_addr or a static view")

    # -- membership view ---------------------------------------------------
    def refresh_view(self, force: bool = False) -> MembershipView:
        with self._view_lock:
            now = time.monotonic()
            if (
                not force
                and self.view is not None
                and now - self._last_refresh < _VIEW_TTL_S
            ):
                return self.view
            if self.directory_addr is not None:
                try:
                    view = DirectoryClient(
                        self.directory_addr, token=self.token
                    ).view()
                except (OSError, RuntimeError):
                    if self.view is None:
                        raise
                    return self.view  # serve on the last-known view
                if self.view is None or view.epoch != self.view.epoch:
                    self._bind_view(view)
            self._last_refresh = now
            return self.view

    def _bind_view(self, view: MembershipView) -> None:
        old = self.handles
        new: dict[int, HostHandle] = {}
        for m in view.members:
            if not m.alive:
                continue
            prev = old.get(m.rank)
            if prev is not None and prev.addr == m.addr:
                new[m.rank] = prev  # keep connection + health state
            else:
                new[m.rank] = HostHandle(
                    m.host_id, m.rank, m.addr, self.token
                )
        for rank, h in old.items():
            if new.get(rank) is not h:
                h.close()
        self.handles = new
        self.view = view
        log.info(
            "longhaul front: view epoch %d, live ranks %s",
            view.epoch, sorted(new),
        )

    # -- health machine ----------------------------------------------------
    def _pick(self, segment: int) -> HostHandle:
        view = self.view
        live = sorted(self.handles)
        if not live:
            metrics.longhaul_unavailable.inc()
            raise Unavailable("no live hosts", self.retry_after_s)
        rank = placement.segment_owner(segment, live, view.n_hosts)
        h = self.handles[rank]
        if h.state == DEAD:
            if time.monotonic() - h.dead_since >= self.probation_s:
                if not h._probe_inflight:
                    h._probe_inflight = True
                    h.state = HALF_OPEN  # this caller is the one probe
                    return h
            metrics.longhaul_unavailable.inc()
            raise Unavailable(
                f"owner {h.host_id} dead (probation)", self.retry_after_s
            )
        if h.state == HALF_OPEN:
            # someone else's probe is in flight: shed, don't pile on
            metrics.longhaul_unavailable.inc()
            raise Unavailable(
                f"owner {h.host_id} half-open", self.retry_after_s
            )
        return h

    def _record_failure(self, h: HostHandle) -> None:
        metrics.longhaul_route_errors.labels(h.host_id).inc()
        h.consecutive_errors += 1
        live = [x for x in self.handles.values() if x.state == HEALTHY]
        if h.state == HALF_OPEN:
            h.state = DEAD
            h.dead_since = time.monotonic()
            h._probe_inflight = False
            return
        if h.consecutive_errors >= self.death_threshold:
            # last-healthy-host protection: never give up on the only
            # host we can still name — keep striking, keep trying
            if not (len(live) == 1 and live[0] is h):
                h.state = DEAD
                h.dead_since = time.monotonic()
                log.warning(
                    "longhaul front: %s DEAD after %d strikes",
                    h.host_id, h.consecutive_errors,
                )

    def _record_success(self, h: HostHandle) -> None:
        if h.state != HEALTHY:
            log.info("longhaul front: %s revived", h.host_id)
        h.state = HEALTHY
        h.consecutive_errors = 0
        h._probe_inflight = False

    # -- routing -----------------------------------------------------------
    def score(
        self, rows, ents, fmt: str = "json"
    ) -> np.ndarray:
        """Route one batch: group rows by owning host (same-slot rows
        always share a group), one flush per owner, reassemble in request
        order. ``ents[i]`` is ``(slot, fp, ts)`` or None (null rows ride
        segment 0 deterministically)."""
        self.refresh_view()
        rows = np.asarray(rows, np.float32)
        n = rows.shape[0]
        groups: dict[int, list[int]] = {}
        for i in range(n):
            ent = ents[i]
            seg = (
                placement.host_of(int(ent[0]), self.n_hosts)
                if ent is not None
                else 0
            )
            groups.setdefault(seg, []).append(i)
        out = np.empty(n, np.float32)
        for seg in sorted(groups):
            idx = groups[seg]
            sub_rows = rows[idx]
            sub_ents = [
                list(ents[i]) if ents[i] is not None else None
                for i in idx
            ]
            scores = self._route_segment(seg, sub_rows, sub_ents, fmt)
            out[idx] = scores
        return out

    def _route_segment(
        self, segment: int, rows: np.ndarray, ents: list, fmt: str
    ) -> np.ndarray:
        h = self._pick(segment)
        try:
            result = h.call(
                "score",
                {"rows": codec.pack_array(rows), "ents": ents},
                timeout=self.call_timeout,
            )
        except (OSError, RuntimeError):
            self._record_failure(h)
            # the owner may have changed under us (failover completed):
            # force a view refresh and retry the NEW owner exactly once
            self.refresh_view(force=True)
            h2 = self._pick(segment)
            if h2 is h:
                metrics.longhaul_unavailable.inc()
                raise Unavailable(
                    f"segment {segment} owner unreachable",
                    self.retry_after_s,
                ) from None
            try:
                result = h2.call(
                    "score",
                    {"rows": codec.pack_array(rows), "ents": ents},
                    timeout=self.call_timeout,
                )
            except (OSError, RuntimeError):
                self._record_failure(h2)
                metrics.longhaul_unavailable.inc()
                raise Unavailable(
                    f"segment {segment} owner unreachable",
                    self.retry_after_s,
                ) from None
            h = h2
        if result.get("unavailable"):
            # explicit backpressure (inheriting/recovering): NOT a strike
            metrics.longhaul_unavailable.inc()
            raise Unavailable(
                f"{h.host_id}: {result.get('reason', 'unavailable')}",
                float(result.get("retry_after_s", self.retry_after_s)),
            )
        self._record_success(h)
        metrics.longhaul_routed_rows.labels(h.host_id, fmt).inc(
            rows.shape[0]
        )
        return codec.unpack_array(result["scores"]).astype(np.float32)

    # -- the multi-format edge --------------------------------------------
    def handle_request(self, payload: bytes, fmt: str) -> bytes:
        """Decode (json/msgpack/binary) → route → encode in kind. The 503
        floor is honored per format (JSON/msgpack bodies carry
        ``status: 503`` + ``retry_after_s``; binary answers the hyperloop
        UNAVAILABLE status frame with a retry hint)."""
        rows, ents = codec.decode_request(payload, fmt, self.spec)
        try:
            scores = self.score(rows, ents, fmt=fmt)
        except Unavailable as e:
            return codec.encode_unavailable(
                str(e), e.retry_after_s, fmt
            )
        return codec.encode_response(scores, fmt)

    # -- control plane helpers --------------------------------------------
    def drive_failover(self, dead_rank: int, peer_dir: str) -> dict | None:
        """Instruct the ring inheritor of ``dead_rank``'s segments to
        replay the dead peer's generation. Idempotent per view: returns
        the inheritor's summary, or None when nothing is inheritable
        (rank unknown or still alive in the current view)."""
        view = self.refresh_view(force=True)
        dead = view.member_by_rank(dead_rank)
        if dead is None or dead.alive:
            return None
        live = view.live_ranks
        if not live:
            return None
        # the dead rank's home segment (segment r lives on rank r)
        segs = [dead_rank]
        inheritor_rank = placement.segment_owner(
            dead_rank, live, view.n_hosts
        )
        h = self.handles.get(inheritor_rank)
        if h is None:
            return None
        summary = h.call(
            "inherit",
            {"peer_dir": peer_dir, "segments": segs, "epoch": view.epoch},
            timeout=max(self.call_timeout, 120.0),
        )
        return summary

    def status(self) -> dict:
        view = self.view
        return {
            "epoch": view.epoch if view else None,
            "n_hosts": self.n_hosts,
            "hosts": {
                h.host_id: {
                    "rank": rank,
                    "state": h.state,
                    "consecutive_errors": h.consecutive_errors,
                }
                for rank, h in sorted(self.handles.items())
            },
        }

    def close(self) -> None:
        for h in self.handles.values():
            h.close()
