"""longhaul — the multi-host switchyard: a cross-process serving mesh.

The shard front (``mesh/front.py``) shards only within one process;
longhaul spreads the same contracts across hosts. One keyspace, two
moduli: an entity's ledger slot picks its owning HOST via
``slot mod N_hosts`` (outer level, :mod:`.placement`) and its device
shard within that host via the existing ``slot mod n_shards`` rule
(inner level, ``ledger/placement.py``) — the two levels compose because
both are congruences on the same slot integer.

Layers (one module each):

- :mod:`.membership` — the netstore-disciplined host directory:
  heartbeats, epoch-numbered membership views, durable state.
- :mod:`.placement` — segment ownership, ring inheritance on host
  death, and the host-side segment merge used by failover.
- :mod:`.front` — the routing tier: JSON / msgpack / binary frames in,
  rows grouped per owning host (same-slot rows always travel together,
  which is what keeps routed scores bitwise), PR-6/7 degradation
  contracts out (503 + Retry-After, last-healthy-host protection,
  per-host half-open probation).
- :mod:`.host` — one serving process: wraps the micro-batcher +
  lifeboat stack behind a framed-socket data plane, inherits a dead
  peer's segment by replaying the peer's journal+snapshot generation
  (``lifeboat/recovery.py`` — the bitwise-replay guarantee, segment
  scoped), epoch-fences promotion finalization.
- :mod:`.fleet` — the cross-host reduce: per-host partial pools, one
  merge (the DrJAX idiom at host level); a mesh-collective path for
  jax.distributed process meshes and a socket allreduce fallback, both
  behind one interface and both meshcheck/contract-proven.
- :mod:`.scrape` — fleet drift-window merge and /slo/status
  aggregation with the epoch fence: two membership epochs never
  double-count a host's window.
"""

from fraud_detection_tpu.longhaul.membership import (  # noqa: F401
    DirectoryClient,
    DirectoryServer,
    MemberInfo,
    MembershipView,
)
from fraud_detection_tpu.longhaul.placement import (  # noqa: F401
    host_of,
    merge_segment,
    owned_segments,
    segment_mask,
    segment_owner,
)
