"""Request/response codecs for the longhaul front tier.

The front accepts the SAME scoring request in three formats and answers
in kind:

- ``json`` — ``{"rows": [[...]], "entities": [...|null], "ts": [...]}``;
- ``msgpack`` — the identical schema, msgpack-packed (rides the
  ``application/msgpack`` content type like the binlane HTTP fallback);
- ``binary`` — the hyperloop frame layout (``service/binlane.py``'s
  versioned wire contract: magic/version/layout header, little-endian
  f32 feature block, u32 entity fingerprints, f64 timestamps), so a
  binlane client can point at the longhaul front unchanged.

Whatever the ingress format, the canonical internal form is the same
``(rows f32[n,d], ents)`` pair the micro-batcher flushes — ``ents[i]``
is ``(slot, fingerprint, rel_ts)`` or ``None`` — which is what keeps
routed scores bitwise across formats: the format only changes how bytes
arrive, never the floats that reach the fused body. Float fidelity notes:
JSON/msgpack carry f32 values through f64, which is exact in both
directions; the binary path ships the f32 bytes themselves.

Host-to-host frames (front → owning host) use base64-packed f32 blocks
inside the framed-JSON wire (``service/wire.py``) — bitwise-safe and
auditable with the same tooling as the netstore protocol.
"""

from __future__ import annotations

import base64
import json

import numpy as np

from fraud_detection_tpu.ledger.state import entity_slot
from fraud_detection_tpu.service.binlane import (
    FLAG_ENTITY,
    FLAG_TS,
    LAYOUT_F32,
    MAGIC,
    ST_OK,
    ST_UNAVAILABLE,
    VERSION,
    _ERRPAY,
    _FRAME,
    _RESP,
)

FORMATS = ("json", "msgpack", "binary")


class Unavailable(Exception):
    """The typed 503: the segment's owner is inheriting, or no healthy
    host serves it. Always carries a Retry-After hint — the degradation
    contract's floor (never worse than 503 + Retry-After)."""

    def __init__(self, message: str, retry_after_s: float):
        self.retry_after_s = retry_after_s
        super().__init__(message)


# -- base64 array packing (host-to-host frames) ----------------------------

def pack_array(arr: np.ndarray) -> dict:
    # shape from the ORIGINAL array: ascontiguousarray promotes 0-d
    # scalars to (1,), and a reduced scalar must come back 0-d
    a = np.asarray(arr)
    return {
        "b64": base64.b64encode(
            np.ascontiguousarray(a).tobytes()
        ).decode("ascii"),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def unpack_array(d: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"])


def pack_table(state) -> dict:
    return {
        name: pack_array(np.asarray(getattr(state, name)))
        for name in ("acc", "last_ts", "fingerprint", "collisions",
                     "evictions")
    }


def unpack_table(d: dict):
    from fraud_detection_tpu.ledger.state import LedgerState

    return LedgerState(**{name: unpack_array(d[name]) for name in d})


# -- the three ingress formats ---------------------------------------------

def _ents_from_ids(entities, ts, spec):
    ents = []
    for i, ent in enumerate(entities):
        if ent is None:
            ents.append(None)
        else:
            s, fp = spec.row_keys(ent)
            ents.append((s, fp, float(ts[i])))
    return ents


def decode_request(payload: bytes, fmt: str, spec):
    """Decode one scoring request → ``(rows f32[n,d], ents)``."""
    if fmt == "json":
        return _decode_mapping(json.loads(payload.decode("utf-8")), spec)
    if fmt == "msgpack":
        import msgpack

        return _decode_mapping(msgpack.unpackb(payload, raw=False), spec)
    if fmt == "binary":
        return _decode_binary(payload, spec)
    raise ValueError(f"unknown request format: {fmt}")


def _decode_mapping(obj: dict, spec):
    rows = np.asarray(obj["rows"], np.float32)
    if rows.ndim == 1:
        rows = rows[None, :]
    n = rows.shape[0]
    entities = obj.get("entities") or [None] * n
    ts = obj.get("ts") or [0.0] * n
    return rows, _ents_from_ids(entities, ts, spec)


def _decode_binary(payload: bytes, spec):
    """The hyperloop request frame (f32 layout). Entities arrive as u32
    fingerprints — the slot derives from the SAME multiply-shift hash the
    JSON edge applies, so an entity keyed on any lane shares one slot
    (and therefore one owning host)."""
    if len(payload) < _FRAME.size:
        raise ValueError("short binary frame")
    magic, version, layout, d, flags, n = _FRAME.unpack_from(payload, 0)
    if magic != MAGIC or version != VERSION:
        raise ValueError("bad magic/version")
    if layout != LAYOUT_F32:
        raise ValueError("longhaul front accepts the f32 layout only")
    off = _FRAME.size
    need = n * d * 4
    rows = np.frombuffer(
        payload, dtype="<f4", count=n * d, offset=off
    ).reshape(n, d).astype(np.float32)
    off += need
    fps = None
    if flags & FLAG_ENTITY:
        fps = np.frombuffer(payload, dtype="<u4", count=n, offset=off)
        off += n * 4
    ts = None
    if flags & FLAG_TS:
        ts = np.frombuffer(payload, dtype="<f8", count=n, offset=off)
        off += n * 8
    ents = []
    for i in range(n):
        fp = int(fps[i]) if fps is not None else 0
        if fp == 0:
            ents.append(None)  # the reserved null path
        else:
            slot = entity_slot(fp, spec.log2_slots)
            t = float(ts[i]) if ts is not None else 0.0
            ents.append((slot, fp, t))
    return rows, ents


def encode_request(rows, entities, ts, fmt: str, spec=None) -> bytes:
    """Client-side encoder (tests/bench drive the front with this)."""
    rows = np.asarray(rows, np.float32)
    n = rows.shape[0]
    if fmt == "json":
        return json.dumps(
            {
                "rows": rows.astype(np.float64).tolist(),
                "entities": list(entities),
                "ts": [float(t) for t in ts],
            }
        ).encode("utf-8")
    if fmt == "msgpack":
        import msgpack

        return msgpack.packb(
            {
                "rows": rows.astype(np.float64).tolist(),
                "entities": list(entities),
                "ts": [float(t) for t in ts],
            },
            use_single_float=False,
        )
    if fmt == "binary":
        if spec is None:
            raise ValueError("binary encoding needs the ledger spec")
        from fraud_detection_tpu.ledger.state import entity_fingerprint

        fps = np.zeros(n, "<u4")
        for i, ent in enumerate(entities):
            if ent is not None:
                fps[i] = entity_fingerprint(ent)
        hdr = _FRAME.pack(
            MAGIC, VERSION, LAYOUT_F32, rows.shape[1],
            FLAG_ENTITY | FLAG_TS, n,
        )
        return (
            hdr
            + rows.astype("<f4").tobytes()
            + fps.tobytes()
            + np.asarray(ts, "<f8").tobytes()
        )
    raise ValueError(f"unknown request format: {fmt}")


def encode_response(scores: np.ndarray, fmt: str) -> bytes:
    scores = np.asarray(scores, np.float32)
    if fmt == "json":
        return json.dumps(
            {"scores": scores.astype(np.float64).tolist()}
        ).encode("utf-8")
    if fmt == "msgpack":
        import msgpack

        return msgpack.packb(
            {"scores": scores.astype(np.float64).tolist()},
            use_single_float=False,
        )
    if fmt == "binary":
        hdr = _RESP.pack(MAGIC, VERSION, ST_OK, 0, scores.shape[0])
        return hdr + scores.astype("<f4").tobytes()
    raise ValueError(f"unknown response format: {fmt}")


def encode_unavailable(message: str, retry_after_s: float, fmt: str) -> bytes:
    """The 503 + Retry-After floor, in the caller's own format."""
    if fmt == "json":
        return json.dumps(
            {"error": message, "status": 503,
             "retry_after_s": retry_after_s}
        ).encode("utf-8")
    if fmt == "msgpack":
        import msgpack

        return msgpack.packb(
            {"error": message, "status": 503,
             "retry_after_s": retry_after_s}
        )
    if fmt == "binary":
        msg = message.encode("utf-8")
        hdr = _RESP.pack(MAGIC, VERSION, ST_UNAVAILABLE, 0, len(msg))
        return hdr + _ERRPAY.pack(int(retry_after_s * 1000.0)) + msg
    raise ValueError(f"unknown response format: {fmt}")


def decode_response(payload: bytes, fmt: str) -> np.ndarray:
    """Decode a front response; raises :class:`Unavailable` on the 503."""
    if fmt in ("json", "msgpack"):
        if fmt == "json":
            obj = json.loads(payload.decode("utf-8"))
        else:
            import msgpack

            obj = msgpack.unpackb(payload, raw=False)
        if obj.get("status") == 503:
            raise Unavailable(
                obj.get("error", "unavailable"),
                float(obj.get("retry_after_s", 1.0)),
            )
        return np.asarray(obj["scores"], np.float32)
    if fmt == "binary":
        magic, version, status, _k, n = _RESP.unpack_from(payload, 0)
        if magic != MAGIC or version != VERSION:
            raise ValueError("bad response magic/version")
        if status == ST_OK:
            return np.frombuffer(
                payload, dtype="<f4", count=n, offset=_RESP.size
            ).astype(np.float32)
        (retry_ms,) = _ERRPAY.unpack_from(payload, _RESP.size)
        msg = payload[_RESP.size + _ERRPAY.size:].decode(
            "utf-8", "replace"
        )
        raise Unavailable(msg or "unavailable", retry_ms / 1000.0)
    raise ValueError(f"unknown response format: {fmt}")
