"""Cluster membership: the netstore-disciplined host directory.

One small control-plane server (the same framed-JSON wire and durable
state discipline as ``service/netserver.py``) owns the fleet's source of
truth: which host ranks exist, which are live, and the **membership
epoch** — a monotone integer bumped on every membership change (join,
death, leave, rejoin). The epoch is the fleet's fence token:

- routing views are stamped with the epoch they were computed under;
- promotion finalization is refused when the finalizing host's epoch is
  stale (:meth:`~fraud_detection_tpu.longhaul.host.HostServer.finalize_promotion`);
- fleet scrapes merge only contributions reported under ONE epoch, so a
  split-brained host can never be double-counted
  (:mod:`fraud_detection_tpu.longhaul.scrape`).

State durability follows netserver exactly: ``members.json`` is written
tmp → fsync → ``os.replace`` under the ``longhaul.members`` lock on every
mutation, so a restarted directory resumes with the same ranks and a
STRICTLY higher epoch (restart bumps once — any view issued by the old
incarnation is thereby fenced). Heartbeat times are deliberately
volatile: after a restart every member must prove liveness afresh.

The liveness rule is crash-detector standard: a member that has not
heartbeated within ``dead_after_s`` is marked dead by the sweeper and the
epoch bumps. Death here means *membership* death — the host's segment is
up for inheritance — not process death; a partitioned-but-running host
discovers its own death on its next heartbeat (``{"stale": true}``) and
must stop finalizing anything fenced by its old epoch.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from dataclasses import dataclass

from fraud_detection_tpu import config
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.service.wire import (
    CONN_STALL_TIMEOUT,
    attach_auth,
    check_auth,
    recv_frame,
    send_frame,
)
from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.longhaul")

_STATE_FILE = "members.json"
#: sweeper tick — liveness resolution, far below any sane dead_after_s
_TICK_S = 0.05


@dataclass(frozen=True)
class MemberInfo:
    host_id: str
    rank: int
    addr: str  # "host:port" of the member's data plane
    alive: bool


@dataclass(frozen=True)
class MembershipView:
    """An epoch-stamped snapshot of the fleet. ``n_hosts`` is the segment
    count (fixed fleet geometry), ``members`` the known ranks."""

    epoch: int
    n_hosts: int
    members: tuple[MemberInfo, ...]

    @property
    def live_ranks(self) -> tuple[int, ...]:
        return tuple(m.rank for m in self.members if m.alive)

    def member_by_rank(self, rank: int) -> MemberInfo | None:
        for m in self.members:
            if m.rank == rank:
                return m
        return None

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "n_hosts": self.n_hosts,
            "members": [
                {
                    "host_id": m.host_id,
                    "rank": m.rank,
                    "addr": m.addr,
                    "alive": m.alive,
                }
                for m in self.members
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MembershipView":
        return cls(
            epoch=int(d["epoch"]),
            n_hosts=int(d["n_hosts"]),
            members=tuple(
                MemberInfo(
                    host_id=m["host_id"],
                    rank=int(m["rank"]),
                    addr=m["addr"],
                    alive=bool(m["alive"]),
                )
                for m in d["members"]
            ),
        )


class DirectoryServer:
    """The membership directory. Start with :meth:`start`; every mutation
    holds :attr:`_members_lock` (lockdep ``longhaul.members``) across
    {mutate → persist → epoch bump} so a concurrent ``view`` can never
    observe a membership change without its epoch."""

    def __init__(
        self,
        directory: str,
        n_hosts: int,
        port: int = 0,
        host: str = "127.0.0.1",
        dead_after_s: float | None = None,
        token: str | None = None,
    ):
        self.directory = directory
        self.n_hosts = int(n_hosts)
        self.dead_after_s = (
            dead_after_s
            if dead_after_s is not None
            else config.longhaul_dead_after_s()
        )
        self.token = token if token is not None else config.store_token()
        self._members_lock = lockdep.lock("longhaul.members")
        self.epoch = 0
        #: host_id -> {rank, addr, alive}
        self.members: dict[str, dict] = {}
        #: volatile: host_id -> last heartbeat monotonic time
        self._last_hb: dict[str, float] = {}
        self._load_state()
        # a restarted directory fences every view the old incarnation
        # issued: bump once, durably, before serving anything
        with self._members_lock:
            self.epoch += 1
            self._save_state()  # graftcheck: ignore[blocking-under-lock] -- the restart fence must be durable before any view is served
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # graftcheck: ignore[socket-no-timeout] -- listener blocks in accept by design; close() unblocks it
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- durable state (netserver discipline) -----------------------------
    def _state_path(self) -> str:
        return os.path.join(self.directory, _STATE_FILE)

    def _load_state(self) -> None:
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return
        self.epoch = int(st.get("epoch", 0))
        self.members = {
            hid: dict(m) for hid, m in st.get("members", {}).items()
        }
        # liveness is volatile: every member re-proves itself after a
        # directory restart (they are "alive" only once they heartbeat)
        for m in self.members.values():
            m["alive"] = False

    def _save_state(self) -> None:
        """tmp → fsync → replace, under the members lock."""
        os.makedirs(self.directory, exist_ok=True)
        path = self._state_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": self.epoch, "members": self.members}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        metrics.longhaul_membership_epoch.set(self.epoch)
        metrics.longhaul_hosts_live.set(
            sum(1 for m in self.members.values() if m["alive"])
        )

    # -- view --------------------------------------------------------------
    def view(self) -> MembershipView:
        with self._members_lock:
            return self._view_locked()

    def _view_locked(self) -> MembershipView:
        return MembershipView(
            epoch=self.epoch,
            n_hosts=self.n_hosts,
            members=tuple(
                MemberInfo(
                    host_id=hid,
                    rank=int(m["rank"]),
                    addr=m["addr"],
                    alive=bool(m["alive"]),
                )
                for hid, m in sorted(
                    self.members.items(), key=lambda kv: kv[1]["rank"]
                )
            ),
        )

    # -- mutations ---------------------------------------------------------
    def join(self, host_id: str, addr: str) -> MembershipView:
        """Admit (or revive) a member. Rank assignment is sticky: a known
        host_id keeps its rank across rejoins (its segment follows it);
        a new host takes the lowest free rank. Epoch bumps."""
        with self._members_lock:
            known = self.members.get(host_id)
            if known is None:
                used = {int(m["rank"]) for m in self.members.values()}
                free = [r for r in range(self.n_hosts) if r not in used]
                if not free:
                    raise ValueError(
                        f"fleet full: {self.n_hosts} ranks, "
                        f"{len(self.members)} members"
                    )
                self.members[host_id] = {
                    "rank": free[0], "addr": addr, "alive": True,
                }
            else:
                known["addr"] = addr
                known["alive"] = True
            self._last_hb[host_id] = time.monotonic()
            self.epoch += 1
            self._save_state()  # graftcheck: ignore[blocking-under-lock] -- a join must be durable atomically with its epoch bump, or a directory crash forgets the member but not the fence
            metrics.longhaul_host_up.labels(host_id).set(1)
            log.info(
                "longhaul: %s joined as rank %d (epoch %d)",
                host_id, self.members[host_id]["rank"], self.epoch,
            )
            return self._view_locked()

    def heartbeat(self, host_id: str) -> dict:
        """Record liveness. A member the directory considers dead gets
        ``{"stale": true}`` — its cue to rejoin and re-fence."""
        with self._members_lock:
            m = self.members.get(host_id)
            if m is None or not m["alive"]:
                return {"epoch": self.epoch, "stale": True}
            self._last_hb[host_id] = time.monotonic()
            metrics.longhaul_host_heartbeat_age.labels(host_id).set(0.0)
            return {"epoch": self.epoch, "stale": False}

    def leave(self, host_id: str) -> MembershipView:
        with self._members_lock:
            m = self.members.get(host_id)
            if m is not None and m["alive"]:
                m["alive"] = False
                self.epoch += 1
                self._save_state()  # graftcheck: ignore[blocking-under-lock] -- a leave must be durable atomically with its epoch bump
                self._drop_member_series(host_id)
                log.info(
                    "longhaul: %s left (epoch %d)", host_id, self.epoch
                )
            return self._view_locked()

    def mark_dead(self, host_id: str) -> MembershipView:
        """Administrative/failure-detector death — same epoch semantics as
        a missed-heartbeat death."""
        with self._members_lock:
            self._mark_dead_locked(host_id)
            return self._view_locked()

    def _mark_dead_locked(self, host_id: str) -> None:
        m = self.members.get(host_id)
        if m is None or not m["alive"]:
            return
        m["alive"] = False
        self.epoch += 1
        self._save_state()
        self._drop_member_series(host_id)
        log.warning(
            "longhaul: %s marked dead (epoch %d) — segment up for "
            "inheritance", host_id, self.epoch,
        )

    def _drop_member_series(self, host_id: str) -> None:
        # stale-series discipline: a dead member's gauges must not read
        # as live on dashboards (counters stay; their rate goes quiet)
        metrics.longhaul_host_up.labels(host_id).set(0)
        metrics.drop_host_gauges(host_id)

    # -- sweeper + accept loop --------------------------------------------
    def _sweep(self) -> None:
        now = time.monotonic()
        with self._members_lock:
            for hid, m in self.members.items():
                if not m["alive"]:
                    continue
                last = self._last_hb.get(hid)
                if last is None:
                    # joined before a directory restart and silent since:
                    # start its clock at first observation
                    self._last_hb[hid] = now
                    continue
                age = now - last
                metrics.longhaul_host_heartbeat_age.labels(hid).set(age)
                if age > self.dead_after_s:
                    self._mark_dead_locked(hid)

    def start(self) -> None:
        t = threading.Thread(
            target=self._accept_loop, name="longhaul-dir", daemon=True
        )
        t.start()
        self._threads.append(t)
        s = threading.Thread(
            target=self._sweep_loop, name="longhaul-sweep", daemon=True
        )
        s.start()
        self._threads.append(s)

    def _sweep_loop(self) -> None:
        while not self._stop.wait(_TICK_S):
            try:
                self._sweep()
            except Exception:
                log.exception("longhaul sweeper tick failed")

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed on stop
            conn.settimeout(CONN_STALL_TIMEOUT)
            t = threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            )
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    req = recv_frame(conn)
                except TimeoutError:
                    continue  # idle at a frame boundary: re-arm
                except OSError:
                    return  # stalled mid-frame or reset: drop
                if req is None:
                    return
                try:
                    if self.token and not check_auth(req, self.token):
                        send_frame(
                            conn,
                            {"ok": False, "error": "unauthorized",
                             "kind": "auth"},
                        )
                        continue
                    result = self._dispatch(
                        req.get("op", ""), req.get("args", {})
                    )
                    send_frame(conn, {"ok": True, "result": result})
                except OSError:
                    return
                except Exception as e:  # surfaced to the client in-band
                    log.debug("directory op failed", exc_info=True)
                    try:
                        send_frame(
                            conn,
                            {"ok": False, "error": str(e),
                             "kind": type(e).__name__},
                        )
                    except OSError:
                        return

    def _dispatch(self, op: str, args: dict):
        if op == "join":
            return self.join(args["host_id"], args["addr"]).to_dict()
        if op == "heartbeat":
            return self.heartbeat(args["host_id"])
        if op == "leave":
            return self.leave(args["host_id"]).to_dict()
        if op == "mark_dead":
            return self.mark_dead(args["host_id"]).to_dict()
        if op == "view":
            return self.view().to_dict()
        if op == "ping":
            return {"pong": True, "epoch": self.epoch}
        raise ValueError(f"unknown op: {op}")

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)


class DirectoryClient:
    """Thin control-plane client: one short-lived connection per call
    (membership traffic is rare; simplicity beats pooling here)."""

    def __init__(
        self,
        addr: str,
        token: str | None = None,
        timeout: float = 5.0,
    ):
        from fraud_detection_tpu.service.wire import parse_hostport

        self.host, self.port = parse_hostport(addr, 7300)
        self.token = token if token is not None else config.store_token()
        self.timeout = timeout

    def _call(self, op: str, **args):
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        ) as sock:
            sock.settimeout(self.timeout)
            req = {"op": op, "args": args}
            if self.token:
                req = attach_auth(req, self.token)
            send_frame(sock, req)
            resp = recv_frame(sock)
        if resp is None:
            raise ConnectionError("directory closed the connection")
        if not resp.get("ok"):
            raise RuntimeError(
                f"directory {op} failed: {resp.get('error')}"
            )
        return resp["result"]

    def join(self, host_id: str, addr: str) -> MembershipView:
        return MembershipView.from_dict(
            self._call("join", host_id=host_id, addr=addr)
        )

    def heartbeat(self, host_id: str) -> dict:
        return self._call("heartbeat", host_id=host_id)

    def leave(self, host_id: str) -> MembershipView:
        return MembershipView.from_dict(self._call("leave", host_id=host_id))

    def mark_dead(self, host_id: str) -> MembershipView:
        return MembershipView.from_dict(
            self._call("mark_dead", host_id=host_id)
        )

    def view(self) -> MembershipView:
        return MembershipView.from_dict(self._call("view"))
