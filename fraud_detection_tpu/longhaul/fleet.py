"""Cross-host reduce: per-host partial pools, one merge.

The DrJAX MapReduce idiom (2403.07128) lifted from the device mesh to the
host fleet: every host computes its partials with a LOCAL jitted body
(zero collectives — the map side), and exactly one merge combines them.
Two merge transports stand behind one interface:

- :class:`MeshReducer` — the jax.distributed path. The merge is a
  ``shard_map`` psum over the data axis of a mesh; when
  ``jax.distributed.initialize`` has run, that mesh's devices span
  processes and the SAME program object reduces across hosts over DCN.
  Single-process (tier-1, meshcheck) it degenerates to the local mesh —
  which is exactly what lets the contract prover pin its collective
  budget without a multi-host CI fleet.
- :class:`SocketReducer` — the fallback where jax.distributed is not
  available: hosts ship their partial arrays over the framed wire to the
  rank-0 coordinator, which sums **in rank order** (one fixed float
  association) and broadcasts the result bytes. Every host applies
  byte-identical sums, so fleet-replicated state (the SGD weights) can
  never diverge.

Both transports satisfy ``allreduce(arrays) -> arrays`` and both are
meshcheck/contract-proven: the map bodies (``longhaul.partial_pool``,
``longhaul.fleet_grad``) carry empty collective budgets, the merge bodies
(``longhaul.pool_merge`` {psum: 5}, ``longhaul.grad_merge`` {psum: 2})
carry exact ones.

:func:`fleet_pool_stats` and :func:`fleet_sgd_fit` are the host-level
twins of ``mesh/retrain.mapreduce_pool_stats`` / ``mesh_sgd_fit``: same
summary keys, same objective scaling, data distributed per host instead
of per device shard.
"""

from __future__ import annotations

import logging
import socket
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.longhaul import codec
from fraud_detection_tpu.parallel.compat import shard_map
from fraud_detection_tpu.parallel.mesh import DATA_AXIS, create_mesh
from fraud_detection_tpu.service.wire import (
    attach_auth,
    check_auth,
    recv_frame,
    send_frame,
)

from jax.sharding import NamedSharding, PartitionSpec as P

log = logging.getLogger("fraud_detection_tpu.longhaul")


# -- map side: local jitted bodies (zero collectives) ----------------------

@jax.jit
def _host_partial_pool(x, y, s, v):
    """This host's pool partials — the map half of the fleet pool merge.
    Same five sums as ``mesh/retrain._pool_body`` minus the psum: the
    reduce happens at host level, through whichever transport."""
    n = jnp.sum(v)
    n_pos = jnp.sum(v * y)
    s_sum = jnp.sum(v * s)
    fx = v @ x
    fx2 = v @ (x * x)
    return n, n_pos, s_sum, fx, fx2


@jax.jit
def _host_grad(coef, intercept, x, y_pm, sw):
    """This host's UN-normalized data-term gradient sums for one minibatch
    (sklearn primal: d/dz Σ sw·softplus(−y·z) = −y·σ(−y·z)·sw). The
    1/n_total scaling, L2 term, and momentum update run host-side after
    the merge so every host applies the identical reduced floats."""
    z = x @ coef + intercept
    m = jax.nn.sigmoid(-y_pm * z) * sw * (-y_pm)
    return m @ x, jnp.sum(m)


# -- merge side: the mesh-collective path ----------------------------------

def _merge_pool_body(n, n_pos, s_sum, fx, fx2):
    red = lambda t: jax.lax.psum(jnp.sum(t, axis=0), DATA_AXIS)  # noqa: E731
    return red(n), red(n_pos), red(s_sum), red(fx), red(fx2)


@partial(jax.jit, static_argnames=("mesh",))
def _fleet_pool_merge(n, n_pos, s_sum, fx, fx2, *, mesh):
    """ONE shard_map dispatch merging per-host pool partials stacked on a
    hosts axis — 5 psums, one per summary component (the declared budget;
    anything else on this path is a contract violation)."""
    mapped = shard_map(
        _merge_pool_body,
        mesh=mesh,
        in_specs=(P(DATA_AXIS),) * 5,
        out_specs=(P(),) * 5,
        check_vma=False,
    )
    return mapped(n, n_pos, s_sum, fx, fx2)


def _merge_grad_body(g_coef, g_b):
    red = lambda t: jax.lax.psum(jnp.sum(t, axis=0), DATA_AXIS)  # noqa: E731
    return red(g_coef), red(g_b)


@partial(jax.jit, static_argnames=("mesh",))
def _fleet_grad_merge(g_coef, g_b, *, mesh):
    """ONE shard_map dispatch merging per-host gradient partials — 2
    psums (coef block, intercept)."""
    mapped = shard_map(
        _merge_grad_body,
        mesh=mesh,
        in_specs=(P(DATA_AXIS),) * 2,
        out_specs=(P(),) * 2,
        check_vma=False,
    )
    return mapped(g_coef, g_b)


# -- the one interface -----------------------------------------------------

class LocalReducer:
    """Degenerate single-host transport: the merge of one partial is the
    partial."""

    n_hosts = 1
    rank = 0

    def allreduce(self, arrays):
        return [np.asarray(a, np.float32) for a in arrays]

    def close(self) -> None:
        pass


class MeshReducer:
    """The jax.distributed path: partials reduce through the mesh psum
    bodies. Under ``jax.distributed.initialize`` the mesh spans processes
    and the psum crosses hosts over DCN; single-process it runs on the
    local mesh (how tier-1 and the contract prover exercise the SAME
    program object)."""

    def __init__(self, mesh=None):
        self.mesh = mesh if mesh is not None else create_mesh()
        self.n_hosts = int(np.prod(list(self.mesh.shape.values())))
        self.rank = jax.process_index()

    @staticmethod
    def available() -> bool:
        return jax.process_count() > 1

    def allreduce(self, arrays):
        """Generic allreduce via the grad-merge body, pairwise. Partials
        enter with a leading hosts axis of size 1 per contributor and the
        psum folds across the axis."""
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        out = []
        for a in arrays:
            a = np.asarray(a, np.float32)
            stacked = jnp.asarray(
                np.broadcast_to(a[None], (self.n_hosts,) + a.shape)
                / np.float32(self.n_hosts)
            )
            stacked = jax.device_put(stacked, sharding)
            merged = _fleet_grad_merge(
                stacked.reshape(self.n_hosts, -1),
                jnp.zeros((self.n_hosts,), jnp.float32),
                mesh=self.mesh,
            )[0]
            out.append(np.asarray(merged, np.float32).reshape(a.shape))
        return out

    def close(self) -> None:
        pass


class SocketReducer:
    """Rank-order deterministic socket allreduce. Rank 0 coordinates:
    collects one partial per rank per step, sums rank 0 → N−1 (a fixed
    float association), broadcasts the result bytes. Synchronous
    lockstep — exactly the cadence of an SGD loop."""

    def __init__(
        self,
        rank: int,
        n_hosts: int,
        addr: str,
        token: str | None = None,
        timeout: float = 60.0,
    ):
        from fraud_detection_tpu.service.wire import parse_hostport

        self.rank = int(rank)
        self.n_hosts = int(n_hosts)
        self.token = token if token is not None else config.store_token()
        self.timeout = timeout
        self._host, self._port = parse_hostport(addr, 7500)
        self._step = 0
        self._lock = threading.Lock()
        if self.rank == 0:
            self._listener = socket.socket(  # graftcheck: ignore[socket-no-timeout] -- coordinator listener blocks in accept by design (lockstep reduce); close() unblocks it
                socket.AF_INET, socket.SOCK_STREAM
            )
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
            self._listener.bind((self._host, self._port))
            self._listener.listen(self.n_hosts)
            self.addr = "%s:%d" % self._listener.getsockname()[:2]
            self._peers: dict[int, socket.socket] = {}
        else:
            self._listener = None
            self.addr = f"{self._host}:{self._port}"
            self._conn: socket.socket | None = None

    # -- rank 0 ------------------------------------------------------------
    def _accept_peers(self) -> None:
        while len(self._peers) < self.n_hosts - 1:
            conn, _ = self._listener.accept()
            conn.settimeout(self.timeout)
            hello = recv_frame(conn)
            if self.token and not check_auth(hello, self.token):
                send_frame(conn, {"ok": False, "error": "unauthorized"})
                conn.close()
                continue
            peer_rank = int(hello["args"]["rank"])
            self._peers[peer_rank] = conn
            send_frame(conn, {"ok": True, "result": {"rank": peer_rank}})

    def _coordinate(self, arrays):
        if len(self._peers) < self.n_hosts - 1:
            self._accept_peers()
        partials = {0: [np.asarray(a, np.float32) for a in arrays]}
        for rank, conn in self._peers.items():
            msg = recv_frame(conn)
            step = int(msg["step"])
            if step != self._step:
                raise RuntimeError(
                    f"reduce step skew: rank {rank} at {step}, "
                    f"coordinator at {self._step}"
                )
            partials[rank] = [
                codec.unpack_array(d) for d in msg["arrays"]
            ]
        # rank-order sum: ONE float association, every host gets the
        # same bytes
        totals = [a.copy() for a in partials[0]]
        for rank in range(1, self.n_hosts):
            for i, a in enumerate(partials[rank]):
                totals[i] = totals[i] + a.astype(np.float32)
        packed = [codec.pack_array(t) for t in totals]
        for conn in self._peers.values():
            send_frame(conn, {"step": self._step, "arrays": packed})
        return totals

    # -- rank > 0 ----------------------------------------------------------
    def _participant(self, arrays):
        if self._conn is None:
            self._conn = socket.create_connection(
                (self._host, self._port), timeout=self.timeout
            )
            self._conn.settimeout(self.timeout)
            hello = {"op": "hello", "args": {"rank": self.rank}}
            if self.token:
                hello = attach_auth(hello, self.token)
            send_frame(self._conn, hello)
            ack = recv_frame(self._conn)
            if ack is None or not ack.get("ok"):
                raise ConnectionError("reduce coordinator refused hello")
        send_frame(
            self._conn,
            {
                "step": self._step,
                "arrays": [
                    codec.pack_array(np.asarray(a, np.float32))
                    for a in arrays
                ],
            },
        )
        msg = recv_frame(self._conn)
        if msg is None:
            raise ConnectionError("reduce coordinator went away")
        return [codec.unpack_array(d) for d in msg["arrays"]]

    def allreduce(self, arrays):
        with self._lock:
            out = (
                self._coordinate(arrays)
                if self.rank == 0
                else self._participant(arrays)
            )
            self._step += 1
            return out

    def close(self) -> None:
        if self.rank == 0:
            for conn in self._peers.values():
                try:
                    conn.close()
                except OSError:
                    pass
            try:
                self._listener.close()
            except OSError:
                pass
        elif self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass


def make_reducer(
    rank: int = 0,
    n_hosts: int = 1,
    addr: str | None = None,
    token: str | None = None,
):
    """One interface, two transports: the jax.distributed mesh psum where
    a process mesh exists, the socket allreduce where it doesn't, a no-op
    for a fleet of one."""
    if n_hosts <= 1:
        return LocalReducer()
    if MeshReducer.available():
        return MeshReducer()
    if addr is None:
        raise ValueError("SocketReducer needs the coordinator addr")
    return SocketReducer(rank, n_hosts, addr, token=token)


# -- host-level MapReduce entrants -----------------------------------------

def fleet_pool_stats(x, y, scores, reducer) -> dict:
    """Host-level twin of ``mesh/retrain.mapreduce_pool_stats``: every
    host maps its OWN labeled pool through the local jitted body, the
    fleet merges once. Same summary keys, plus ``hosts``."""
    x_np = np.asarray(x, np.float32)
    if x_np.ndim == 1:
        x_np = x_np[None, :]
    n, d = x_np.shape
    if n:
        v = np.ones((n,), np.float32)
        parts = _host_partial_pool(
            jnp.asarray(x_np),
            jnp.asarray(np.asarray(y, np.float32)),
            jnp.asarray(np.asarray(scores, np.float32)),
            jnp.asarray(v),
        )
        parts = [np.asarray(p, np.float32) for p in parts]
    else:
        parts = [
            np.zeros((), np.float32),
            np.zeros((), np.float32),
            np.zeros((), np.float32),
            np.zeros((d,), np.float32),
            np.zeros((d,), np.float32),
        ]
    cnt, n_pos, s_sum, fx, fx2 = reducer.allreduce(parts)
    cnt_f = max(float(cnt), 1.0)
    mean = np.asarray(fx, np.float64) / cnt_f
    var = np.maximum(np.asarray(fx2, np.float64) / cnt_f - mean**2, 0.0)
    return {
        "rows": int(round(float(cnt))),
        "positives": int(round(float(n_pos))),
        "label_rate": float(n_pos) / cnt_f,
        "score_mean": float(s_sum) / cnt_f,
        "feature_mean": mean,
        "feature_std": np.sqrt(var),
        "hosts": reducer.n_hosts,
    }


def fleet_sgd_fit(
    x,
    y,
    reducer,
    c: float = 1.0,
    epochs: int = 5,
    batch_size: int = 4096,
    lr: float = 0.3,
    momentum: float = 0.9,
    sample_weight=None,
    seed: int = 0,
    warm_start=None,
):
    """Host-level data-parallel minibatch SGD: each host holds ITS data
    partition, computes local gradient sums with the jitted map body, and
    applies the IDENTICAL update after one fleet merge per step — the
    2004.13336 contract with the fleet as the data axis. Every host must
    call with the same hyperparameters and seed; the permutation is
    seeded per-host (rank-salted) so partitions shuffle independently
    while the weights stay fleet-replicated (the merged gradient bytes
    are identical everywhere by the rank-order-sum guarantee)."""
    from fraud_detection_tpu.models.logistic import LogisticParams

    x_np = np.asarray(x, np.float32)
    y_np = np.asarray(y)
    n, d = x_np.shape
    sw = (
        np.asarray(sample_weight, np.float32)
        if sample_weight is not None
        else np.ones((n,), np.float32)
    )
    y_pm = np.where(y_np > 0, 1.0, -1.0).astype(np.float32)

    # fleet geometry first: the step count derives from n_total, which
    # every host learns from the same reduce — lockstep by construction
    geom = reducer.allreduce([np.asarray([n], np.float32)])[0]
    n_total = int(round(float(geom[0])))
    steps = max(
        1, n_total // (reducer.n_hosts * max(batch_size, 1))
    )

    coef = np.zeros((d,), np.float32)
    b = np.float32(0.0)
    if warm_start is not None:
        coef[:] = np.asarray(warm_start.coef, np.float32)
        b = np.float32(warm_start.intercept)
    vel = np.zeros((d,), np.float32)
    vel_b = np.float32(0.0)

    rng = np.random.default_rng(seed * 1000 + reducer.rank)
    for e in range(epochs):
        lr_e = np.float32(
            lr * 0.5 * (1.0 + np.cos(np.pi * e / max(epochs, 1)))
        )
        perm = rng.permutation(n)
        for s in range(steps):
            # wraparound slice keeps every host in lockstep even when
            # partitions are ragged
            start = (s * batch_size) % max(n, 1)
            idx = np.take(
                perm, np.arange(start, start + batch_size) % max(n, 1)
            ) if n else np.zeros((0,), np.int64)
            g_coef, g_b = _host_grad(
                jnp.asarray(coef),
                jnp.asarray(b),
                jnp.asarray(x_np[idx]),
                jnp.asarray(y_pm[idx]),
                jnp.asarray(sw[idx]),
            )
            g_coef, g_b, bc = reducer.allreduce(
                [np.asarray(g_coef, np.float32),
                 np.asarray(g_b, np.float32),
                 np.asarray(float(idx.size), np.float32)]
            )
            # mesh_sgd_fit's objective: c/|global batch| on the data
            # term, 1/n_total on the L2
            scale = np.float32(c) / np.float32(max(float(bc), 1.0))
            g_w = scale * g_coef + coef / np.float32(max(n_total, 1))
            g_bi = scale * g_b
            vel = momentum * vel - lr_e * g_w
            vel_b = np.float32(momentum * vel_b - lr_e * g_bi)
            coef = coef + vel
            b = np.float32(b + vel_b)
    return LogisticParams(
        coef=jnp.asarray(coef), intercept=jnp.asarray(b)
    )
