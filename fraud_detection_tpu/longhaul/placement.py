"""Two-level placement: one keyspace, two moduli.

``ledger/placement.py`` places a request's rows onto device shards with
``slot mod n_shards``; longhaul generalizes the SAME rule one level up:
``slot mod N_hosts`` names the host segment that owns the slot. Both are
congruences on the same slot integer, so they compose freely — a host
owns every table slot in its segment, and within the host the existing
shard rule subdivides them. Two facts carry all the correctness weight:

- **Same-slot rows always land on the same host.** The ledger fold and
  the widened-feature read are per-slot (nothing in the fused body mixes
  slots; ``collisions``/``evictions`` are per-slot *events* summed into
  scalars), so grouping a batch's rows by ``slot mod N`` and flushing
  each group on its owner preserves every slot's flush grouping exactly
  — routed scores and per-slot table leaves stay bitwise equal to a
  single-host serve of the same batches.
- **Segments are disjoint and cover the table**, so failover is a pure
  row-select: the inheritor copies the dead peer's segment rows (from
  the peer's recovered table) into its live table and SUMS the scalar
  event counters — no slot is ever owned twice.

Ring inheritance: segment ``r`` is served by rank ``r`` while alive;
when rank ``r`` dies its segment is inherited by the next LIVE rank
scanning upward with wrap-around. Deterministic, view-only (any observer
with the same membership view computes the same owner), and stable under
rejoin (the returning rank takes its own segment back).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from fraud_detection_tpu.ledger.state import LedgerState


def host_of(slot, n_hosts: int):
    """The outer modulus: segment index for a slot (scalar or ndarray)."""
    return slot % n_hosts


def segment_owner(segment: int, live: Sequence[int], n_hosts: int) -> int:
    """The live rank serving ``segment`` under ring inheritance.

    ``live`` is the set/sequence of live ranks from the current
    membership view. Scans ``segment, segment+1, ... (mod n_hosts)`` and
    returns the first live rank — the segment's own rank while it lives,
    its ring successor after it dies.
    """
    if not 0 <= segment < n_hosts:
        raise ValueError(f"segment {segment} out of range for {n_hosts} hosts")
    alive = set(live)
    if not alive:
        raise ValueError("no live hosts")
    for step in range(n_hosts):
        cand = (segment + step) % n_hosts
        if cand in alive:
            return cand
    raise ValueError(f"live ranks {sorted(alive)} outside 0..{n_hosts - 1}")


def owned_segments(
    rank: int, live: Sequence[int], n_hosts: int
) -> tuple[int, ...]:
    """Every segment ``rank`` currently serves (its own + inherited)."""
    return tuple(
        seg
        for seg in range(n_hosts)
        if segment_owner(seg, live, n_hosts) == rank
    )


def segment_mask(
    n_slots: int, segments: Iterable[int], n_hosts: int
) -> np.ndarray:
    """Boolean mask over table slots belonging to ``segments``."""
    slots = np.arange(n_slots, dtype=np.int64)
    mask = np.zeros(n_slots, dtype=bool)
    for seg in set(segments):
        mask |= (slots % n_hosts) == seg
    return mask


def merge_segment(
    dst: LedgerState,
    src: LedgerState,
    segments: Iterable[int],
    n_hosts: int,
    baseline: tuple[float, float] = (0.0, 0.0),
) -> LedgerState:
    """Fold ``src``'s rows for ``segments`` into ``dst`` (host numpy).

    Per-slot leaves are a pure row-select (the segments are disjoint from
    anything ``dst`` owns, so nothing is overwritten that mattered); the
    scalar event counters sum — each collision/eviction happened at one
    slot on one owner, so the sum counts every event exactly once.
    ``baseline`` is the ``(collisions, evictions)`` pair BOTH tables
    started from (the seeded warmup events every fleet member replicates
    at build): the sum subtracts it once so shared history is not
    double-counted. Same shapes/dtypes in and out: binding the merged
    table back into the drift monitor recompiles nothing.
    """
    acc = np.array(np.asarray(dst.acc), np.float32, copy=True)
    last_ts = np.array(np.asarray(dst.last_ts), np.float32, copy=True)
    fp = np.array(np.asarray(dst.fingerprint), np.uint32, copy=True)
    mask = segment_mask(last_ts.shape[-1], segments, n_hosts)
    acc[..., mask, :] = np.asarray(src.acc, np.float32)[..., mask, :]
    last_ts[..., mask] = np.asarray(src.last_ts, np.float32)[..., mask]
    fp[..., mask] = np.asarray(src.fingerprint, np.uint32)[..., mask]
    coll0, evic0 = np.float32(baseline[0]), np.float32(baseline[1])
    return LedgerState(
        acc=acc,
        last_ts=last_ts,
        fingerprint=fp,
        collisions=np.asarray(
            np.float32(dst.collisions) + np.float32(src.collisions) - coll0
        ),
        evictions=np.asarray(
            np.float32(dst.evictions) + np.float32(src.evictions) - evic0
        ),
    )


def segments_equal(
    a: LedgerState, b: LedgerState, segments: Iterable[int], n_hosts: int
) -> tuple[bool, str]:
    """Bitwise comparison of the per-slot leaves restricted to
    ``segments`` (the failover acceptance check: the inherited segment of
    the survivor's table vs the same segment of an uninterrupted serve).
    Scalar event counters are global, not per-segment — compare those
    separately with :func:`merge_segment`'s sum semantics in mind."""
    mask = segment_mask(
        np.asarray(a.last_ts).shape[-1], segments, n_hosts
    )
    for name in ("acc", "last_ts", "fingerprint"):
        av = np.asarray(getattr(a, name))
        bv = np.asarray(getattr(b, name))
        if name == "acc":  # (..., S, 3): slot axis is second-to-last
            av, bv = av[..., mask, :], bv[..., mask, :]
        else:
            av, bv = av[..., mask], bv[..., mask]
        if av.tobytes() != bv.tobytes():
            n_diff = int(np.sum(av != bv))
            return False, f"{name}: {n_diff} element(s) differ in segment"
    return True, "segment bitwise equal on per-slot leaves"
