"""Cross-host scrape: one merged drift window, one fleet SLO view.

Every host's ``scrape`` op returns its contribution stamped with the
membership epoch that host currently believes (``host.py``). The merge
here accepts ONLY contributions matching the coordinator's epoch — the
split-brain guard: a host on the wrong side of a partition keeps serving
its stale view, but its windows can never double-count into the fleet
aggregate, because the partition itself is what froze its epoch. Stale
contributions are counted (``longhaul_scrape_stale_epoch{host}``) and
dropped, never summed.

Drift windows merge by leaf-sum — the same reduce
``mesh/shardflush.merge_window`` applies over device shards, lifted one
level: decayed histograms are linear in their rows, so summing per-host
windows yields exactly the window a single host would have accumulated
over the union stream (same decay schedule assumed fleet-wide, which the
config layer pins).

SLO status merges on raw window counts (good/bad events add across
hosts); burn rate and budget-remaining derive from the SUMS, not from
averaging per-host ratios — a host serving 1% of traffic can't drag the
fleet budget with a noisy ratio. The result refreshes
``longhaul_fleet_budget_remaining{slo}``.
"""

from __future__ import annotations

import logging

import numpy as np

from fraud_detection_tpu.longhaul import codec
from fraud_detection_tpu.monitor.drift import DriftWindow
from fraud_detection_tpu.service import metrics

log = logging.getLogger("fraud_detection_tpu.longhaul")


def merge_drift_windows(contributions: list, epoch: int):
    """Sum same-epoch per-host windows into one fleet window.

    ``contributions`` are ``scrape`` op results (dicts with ``host_id``,
    ``epoch``, ``window`` as a packed 6-leaf list). Returns
    ``(DriftWindow | None, accepted_hosts, stale_hosts)``.
    """
    merged = None
    accepted: list[str] = []
    stale: list[str] = []
    for con in contributions:
        host = str(con.get("host_id", "?"))
        if int(con.get("epoch", -1)) != int(epoch):
            stale.append(host)
            metrics.longhaul_scrape_stale_epoch.labels(host).inc()
            log.warning(
                "longhaul scrape: dropping stale-epoch contribution "
                "from %s (theirs=%s fleet=%d)",
                host, con.get("epoch"), epoch,
            )
            continue
        accepted.append(host)
        if con.get("window") is None:
            continue
        leaves = [
            codec.unpack_array(d).astype(np.float32)
            for d in con["window"]
        ]
        win = DriftWindow(*leaves)
        if merged is None:
            merged = win
        else:
            merged = DriftWindow(
                *(a + b for a, b in zip(merged, win))
            )
    return merged, accepted, stale


def merge_slo_status(contributions: list, epoch: int) -> dict:
    """Fleet ``/slo/status``: add same-epoch raw counts per SLO, derive
    burn/budget from the sums, refresh the fleet budget gauges."""
    agg: dict[str, dict] = {}
    for con in contributions:
        if int(con.get("epoch", -1)) != int(epoch):
            continue  # merge_drift_windows already counted the stale hit
        for name, d in (con.get("slo") or {}).items():
            a = agg.setdefault(
                name,
                {
                    "objective": float(d.get("objective", 0.0)),
                    "window_good": 0,
                    "window_bad": 0,
                    "total_good": 0,
                    "total_bad": 0,
                    "hosts": 0,
                },
            )
            a["window_good"] += int(d.get("window_good", 0))
            a["window_bad"] += int(d.get("window_bad", 0))
            a["total_good"] += int(d.get("total_good", 0))
            a["total_bad"] += int(d.get("total_bad", 0))
            a["hosts"] += 1
    for name, a in agg.items():
        total = a["window_good"] + a["window_bad"]
        err_budget = max(1.0 - a["objective"], 1e-9)
        bad_rate = (a["window_bad"] / total) if total else 0.0
        a["burn_rate"] = round(bad_rate / err_budget, 4)
        a["budget_remaining"] = round(1.0 - a["burn_rate"], 4)
        metrics.longhaul_fleet_budget_remaining.labels(name).set(
            a["budget_remaining"]
        )
    return agg


def fleet_scrape(clients: list, epoch: int) -> dict:
    """Drive one fleet scrape: ask every reachable host, merge with the
    epoch fence. ``clients`` expose ``call(op, args)`` (front-tier
    :class:`~fraud_detection_tpu.longhaul.front.HostHandle` or anything
    shaped like it). Unreachable hosts are skipped — a scrape never
    blocks the fleet on a dead peer."""
    contributions = []
    unreachable: list[str] = []
    for cl in clients:
        try:
            contributions.append(cl.call("scrape", {}))
        except (OSError, RuntimeError) as exc:
            unreachable.append(getattr(cl, "host_id", "?"))
            log.warning("longhaul scrape: %s unreachable: %s",
                        getattr(cl, "host_id", "?"), exc)
    window, accepted, stale = merge_drift_windows(contributions, epoch)
    slo = merge_slo_status(contributions, epoch)
    rows_seen = sum(
        int(c.get("rows_seen", 0))
        for c in contributions
        if int(c.get("epoch", -1)) == int(epoch)
    )
    return {
        "epoch": int(epoch),
        "window": window,
        "slo": slo,
        "rows_seen": rows_seen,
        "accepted": accepted,
        "stale": stale,
        "unreachable": unreachable,
    }
