"""One longhaul serving host: the data-plane process behind the front.

Wraps the single-process serving stack (micro-batcher + watchtower +
lifeboat) behind a framed-socket data plane and adds the three things a
FLEET member needs that a lone process does not:

- **Membership**: join the directory at start, heartbeat every
  ``LONGHAUL_HEARTBEAT_S``, track the epoch the directory last told us.
  A ``{"stale": true}`` heartbeat answer means the failure detector
  declared us dead while we were partitioned — rejoin (epoch bumps) and
  treat everything fenced by the old epoch as void.
- **Segment inheritance** (:meth:`inherit`): replay a dead peer's
  journal+snapshot generation via ``lifeboat/recovery.py`` — the SAME
  bitwise replay path warm restart uses, pointed at the PEER's directory
  — then merge the peer's segment rows into the live table between
  flushes (under the lifeboat flush lock; same shapes/dtypes, so the
  warmed fused executables keep serving with zero new compiles). The
  host answers 503 + Retry-After while inheriting — readiness gating,
  never silent misroutes into a half-merged table.
- **Epoch-fenced promotion** (:meth:`finalize_promotion`): an alias flip
  decided under epoch ``e`` is refused unless the directory — consulted
  LIVE at finalize time — still reports epoch ``e`` with this host
  alive. A partitioned host cannot reach the directory, so it cannot
  finalize: fail-safe, the stale flip dies instead of moving traffic.

Lock order (enforced by lockdep): ``longhaul.inherit`` →
``lifeboat.flush`` — inheritance takes its own lock first, then briefly
couples to the flush path for the merge+rebind cut.

Runnable: ``python -m fraud_detection_tpu.longhaul.host --host-id h0
--port 7401 --directory 127.0.0.1:7300 --n-hosts 2 --seed 7 --data-dir
/var/lib/fraud/longhaul`` builds the seeded ledger-widened stack and
serves until killed — the subprocess fleet the bench and drills spawn.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.longhaul import codec, placement
from fraud_detection_tpu.longhaul.membership import DirectoryClient
from fraud_detection_tpu.range.faults import fire
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.service.wire import (
    CONN_STALL_TIMEOUT,
    check_auth,
    recv_frame,
    send_frame,
)
from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.longhaul")

READY = "ready"
INHERITING = "inheriting"


class LedgerBackend:
    """The serving stack one host owns: scorer + watchtower (drift/ledger
    bind) + micro-batcher + optional lifeboat. ``score_items`` drives the
    REAL flush body — staging, the journal hook, the fused stateful
    dispatch — so a routed sub-batch is one flush, exactly like a local
    one."""

    def __init__(
        self, scorer, watchtower, spec, microbatcher, boat=None,
        baseline_counters: tuple[float, float] = (0.0, 0.0),
    ):
        self.scorer = scorer
        self.watchtower = watchtower
        self.spec = spec
        self.mb = microbatcher
        self.boat = boat
        #: (collisions, evictions) of the SEEDED table every fleet member
        #: starts from — subtracted once when merging a peer's counters
        self.baseline_counters = baseline_counters
        self._tgt = microbatcher._fused_target(scorer)

    @property
    def drift(self):
        return self.watchtower.drift

    def score_items(self, items) -> np.ndarray:
        out = self.mb._flush_device(self.scorer, self._tgt, items, False)
        return np.asarray(out[0], np.float32)

    def table(self):
        return self.drift.ledger_snapshot()


class HostServer:
    """The framed-socket data plane + membership agent for one host."""

    def __init__(
        self,
        host_id: str,
        backend: LedgerBackend,
        n_hosts: int,
        port: int = 0,
        bind: str = "127.0.0.1",
        directory_addr: str | None = None,
        heartbeat_s: float | None = None,
        token: str | None = None,
        served_version: str | None = None,
    ):
        self.host_id = host_id
        self.backend = backend
        self.n_hosts = int(n_hosts)
        self.directory_addr = directory_addr
        self.heartbeat_s = (
            heartbeat_s
            if heartbeat_s is not None
            else config.longhaul_heartbeat_s()
        )
        self.token = token if token is not None else config.store_token()
        self.state = READY
        self.rank: int | None = None
        self.owned_segments: set[int] = set()
        #: segments this host has DATA for beyond its home segment —
        #: grown only by :meth:`inherit` (an explicit, replayed take-over)
        self._inherited: set[int] = set()
        self.known_epoch = 0
        self.served_version = served_version
        self.last_inherit: dict | None = None
        self._inherit_lock = lockdep.lock("longhaul.inherit")
        #: scenario hook: True simulates a network partition (heartbeats
        #: stop reaching the directory; data plane stays up — split brain)
        self.partitioned = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # graftcheck: ignore[socket-no-timeout] -- listener blocks in accept by design; kill() unblocks it
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind, port))
        self._sock.listen(64)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- membership --------------------------------------------------------
    def _directory(self) -> DirectoryClient | None:
        if self.directory_addr is None:
            return None
        if self.partitioned:
            # the partition: control-plane packets don't route. Pointing
            # the client at a dead port makes EVERY control call fail the
            # same way a real partition would — heartbeats never arrive
            # and finalize_promotion cannot consult the directory, so the
            # fence fails safe (unreachable = un-finalizable).
            return DirectoryClient("127.0.0.1:9", token=self.token, timeout=0.2)
        return DirectoryClient(self.directory_addr, token=self.token)

    def join(self) -> None:
        d = self._directory()
        if d is None:
            # directory-less single host: owns every segment
            self.rank = 0
            self.owned_segments = set(range(self.n_hosts))
            return
        view = d.join(self.host_id, self.addr)
        self.known_epoch = view.epoch
        me = next(m for m in view.members if m.host_id == self.host_id)
        self.rank = me.rank
        self._recompute_claim(view)
        log.info(
            "longhaul host %s: rank %d, segments %s, epoch %d",
            self.host_id, self.rank, sorted(self.owned_segments),
            self.known_epoch,
        )

    def _recompute_claim(self, view) -> None:
        """A host serves the intersection of what the ring ASSIGNS it and
        what it has DATA for (home segment + explicitly inherited). Ring
        assignment without data is never served silently — those rows get
        the 503 until :meth:`inherit` lands; data without assignment (a
        peer rejoined and took its segment back) is dropped from the
        claim so two hosts never serve one segment."""
        if self.rank is None:
            return
        ring = set(
            placement.owned_segments(
                self.rank, view.live_ranks, self.n_hosts
            )
        )
        have = {self.rank} | self._inherited
        self.owned_segments = ring & have
        self._inherited &= ring

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            if self.partitioned:
                continue  # the partition: beats never leave the host
            d = self._directory()
            if d is None:
                continue
            try:
                ans = d.heartbeat(self.host_id)
                if ans.get("stale"):
                    # the failure detector declared us dead while we were
                    # away: rejoin (epoch bumps, old fences void)
                    log.warning(
                        "longhaul host %s: heartbeat says stale — "
                        "rejoining", self.host_id,
                    )
                    self.join()
                elif int(ans["epoch"]) != self.known_epoch:
                    # membership changed: re-derive what we may serve
                    self.known_epoch = int(ans["epoch"])
                    try:
                        self._recompute_claim(d.view())
                    except (OSError, RuntimeError):
                        pass
            except (OSError, RuntimeError):
                log.warning(
                    "longhaul host %s: directory unreachable", self.host_id
                )

    # -- failover ----------------------------------------------------------
    def inherit(
        self, peer_dir: str, segments: set[int] | list[int], epoch: int,
    ) -> dict:
        """Warm-restart a dead peer's segment from its journal+snapshot
        generation and merge it into the live table. Returns a summary
        (replayed rows, duration, rows/s) the caller can publish."""
        from fraud_detection_tpu.lifeboat import recovery as recovery_mod

        segments = set(int(s) for s in segments)
        with self._inherit_lock:
            self.state = INHERITING
            metrics.longhaul_failover_in_progress.set(1)
            t0 = time.perf_counter()
            try:
                fire(
                    "longhaul.inherit",
                    host=self.host_id, segments=sorted(segments),
                )
                rep = recovery_mod.recover(peer_dir, self.backend.spec)
                boat = self.backend.boat
                flush_lock = (
                    boat.flush_lock if boat is not None
                    else threading.Lock()
                )
                with flush_lock:
                    # between flushes: nothing is mid-dispatch, the live
                    # table is quiescent for the segment splice
                    live = self.backend.table()
                    if (
                        rep.restored
                        and rep.state is not None
                        and live is not None
                    ):
                        merged = placement.merge_segment(
                            live, rep.state, segments, self.n_hosts,
                            baseline=self.backend.baseline_counters,
                        )
                        # same shapes/dtypes → zero new compiles
                        self.backend.drift.bind_ledger(
                            self.backend.spec, merged
                        )
                self._inherited |= segments
                self.owned_segments |= segments
                self.known_epoch = max(self.known_epoch, int(epoch))
                dt = time.perf_counter() - t0
                rows_per_sec = (
                    rep.replayed_rows / dt if dt > 0 else 0.0
                )
                summary = {
                    "segments": sorted(segments),
                    "restored": bool(rep.restored),
                    "replayed_rows": int(rep.replayed_rows),
                    "torn_rows": int(rep.torn_rows),
                    "duration_s": dt,
                    "replay_rows_per_sec": rows_per_sec,
                    "epoch": self.known_epoch,
                }
                self.last_inherit = summary
                metrics.longhaul_failovers.labels(self.host_id).inc()
                metrics.longhaul_failover_duration.set(dt)
                metrics.longhaul_inherited_rows.labels(self.host_id).inc(
                    rep.replayed_rows
                )
                metrics.longhaul_replay_rows_per_sec.set(rows_per_sec)
                log.info(
                    "longhaul host %s: inherited segments %s — %d rows "
                    "replayed in %.3fs", self.host_id, sorted(segments),
                    rep.replayed_rows, dt,
                )
                return summary
            finally:
                self.state = READY
                metrics.longhaul_failover_in_progress.set(0)

    # -- epoch-fenced promotion -------------------------------------------
    def finalize_promotion(self, version: str, epoch: int) -> dict:
        """Apply an alias flip decided under membership epoch ``epoch``.

        The fence consults the directory LIVE: the flip lands only if the
        current epoch still equals the deciding epoch AND this host is
        alive in the current view. A partitioned host cannot reach the
        directory → cannot finalize (fail-safe); a host the detector
        declared dead sees the epoch moved on → refuses. Either way the
        stale flip dies instead of moving traffic."""
        d = self._directory()
        if d is None:
            self.served_version = version
            return {"applied": True, "version": version, "epoch": epoch}
        try:
            view = d.view()
        except (OSError, RuntimeError) as e:
            metrics.longhaul_promotion_fenced.labels(self.host_id).inc()
            return {
                "applied": False, "fenced": True,
                "reason": f"directory unreachable: {e}",
            }
        me = next(
            (m for m in view.members if m.host_id == self.host_id), None
        )
        if view.epoch != int(epoch) or me is None or not me.alive:
            metrics.longhaul_promotion_fenced.labels(self.host_id).inc()
            return {
                "applied": False, "fenced": True,
                "reason": (
                    f"stale epoch: decided at {epoch}, directory at "
                    f"{view.epoch}, alive={bool(me and me.alive)}"
                ),
            }
        self.served_version = version
        self.known_epoch = view.epoch
        return {"applied": True, "version": version, "epoch": view.epoch}

    # -- data plane --------------------------------------------------------
    def start(self) -> None:
        self.join()
        t = threading.Thread(
            target=self._accept_loop,
            name=f"longhaul-{self.host_id}", daemon=True,
        )
        t.start()
        self._threads.append(t)
        if self.directory_addr is not None:
            hb = threading.Thread(
                target=self._hb_loop,
                name=f"longhaul-hb-{self.host_id}", daemon=True,
            )
            hb.start()
            self._threads.append(hb)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.settimeout(CONN_STALL_TIMEOUT)
            t = threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            )
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    req = recv_frame(conn)
                except TimeoutError:
                    continue
                except OSError:
                    return
                if req is None:
                    return
                try:
                    if self.token and not check_auth(req, self.token):
                        send_frame(
                            conn,
                            {"ok": False, "error": "unauthorized",
                             "kind": "auth"},
                        )
                        continue
                    result = self._dispatch(
                        req.get("op", ""), req.get("args", {})
                    )
                    send_frame(conn, {"ok": True, "result": result})
                except OSError:
                    return
                except Exception as e:  # surfaced to the caller in-band
                    log.debug("host op failed", exc_info=True)
                    try:
                        send_frame(
                            conn,
                            {"ok": False, "error": str(e),
                             "kind": type(e).__name__},
                        )
                    except OSError:
                        return

    def _dispatch(self, op: str, args: dict):
        if op == "score":
            return self._op_score(args)
        if op == "status":
            return self.status()
        if op == "table":
            table = self.backend.table()
            return codec.pack_table(table) if table is not None else None
        if op == "inherit":
            return self.inherit(
                args["peer_dir"], args["segments"], args.get("epoch", 0)
            )
        if op == "promote":
            return self.finalize_promotion(
                args["version"], args["epoch"]
            )
        if op == "scrape":
            return self._op_scrape()
        if op == "ping":
            return {"pong": True, "host_id": self.host_id}
        raise ValueError(f"unknown op: {op}")

    def _op_score(self, args: dict) -> dict:
        if self.state != READY:
            # readiness gate: 503 + Retry-After while inheriting — the
            # front surfaces this verbatim, never a silent misroute
            return {
                "unavailable": True,
                "retry_after_s": config.longhaul_retry_after_s(),
                "reason": self.state,
            }
        boat = self.backend.boat
        if boat is not None and boat.state == "recovering":
            return {
                "unavailable": True,
                "retry_after_s": config.longhaul_retry_after_s(),
                "reason": "recovering",
            }
        rows = codec.unpack_array(args["rows"]).astype(np.float32)
        ents_wire = args.get("ents") or [None] * rows.shape[0]
        # possession gate: the ring may assign us a dead peer's segment
        # before we've replayed its data — those rows get the 503, never
        # a silent serve from a table that hasn't inherited them
        need = {
            placement.host_of(int(e[0]), self.n_hosts)
            if e is not None else 0
            for e in ents_wire
        }
        missing = need - self.owned_segments
        if missing:
            return {
                "unavailable": True,
                "retry_after_s": config.longhaul_retry_after_s(),
                "reason": (
                    f"not owner of segment(s) {sorted(missing)} "
                    "(inheritance pending)"
                ),
            }
        items = []
        for i in range(rows.shape[0]):
            ent = ents_wire[i]
            if ent is not None:
                ent = (int(ent[0]), int(ent[1]), float(ent[2]))
            items.append((rows[i], None, None, ent))
        scores = self.backend.score_items(items)
        return {"scores": codec.pack_array(scores)}

    def _op_scrape(self) -> dict:
        """One host's contribution to a fleet scrape, stamped with the
        epoch this host currently believes — the merge side drops
        contributions whose epoch doesn't match the coordinator's
        (scrape.py: two epochs never double-count a window)."""
        from fraud_detection_tpu.telemetry import slo as slo_mod

        drift = self.backend.drift
        window = None
        if hasattr(drift, "window_snapshot"):
            w = drift.window_snapshot()
            if w is not None:
                window = [
                    codec.pack_array(np.asarray(leaf)) for leaf in w
                ]
        eng = slo_mod.engine()
        return {
            "host_id": self.host_id,
            "epoch": self.known_epoch,
            "rows_seen": int(getattr(drift, "rows_seen", 0)),
            "window": window,
            "slo": eng.snapshot() if eng is not None else {},
        }

    def status(self) -> dict:
        boat = self.backend.boat
        return {
            "host_id": self.host_id,
            "rank": self.rank,
            "state": self.state,
            "owned_segments": sorted(self.owned_segments),
            "epoch": self.known_epoch,
            "served_version": self.served_version,
            "addr": self.addr,
            "last_inherit": self.last_inherit,
            "lifeboat": boat.status() if boat is not None else None,
        }

    def kill(self) -> None:
        """Abrupt death (scenario hook): close the listener and stop all
        loops without leaving, flushing, or snapshotting — the directory
        finds out the hard way, via missed heartbeats."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self) -> None:
        """Clean shutdown: leave the directory first so the epoch bumps
        from an explicit leave, not a detector timeout."""
        d = self._directory()
        if d is not None:
            try:
                d.leave(self.host_id)
            except (OSError, RuntimeError):
                pass
        self.kill()
        for t in self._threads:
            t.join(timeout=2.0)


def build_seeded_backend(seed: int, data_dir: str, host_id: str):
    """Build the deterministic ledger-widened serving stack every fleet
    member (and the single-host parity reference) shares: same seed →
    same weights, same baseline profile, same spec — which is what makes
    routed scores comparable bitwise across processes."""
    from fraud_detection_tpu.lifeboat import Lifeboat
    from fraud_detection_tpu.range.scenarios import (
        _watchtower,
        build_ledger_model,
    )
    from fraud_detection_tpu.service.microbatch import MicroBatcher

    rm, spec, state0, t0 = build_ledger_model(seed=seed)
    wt = _watchtower(rm.profile, halflife=50_000.0)
    wt.drift.bind_ledger(spec, state0)
    boat = None
    if data_dir:
        lbdir = os.path.join(data_dir, host_id)
        boat = Lifeboat(
            lbdir, spec, drift=wt.drift, snapshot_s=1e9, fsync_s=0.0,
        )
        boat.recover()
        # seed generation: without this, a peer recovering OUR directory
        # would replay the journal onto a fresh table and lose the seeded
        # warmup state — the inherited segment must start where we did
        boat.take_snapshot()
    mb = MicroBatcher(
        scorer=rm.model.scorer, watchtower=wt, telemetry=False,
        max_batch=512, lifeboat=boat,
    )
    backend = LedgerBackend(
        rm.model.scorer, wt, spec, mb, boat=boat,
        baseline_counters=(
            float(np.float32(state0.collisions)),
            float(np.float32(state0.evictions)),
        ),
    )
    return backend, t0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description="longhaul serving host")
    p.add_argument("--host-id", required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--directory", default=None)
    p.add_argument("--n-hosts", type=int, default=None)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--data-dir", default=None)
    args = p.parse_args(argv)
    n_hosts = (
        args.n_hosts if args.n_hosts is not None else config.longhaul_hosts()
    )
    data_dir = (
        args.data_dir
        if args.data_dir is not None
        else config.longhaul_data_dir()
    )
    backend, _t0 = build_seeded_backend(
        args.seed, data_dir, args.host_id
    )
    srv = HostServer(
        args.host_id,
        backend,
        n_hosts=n_hosts,
        port=args.port,
        directory_addr=args.directory,
    )
    srv.start()
    print(f"LONGHAUL_READY {srv.addr}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
