"""Ledger state: the hashed per-entity accumulator table.

A fixed-size, power-of-two table of time-decayed aggregates keyed by a
multiply-shift hash of the request's ``entity_id`` (the card / account /
device the transaction belongs to). One slot holds:

- ``count`` — exponentially time-decayed event count,
- ``amount_sum`` / ``amount_sumsq`` — decayed sum and sum-of-squares of the
  (clipped) transaction amount, the z-score inputs (sumsq stays f32: the
  poison clamp bounds a single term at ``AMOUNT_CLIP²`` and decay bounds
  the series, so the accumulator cannot overflow f32 — see features.py),
- ``last_ts`` — the slot's decay anchor (0 = never seen),
- ``fingerprint`` — the 32-bit entity hash of the slot's latest writer,
  telemetry-only: colliding entities SHARE the slot's aggregates
  gracefully (the fingerprint mismatch only feeds the collision/eviction
  counters, it never forks state).

The table lives as a donated pytree threaded through every fused serving
flush, exactly like the drift window — one live copy, zero host round
trips on the hot path. Snapshots (``ledger_state.npz``) are stamped beside
``model.npz`` so a deploy/hot-swap resumes entity history where training's
replay left it, and carry the :class:`LedgerSpec` (hash geometry + the
null-entity feature vector) the serving tier rebinds with the model.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

LEDGER_FILE = "ledger_state.npz"

#: the K velocity features the ledger widens the feature vector with, in
#: column order (appended after the base schema; the model's feature_names
#: carries them so reason codes / drift panels name them properly)
LEDGER_FEATURE_NAMES = (
    "LedgerCount",      # decayed event count for the entity (pre-event)
    "LedgerAmountSum",  # decayed amount sum for the entity (pre-event)
    "LedgerTimeSince",  # log1p(seconds since the entity's last event)
    "LedgerAmountZ",    # this amount's z-score vs the entity's history
)
LEDGER_K = len(LEDGER_FEATURE_NAMES)

#: poison clamp on the amount feeding the accumulators: a NaN/Inf or
#: absurd amount (the poison_entity_state chaos campaign) folds in as a
#: bounded value instead of NaN-ing the slot — clip² also bounds a single
#: sumsq term at 1e12, keeping the f32 accumulator far from overflow
AMOUNT_CLIP = 1e6

#: z-score clamp — an extreme-but-finite amount yields a bounded feature
ZSCORE_CLIP = 8.0

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
#: Knuth's multiplicative constant — the multiply-shift slot hash
_MULT = 0x9E3779B1


def entity_fingerprint(entity_id) -> int:
    """Stable 32-bit fingerprint of an entity id (string or int): FNV-1a
    over the utf-8 repr, folded to 32 bits. 0 is reserved (= "no entity"),
    so a real entity hashing to 0 is nudged to 1."""
    h = _FNV_OFFSET
    for b in str(entity_id).encode("utf-8"):
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    fp = (h ^ (h >> 32)) & 0xFFFFFFFF
    return fp or 1


def entity_slot(fingerprint: int, log2_slots: int) -> int:
    """Multiply-shift: the top ``log2_slots`` bits of ``fp · 2654435761``
    (mod 2³²) — the classic universal-ish hash for power-of-two tables."""
    return ((fingerprint * _MULT) & 0xFFFFFFFF) >> (32 - log2_slots)


class LedgerState(NamedTuple):
    """The donated device pytree. Leading ``(slots, ...)`` axes per field;
    the mesh tier adds a shard axis in front exactly like the drift window.

    The three decayed accumulators live PACKED in one ``(S, 3)`` array
    (count, amount_sum, amount_sumsq): the batch fold is then two scatters
    over rank-2 updates instead of six rank-1 scatters — scatter dispatch
    overhead, not arithmetic, dominates the update on every backend. The
    ``count``/``amount_sum``/``amount_sumsq`` properties give named views.
    """

    acc: jax.Array          # (S, 3) f32 decayed [count, Σamount, Σamount²]
    last_ts: jax.Array      # (S,) f32 decay anchor; 0 = never seen
    fingerprint: jax.Array  # (S,) uint32 latest writer's entity hash
    collisions: jax.Array   # () f32 writes into a live slot owned by
    #                         another fingerprint (aggregates shared)
    evictions: jax.Array    # () f32 takeovers of a faded slot (the prior
    #                         entity's evidence had decayed below noise)

    @property
    def count(self):
        return self.acc[..., 0]

    @property
    def amount_sum(self):
        return self.acc[..., 1]

    @property
    def amount_sumsq(self):
        return self.acc[..., 2]


@dataclass(frozen=True)
class LedgerSpec:
    """Everything serving needs to widen the feature vector: stamped in
    ``ledger_state.npz`` beside the model so the hash geometry, decay
    horizon, and null-entity features can never drift from the weights
    that were trained against them."""

    n_base: int                 # features clients send (the wire schema)
    slots: int                  # table size, power of two
    halflife_s: float           # decay half-life of the aggregates
    amount_col: int             # index of the Amount column in the base row
    #: absolute offset subtracted from wall-clock event times before they
    #: enter the f32 table: raw unix epochs (~1.7e9) are beyond f32's
    #: integer resolution (~128 s there), so the table keeps an
    #: origin-relative clock. Stamped at train time so a request arriving
    #: right after a deploy continues the replay's clock seamlessly.
    ts_origin: float = 0.0
    null_features: np.ndarray = None  # (K,) raw-space features for entity-less
    #                             rows — the baseline-profile means, so a
    #                             legacy client's rows score at the training
    #                             distribution's center, not at "brand-new
    #                             entity" (see features.py null-slot note)

    def __post_init__(self):
        if self.slots & (self.slots - 1) or self.slots <= 0:
            raise ValueError(f"LEDGER_SLOTS must be a power of two, got {self.slots}")
        nf = np.asarray(
            self.null_features
            if self.null_features is not None
            else np.zeros(LEDGER_K, np.float32),
            np.float32,
        ).reshape(-1)
        if nf.shape[0] != LEDGER_K:
            raise ValueError(
                f"null_features must have {LEDGER_K} entries, got {nf.shape[0]}"
            )
        object.__setattr__(self, "null_features", nf)

    @property
    def log2_slots(self) -> int:
        return int(self.slots).bit_length() - 1

    @property
    def n_features(self) -> int:
        """The widened width the model scores."""
        return self.n_base + LEDGER_K

    @property
    def feature_names(self) -> tuple[str, ...]:
        return LEDGER_FEATURE_NAMES

    def row_keys(self, entity_id) -> tuple[int, int]:
        """(slot, fingerprint) for one request's entity — the host-side
        half of the hash, computed once at submit time."""
        fp = entity_fingerprint(entity_id)
        return entity_slot(fp, self.log2_slots), fp

    def rel_ts(self, epoch_ts: float) -> float:
        """Origin-relative event time for the f32 table (strictly > 0 —
        0 is the never-seen sentinel)."""
        return max(float(epoch_ts) - self.ts_origin, 1e-3)

    @classmethod
    def from_config(cls, n_base: int, null_features=None) -> "LedgerSpec":
        from fraud_detection_tpu import config

        return cls(
            n_base=n_base,
            slots=config.ledger_slots(),
            halflife_s=config.ledger_halflife_s(),
            amount_col=config.ledger_amount_col(),
            null_features=(
                np.zeros(LEDGER_K, np.float32)
                if null_features is None
                else np.asarray(null_features, np.float32)
            ),
        )


def init_state(slots: int) -> LedgerState:
    """A fresh (host, numpy) table — callers device-put it where it lives
    (single device, or sharded with a leading shard axis)."""
    return LedgerState(
        acc=np.zeros((slots, 3), np.float32),
        last_ts=np.zeros((slots,), np.float32),
        fingerprint=np.zeros((slots,), np.uint32),
        collisions=np.zeros((), np.float32),
        evictions=np.zeros((), np.float32),
    )


def device_state(state: LedgerState | None, slots: int) -> LedgerState:
    """Host snapshot (or None = fresh) → device-resident pytree."""
    st = state if state is not None else init_state(slots)
    return LedgerState(*(jnp.asarray(np.asarray(leaf)) for leaf in st))


def save_ledger(directory: str, spec: LedgerSpec, state: LedgerState) -> str:
    """Stamp ``ledger_state.npz`` (spec + table snapshot) beside the model
    artifacts — the thing ``ModelReloader`` rebinds on hot swap."""
    from fraud_detection_tpu.ckpt.atomic import atomic_savez

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, LEDGER_FILE)
    atomic_savez(
        path,
        n_base=np.int64(spec.n_base),
        slots=np.int64(spec.slots),
        halflife_s=np.float64(spec.halflife_s),
        amount_col=np.int64(spec.amount_col),
        ts_origin=np.float64(spec.ts_origin),
        null_features=np.asarray(spec.null_features, np.float32),
        acc=np.asarray(state.acc, np.float32),
        last_ts=np.asarray(state.last_ts, np.float32),
        fingerprint=np.asarray(state.fingerprint, np.uint32),
        collisions=np.asarray(state.collisions, np.float32),
        evictions=np.asarray(state.evictions, np.float32),
    )
    return path


def load_ledger(directory: str) -> tuple[LedgerSpec, LedgerState] | None:
    """Load the stamped spec + snapshot; None when the artifact carries no
    ledger (a stateless model keeps serving the 30-feature path)."""
    path = os.path.join(directory, LEDGER_FILE)
    if not os.path.exists(path):
        return None
    with np.load(path, allow_pickle=False) as z:
        spec = LedgerSpec(
            n_base=int(z["n_base"]),
            slots=int(z["slots"]),
            halflife_s=float(z["halflife_s"]),
            amount_col=int(z["amount_col"]),
            ts_origin=float(z["ts_origin"]) if "ts_origin" in z else 0.0,
            null_features=np.asarray(z["null_features"], np.float32),
        )
        state = LedgerState(
            acc=np.asarray(z["acc"], np.float32),
            last_ts=np.asarray(z["last_ts"], np.float32),
            fingerprint=np.asarray(z["fingerprint"], np.uint32),
            collisions=np.asarray(z["collisions"], np.float32),
            evictions=np.asarray(z["evictions"], np.float32),
        )
    return spec, state
