"""Training-side replay: materialize the widened features through the SAME
traced body serving uses.

``materialize_features`` sorts rows by timestamp (stable, so same-ts rows
keep their input order), replays them through
:func:`fraud_detection_tpu.ledger.features._ledger_read_update` in
fixed-size batches, and returns the ``(n, K)`` velocity features in the
ORIGINAL row order plus the final table state. Because the body is the
exact expression the fused serving flush traces, a model fitted on these
columns is structurally incapable of train/serve skew — the parity test
drives the serving flush and this replay over the same rows and asserts
the scores match exactly.

Base datasets (the Kaggle CSV) carry no entity ids, so
``synthesize_entities`` assigns deterministic pseudo-entities and
timestamps (the ``Time`` column when the schema has one, else row order):
the fit still sees a realistic distribution over the velocity columns
instead of a constant null vector, and the assignment is seed-stable so
two trainings of the same data produce bitwise-identical features.
"""

from __future__ import annotations

import numpy as np

from fraud_detection_tpu.ledger.state import (
    LedgerSpec,
    LedgerState,
    device_state,
    entity_fingerprint,
    entity_slot,
)

#: replay batch size — also the serving parity test's flush size. Features
#: of rows in ONE batch read the pre-batch state (see features.py), so the
#: batch partition is part of the replay contract; keep it stable.
REPLAY_BATCH = 256


def synthesize_entities(
    x: np.ndarray,
    feature_names,
    seed: int = 0,
    events_per_entity: int = 50,
) -> tuple[list[str], np.ndarray]:
    """Deterministic pseudo-entities + timestamps for an entity-less base
    dataset. Entities are assigned by a seeded shuffle of ``row → pool of
    n/events_per_entity ids`` (so each pseudo-card sees ~events_per_entity
    transactions spread across the timeline); timestamps come from the
    ``Time`` column when present (offset to be strictly positive), else
    one second per row."""
    n = x.shape[0]
    names = list(feature_names or [])
    rng = np.random.default_rng(seed)
    n_entities = max(n // max(events_per_entity, 1), 1)
    assignment = rng.integers(0, n_entities, size=n)
    entities = [f"sim-{int(e)}" for e in assignment]
    if "Time" in names:
        t = np.asarray(x[:, names.index("Time")], np.float64)
        ts = (t - t.min() + 1.0).astype(np.float32)
    else:
        ts = (np.arange(n, dtype=np.float32) + 1.0)
    return entities, ts


def row_keys(
    spec: LedgerSpec, entities, n: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized host half of the hash: (slots, fingerprints, has_entity)
    for a row list whose entries may be None (no entity)."""
    slots = np.zeros(n, np.int32)
    fps = np.zeros(n, np.uint32)
    has = np.zeros(n, np.float32)
    for i, e in enumerate(entities):
        if e is None:
            continue
        fp = entity_fingerprint(e)
        slots[i] = entity_slot(fp, spec.log2_slots)
        fps[i] = fp
        has[i] = 1.0
    return slots, fps, has


def materialize_features(
    spec: LedgerSpec,
    x: np.ndarray,
    entities,
    ts: np.ndarray,
    state: LedgerState | None = None,
    batch: int = REPLAY_BATCH,
) -> tuple[np.ndarray, LedgerState]:
    """Replay ``x`` (n, n_base) in timestamp order through the serving
    body; returns features aligned to the INPUT order + the final state."""
    import jax
    import jax.numpy as jnp

    from fraud_detection_tpu.ledger.features import _ledger_read_update

    x = np.asarray(x, np.float32)
    n = x.shape[0]
    ts = np.asarray(ts, np.float32).reshape(-1)
    if len(entities) != n or ts.shape[0] != n:
        raise ValueError("entities/ts must align with the rows")
    order = np.argsort(ts, kind="stable")
    slots, fps, has = row_keys(spec, [entities[i] for i in order], n)
    amounts = x[order][:, spec.amount_col].astype(np.float32)
    ts_o = ts[order]

    step = jax.jit(_ledger_read_update)
    dev = device_state(state, spec.slots)
    null = jnp.asarray(spec.null_features)
    hl = jnp.float32(spec.halflife_s)
    feats = np.zeros((n, spec.null_features.shape[0]), np.float32)
    for lo in range(0, n, batch):
        hi = min(lo + batch, n)
        pad = batch - (hi - lo)
        sl = np.pad(slots[lo:hi], (0, pad))
        fb = np.pad(fps[lo:hi], (0, pad))
        tb = np.pad(ts_o[lo:hi], (0, pad))
        ab = np.pad(amounts[lo:hi], (0, pad))
        hb = np.pad(has[lo:hi], (0, pad))
        fk, dev = step(
            dev,
            jnp.asarray(sl), jnp.asarray(fb), jnp.asarray(tb),
            jnp.asarray(ab), jnp.asarray(hb), null, hl,
        )
        feats[order[lo:hi]] = np.asarray(fk)[: hi - lo]
    host = LedgerState(*(np.asarray(leaf) for leaf in dev))
    return feats, host
