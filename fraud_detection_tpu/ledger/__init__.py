"""Ledger: the device-resident stateful feature engine.

The model scored 30 stateless PCA features; real fraud systems score
*velocity* — per-card transaction count/sum over sliding windows,
time-since-last-event, amount z-scores. The ledger is a fixed-size hashed
per-entity accumulator table living on device as a donated pytree exactly
like the drift window: the fused serving flush reads each row's aggregates,
derives K velocity features, writes the updated accumulators back, and
scores the widened ``[rows, base + K]`` feature block — all in the SAME
single donated dispatch the flush already pays (monitor/drift
``_fused_flush_ledger``; the shard_map twin in mesh/shardflush).

Train/serve skew is structurally impossible: training replays base +
feedback rows *through the same traced body* (:mod:`.replay`) in timestamp
order to materialize the widened training features, so the features the
model fits on are, by construction, the features serving computes.
"""

from fraud_detection_tpu.ledger.state import (  # noqa: F401
    LEDGER_FEATURE_NAMES,
    LEDGER_K,
    LedgerSpec,
    LedgerState,
    entity_fingerprint,
    entity_slot,
    init_state,
    load_ledger,
    save_ledger,
)
from fraud_detection_tpu.ledger.features import (  # noqa: F401
    _ledger_read_update,
    ledger_stats,
)
from fraud_detection_tpu.ledger.replay import (  # noqa: F401
    materialize_features,
    synthesize_entities,
)
from fraud_detection_tpu.ledger.placement import shard_placement  # noqa: F401
