"""Hash-mod-shard row placement for the sharded (shard_map) ledger flush.

Under ``MESH_FLUSH_DEVICES=N`` the fused flush splits the staged bucket's
rows positionally: rows ``[s·b/N, (s+1)·b/N)`` execute on device shard
``s``, and each shard folds its rows into ITS OWN ledger sub-table (leading
shard axis, donated through — exactly the drift-window discipline). For a
given entity's aggregates to live on exactly ONE shard, every row of that
entity must always land in the same shard's row range; the batcher
therefore *places* rows by ``slot mod N`` before staging — a host-side
permutation, never a device collective.

Entity-less rows carry no state and fill whichever segment has room.
Because a skewed entity mix can overfill one segment (9 of 16 rows hashing
to shard 0 of 2), the bucket is bumped to the next power of two that fits
``N × max_segment`` — the warm ladder for a mesh ledger extends by the
shard factor so the bump never compiles mid-traffic.
"""

from __future__ import annotations

import numpy as np


def shard_placement(
    slots: np.ndarray,       # (n,) int32 table slot per row
    has_entity: np.ndarray,  # (n,) truthy when the row carries an entity
    n_shards: int,
    min_bucket: int = 8,
) -> tuple[int, np.ndarray]:
    """Positions for ``n`` rows in a segment-aligned bucket.

    Returns ``(bucket, positions)`` where ``positions[i]`` is row ``i``'s
    staged index: entity rows sit inside segment ``slots[i] % n_shards``,
    entity-less rows pack into the emptiest segments. The bucket is the
    smallest power of two ≥ ``max(n, n_shards · max_segment, min_bucket)``
    divisible into equal segments."""
    from fraud_detection_tpu.ops.scorer import _bucket

    n = int(slots.shape[0])
    shard_of = np.where(
        np.asarray(has_entity, bool), np.asarray(slots) % n_shards, -1
    )
    counts = np.bincount(shard_of[shard_of >= 0], minlength=n_shards)
    # entity-less rows fill the emptiest segments (balance, no state)
    free = counts.copy()
    for i in np.flatnonzero(shard_of < 0):
        s = int(np.argmin(free))
        shard_of[i] = s
        free[s] += 1
    max_seg = int(free.max()) if n else 0
    bucket = _bucket(max(n, n_shards * max_seg, min_bucket), min_bucket)
    seg = bucket // n_shards
    positions = np.zeros(n, np.int64)
    cursor = np.zeros(n_shards, np.int64)
    for i in range(n):
        s = int(shard_of[i])
        positions[i] = s * seg + cursor[s]
        cursor[s] += 1
    return bucket, positions
