"""The ledger's traced read-update body — the ONE place velocity features
are computed.

``_ledger_read_update`` is un-jitted (like ``ops/scorer._raw_score_linear``
and ``ops/linear_shap._raw_linear_shap``): the fused serving flush
(monitor/drift ``_fused_flush_ledger``), the shard_map mesh body
(mesh/shardflush), AND the training replay (:mod:`.replay`) all trace this
exact expression, so train/serve skew is structurally impossible — there is
no second implementation to drift.

Semantics (deterministic by construction — every write is a scatter-add or
scatter-max, never a duplicate-index scatter-set):

- **reads** see the pre-batch state decayed to each row's own timestamp:
  ``decayed = acc · 2^(−Δt/halflife)``. First-seen entities (anchor 0) read
  empty aggregates; entity-less rows read the spec's ``null_features``.
- **writes** fold the whole batch against a per-slot anchor: the slot's new
  ``last_ts`` is the scatter-max of its rows' timestamps, the old
  accumulators decay to that anchor, and each row's contribution decays
  from its own timestamp to the anchor before the scatter-add. Rows of one
  flush therefore fold without *intra-batch* decay between them — windows
  are hours, flushes are milliseconds, so the deviation from strictly
  sequential processing is ``2^(−ms/hours)`` ≈ one ulp — and, crucially,
  the result is identical for any row order within the batch, which is
  what makes the replay bitwise-reproducible.
- **padding and entity-less rows** carry weight 0: they scatter-add exact
  zeros and scatter-max a 0 timestamp, leaving every slot *bitwise*
  unchanged — the property the all-padding warmup test pins.
- **poison guard**: the amount is ``nan_to_num``-ed and clipped to
  ``±AMOUNT_CLIP`` before it touches an accumulator, and the z-score
  output is clipped to ``±ZSCORE_CLIP`` — a NaN/Inf/absurd amount (the
  ``poison_entity_state`` chaos campaign) degrades one entity's features
  to a clamped value instead of NaN-ing the slot or the score.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fraud_detection_tpu.ledger.state import (
    AMOUNT_CLIP,
    ZSCORE_CLIP,
    LedgerState,
)


def _ledger_read_update(
    state: LedgerState,
    slot_idx: jax.Array,   # (n,) int32 table slot per row
    fp: jax.Array,         # (n,) uint32 entity fingerprint (0 = none)
    ts: jax.Array,         # (n,) f32 event timestamp, strictly > 0 for
    #                        real entity rows (host guarantees it)
    amount: jax.Array,     # (n,) f32 transaction amount (pre-clamp)
    has_entity: jax.Array,  # (n,) f32 1.0 when the row carries an entity
    null_features: jax.Array,  # (K,) features for entity-less rows
    halflife_s: jax.Array,  # () f32 decay half-life
) -> tuple[jax.Array, LedgerState]:
    """Read K velocity features per row and fold the batch back into the
    donated table. Returns ``(features (n, K), new_state)``."""
    inv_hl = 1.0 / jnp.maximum(halflife_s, 1e-6)
    w = has_entity.astype(jnp.float32)
    # clamp once, then promise in-bounds to every gather/scatter: XLA's
    # per-update bounds checks are pure overhead on the scatter loop, and
    # the clamp makes a corrupted index degrade to a shared slot instead
    # of undefined behavior
    slot_idx = jnp.clip(slot_idx, 0, state.acc.shape[0] - 1)
    _IB = "promise_in_bounds"
    # poison guard: non-finite → 0, then the symmetric clip
    a = jnp.clip(
        jnp.nan_to_num(amount, nan=0.0, posinf=AMOUNT_CLIP, neginf=-AMOUNT_CLIP),
        -AMOUNT_CLIP,
        AMOUNT_CLIP,
    )
    ts = jnp.maximum(jnp.nan_to_num(ts, nan=0.0, posinf=0.0, neginf=0.0), 0.0)

    # ---- read: pre-batch state decayed to each row's timestamp ----------
    prev_acc = state.acc[slot_idx]  # (n, 3) one gather for all three
    prev_cnt = prev_acc[:, 0]
    prev_sum = prev_acc[:, 1]
    prev_ssq = prev_acc[:, 2]
    prev_ts = state.last_ts[slot_idx]
    prev_fp = state.fingerprint[slot_idx]
    seen = (prev_ts > 0.0).astype(jnp.float32)
    dt = jnp.maximum(ts - prev_ts, 0.0)
    f_row = jnp.exp2(-dt * inv_hl) * seen
    dcnt = prev_cnt * f_row
    dsum = prev_sum * f_row
    dssq = prev_ssq * f_row

    mean = dsum / jnp.maximum(dcnt, 1.0)
    var = jnp.maximum(dssq / jnp.maximum(dcnt, 1.0) - mean * mean, 0.0)
    # +1 in the denominator: bounded z for near-degenerate histories (a
    # two-event entity with identical amounts must not explode the score)
    z = jnp.clip(
        (a - mean) / jnp.sqrt(var + 1.0), -ZSCORE_CLIP, ZSCORE_CLIP
    ) * (dcnt >= 2.0)
    # time-since-last: log1p keeps seconds-to-days on one scale; never-seen
    # entities read the horizon sentinel (8 half-lives ≈ "forever ago")
    tsl_null = jnp.log1p(8.0 * halflife_s)
    tsl = jnp.where(seen > 0.0, jnp.log1p(dt), tsl_null)
    feats = jnp.stack([dcnt, dsum, tsl, z], axis=1)
    feats = jnp.where(w[:, None] > 0.0, feats, null_features[None, :])

    # ---- write: deterministic scatter fold ------------------------------
    ts_eff = ts * w  # padding / entity-less rows push a 0 anchor (no-op)
    new_last = state.last_ts.at[slot_idx].max(ts_eff, mode=_IB)
    # Old accumulators decay from their previous anchor to the new one.
    # Done as a scatter-SET of pre-decayed values rather than a full-table
    # multiply: the decay factor is a per-SLOT quantity (both anchors are
    # slot state), so every row of a slot computes the bitwise-identical
    # update value and duplicate-index scatter order cannot matter — while
    # the transcendentals stay (n,)-sized instead of (slots,)-sized.
    # Untouched slots keep their bytes (nothing scatters there); a slot
    # touched only by weight-0 rows has anchor == previous anchor, so the
    # set re-writes its current value times exp2(-0) = 1 — bitwise
    # unchanged, which is what keeps the all-padding warmup invariant.
    anchor = new_last[slot_idx]
    f_anchor = jnp.exp2(-(anchor - prev_ts) * inv_hl)
    # each row's event decays from its own timestamp to the slot anchor
    g = jnp.exp2(-jnp.maximum(anchor - ts, 0.0) * inv_hl) * w
    ga = g * a
    new_acc = (
        state.acc.at[slot_idx].set(prev_acc * f_anchor[:, None], mode=_IB)
        .at[slot_idx].add(jnp.stack([g, ga, ga * a], axis=1), mode=_IB)
    )
    # fingerprint: best-effort "latest writer" telemetry — scatter-max is
    # the deterministic choice for duplicate slots; collision accounting
    # below compares against the PRE-batch owner either way
    fp_eff = jnp.where(w > 0.0, fp, jnp.uint32(0))
    new_fp = state.fingerprint.at[slot_idx].max(fp_eff, mode=_IB)
    mismatch = w * (prev_fp != fp).astype(jnp.float32) * (prev_fp != 0)
    live = (dcnt > 0.5).astype(jnp.float32)
    new_coll = state.collisions + jnp.sum(mismatch * live)
    new_evic = state.evictions + jnp.sum(mismatch * (1.0 - live))
    return feats, LedgerState(
        acc=new_acc,
        last_ts=new_last,
        fingerprint=new_fp,
        collisions=new_coll,
        evictions=new_evic,
    )


@jax.jit
def _ledger_stats(state: LedgerState, halflife_s: jax.Array):
    """Scrape-time occupancy reduce: the fraction of slots whose evidence,
    decayed to the table's own clock (the most recent anchor — slots only
    decay lazily on writes, so the stored counts are stale by construction),
    is still above noise. This is the LedgerSaturated alert input: without
    the decay, occupancy would be a monotonically-growing ever-claimed
    fraction and the alert would page on long-dead entities. Also returns
    the raw claimed fraction and the collision/eviction totals."""
    claimed = (state.last_ts > 0.0).astype(jnp.float32)
    now = jnp.max(state.last_ts)
    inv_hl = 1.0 / jnp.maximum(halflife_s, 1e-6)
    decayed = state.count * jnp.exp2(-(now - state.last_ts) * inv_hl)
    active = claimed * (decayed >= 0.5).astype(jnp.float32)
    n = state.last_ts.shape[0]
    return (
        jnp.sum(active) / n,
        jnp.sum(claimed) / n,
        state.collisions,
        state.evictions,
    )


def ledger_stats(state: LedgerState, halflife_s: float | None = None) -> dict:
    """Host dict of the scrape-time ledger telemetry. ``halflife_s`` is the
    spec's decay horizon; None (tests/offline inspection) reports the
    undecayed view (occupancy = slots with count ≥ 0.5 at last write)."""
    occ, claimed, coll, evic = _ledger_stats(
        state, jnp.float32(halflife_s if halflife_s else float("inf"))
    )
    return {
        "slot_occupancy": float(occ),
        "slots_claimed_frac": float(claimed),
        "hash_collisions": float(coll),
        "evictions": float(evic),
    }
