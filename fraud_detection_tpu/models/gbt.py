"""Gradient-boosted-trees fraud model.

The TPU-native stand-in for the reference's ``XGBClassifier`` artifact
(train_model.py:95-113): a fitted :class:`~fraud_detection_tpu.ops.gbt.
GBTModel` forest + the frozen feature order, sharing the family-agnostic
estimator surface (:class:`~fraud_detection_tpu.models.base.FraudModelBase`)
so the serving app, worker, and offline tools treat both families alike.

The scaler is folded into the bin edges at construction
(:func:`~fraud_detection_tpu.ops.gbt.fold_scaler_into_gbt`), so like the
linear model this one scores *raw* inputs with zero preprocessing launches.
"""

from __future__ import annotations

import numpy as np

from fraud_detection_tpu.ckpt.checkpoint import (
    load_gbt_artifacts,
    save_gbt_artifacts,
)
from fraud_detection_tpu.models.base import FraudModelBase
from fraud_detection_tpu.ops.gbt import GBTModel, fold_scaler_into_gbt
from fraud_detection_tpu.ops.scorer import GBTBatchScorer


class FraudGBTModel(FraudModelBase):
    def __init__(
        self,
        model: GBTModel,
        feature_names: list[str],
        scaler=None,
        background: np.ndarray | None = None,
    ):
        if scaler is not None:
            model = fold_scaler_into_gbt(model, scaler)
        self.model = model
        self.feature_names = list(feature_names)
        self.background = background  # raw-space sample for TreeSHAP
        self._scorer = GBTBatchScorer(model)
        self._raw_explainer = None

    # -- explainability ----------------------------------------------------
    def raw_explainer(self):
        """Exact interventional TreeSHAP over the forest (ops/tree_shap),
        taking raw inputs — same role as the linear model's closed-form SHAP
        explainer. Background: the stored training sample, or a single
        all-zeros row when absent (the legacy reference worker's zero
        background, api/worker.py:52-53). Built once and cached."""
        if self._raw_explainer is None:
            from fraud_detection_tpu.ops.tree_shap import build_tree_explainer

            bg = self.background
            if bg is None:
                bg = np.zeros((1, len(self.feature_names)), np.float32)
            self._raw_explainer = build_tree_explainer(self.model, bg)
        return self._raw_explainer

    def explain_batch(self, x: np.ndarray) -> tuple[np.ndarray, float]:
        from fraud_detection_tpu.ops.tree_shap import tree_shap

        explainer = self.raw_explainer()
        phi = np.asarray(tree_shap(explainer, np.asarray(x, np.float32)))
        return phi, float(explainer.expected_value)

    # -- persistence -------------------------------------------------------
    def save(self, directory: str) -> str:
        return save_gbt_artifacts(
            directory, self.model, self.feature_names, self.background
        )

    @classmethod
    def load(cls, directory: str) -> "FraudGBTModel":
        model, feature_names, background = load_gbt_artifacts(directory)
        return cls(model, feature_names, background=background)
