"""Gradient-boosted-trees fraud model.

The TPU-native stand-in for the reference's ``XGBClassifier`` artifact
(train_model.py:95-113): a fitted :class:`~fraud_detection_tpu.ops.gbt.
GBTModel` forest + the frozen feature order, sharing the family-agnostic
estimator surface (:class:`~fraud_detection_tpu.models.base.FraudModelBase`)
so the serving app, worker, and offline tools treat both families alike.

The scaler is folded into the bin edges at construction
(:func:`~fraud_detection_tpu.ops.gbt.fold_scaler_into_gbt`), so like the
linear model this one scores *raw* inputs with zero preprocessing launches.
Because the fold consumes the scaler, the int8 wire calibration
(``quant_calibration.npz``) is derived HERE — while the scaler still exists
— and stamped beside the forest by :meth:`save`, exactly like the linear
family: a later ``SCORER_WIRE=int8`` deploy (or a hot swap into one) must
quantize against the training profile this forest was fitted on (evergreen:
full fused wire/explain parity for the GBT family).
"""

from __future__ import annotations

import logging

import numpy as np

from fraud_detection_tpu.ckpt.checkpoint import (
    load_gbt_artifacts,
    save_gbt_artifacts,
)
from fraud_detection_tpu.models.base import FraudModelBase
from fraud_detection_tpu.ops.gbt import GBTModel, fold_scaler_into_gbt
from fraud_detection_tpu.ops.quant import (
    QuantCalibration,
    derive_calibration,
    load_calibration,
    save_calibration,
)
from fraud_detection_tpu.ops.scorer import GBTBatchScorer

log = logging.getLogger("fraud_detection_tpu.models")


class FraudGBTModel(FraudModelBase):
    #: serve-time vs backfill attribution tolerance for the worker's
    #: consistency check: TreeSHAP attributions live in margin space and a
    #: quantized wire can flip a bin boundary — φ then moves by a leaf-value
    #: delta, not an elementwise rounding error — so the GBT bar is wider
    #: than the linear family's 5e-2 (on the f32 wire the two paths share
    #: one traced body and agree bitwise; this bar only absorbs the int8
    #: lattice).
    explain_consistency_atol = 0.25

    def __init__(
        self,
        model: GBTModel,
        feature_names: list[str],
        scaler=None,
        background: np.ndarray | None = None,
        calibration: QuantCalibration | None = None,
        io_dtype: str | None = None,
    ):
        if scaler is not None:
            # derive the int8 calibration BEFORE the fold consumes the
            # scaler (serve-time loads get it from the stamped sidecar)
            if calibration is None:
                calibration = derive_calibration(scaler)
            model = fold_scaler_into_gbt(model, scaler)
        self.model = model
        self.feature_names = list(feature_names)
        self.background = background  # raw-space sample for TreeSHAP
        self.calibration = calibration
        # quickwire/evergreen: the serving wire comes from SCORER_WIRE
        # unless pinned. int8 needs the stamped calibration — without one,
        # fall back to f32 loudly rather than refuse to serve (the linear
        # family's contract).
        if io_dtype is None:
            from fraud_detection_tpu import config

            io_dtype = config.scorer_wire()
        if io_dtype == "int8" and calibration is None:
            log.warning(
                "SCORER_WIRE=int8 but the GBT model carries no stamped "
                "quant_calibration.npz (and its scaler is folded into the "
                "bin edges) — serving on the float32 wire instead"
            )
            io_dtype = "float32"
        self._scorer = GBTBatchScorer(
            model,
            io_dtype=io_dtype,
            calibration=calibration if io_dtype == "int8" else None,
            # lazy: the fused explain leg resolves the cached TreeSHAP
            # explainer on first fused_spec() (warmup), never at load
            explainer=self.raw_explainer,
        )
        self._raw_explainer = None

    # -- explainability ----------------------------------------------------
    def raw_explainer(self):
        """Exact interventional TreeSHAP over the forest (ops/tree_shap),
        taking raw inputs — same role as the linear model's closed-form SHAP
        explainer. Background: the stored training sample, or a single
        all-zeros row when absent (the legacy reference worker's zero
        background, api/worker.py:52-53). Built once and cached; the SAME
        explainer pytree rides ``FusedSpec.explain_args`` into the fused
        serve-time reason codes, so the worker backfill and the fused leg
        share one background table by construction. The background
        subsample seed threads from ``config.explain_background_seed()``
        so the build replays deterministically."""
        if self._raw_explainer is None:
            from fraud_detection_tpu import config
            from fraud_detection_tpu.ops.tree_shap import build_tree_explainer

            bg = self.background
            if bg is None:
                bg = np.zeros((1, len(self.feature_names)), np.float32)
            self._raw_explainer = build_tree_explainer(
                self.model, bg, seed=config.explain_background_seed()
            )
        return self._raw_explainer

    def explain_batch(self, x: np.ndarray) -> tuple[np.ndarray, float]:
        from fraud_detection_tpu.ops.tree_shap import tree_shap

        explainer = self.raw_explainer()
        phi = np.asarray(tree_shap(explainer, np.asarray(x, np.float32)))
        return phi, float(explainer.expected_value)

    # -- persistence -------------------------------------------------------
    def save(self, directory: str) -> str:
        out = save_gbt_artifacts(
            directory, self.model, self.feature_names, self.background
        )
        if self.calibration is not None:
            # evergreen: the int8 wire calibration ships beside the forest
            # regardless of the CURRENT serving wire (train.py contract —
            # the linear family stamps the same sidecar)
            save_calibration(directory, self.calibration)
        return out

    @classmethod
    def load(cls, directory: str) -> "FraudGBTModel":
        model, feature_names, background = load_gbt_artifacts(directory)
        return cls(
            model,
            feature_names,
            background=background,
            calibration=load_calibration(directory),
        )
