"""High-level model classes tying together params, scaler, and metadata."""

from fraud_detection_tpu.models.logistic import FraudLogisticModel  # noqa: F401
