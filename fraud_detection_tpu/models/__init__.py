"""High-level model classes tying together params, scaler, and metadata."""

from fraud_detection_tpu.models.gbt import FraudGBTModel  # noqa: F401
from fraud_detection_tpu.models.logistic import FraudLogisticModel  # noqa: F401


def load_any_model(directory: str):
    """Load whichever model family the artifact directory holds (the serving
    path is family-agnostic — SURVEY.md §2.3.1's model drift, resolved)."""
    from fraud_detection_tpu.ckpt.checkpoint import artifact_kind

    kind = artifact_kind(directory)
    if kind == "gbt":
        return FraudGBTModel.load(directory)
    return FraudLogisticModel.load(directory)
