"""Shared estimator surface for all fraud model families.

One input-validation/scoring/explanation contract (the reference's client
surface: ``predict``/``predict_proba`` — predict_single.py:28-32,
api/app.py:209-240 — plus the explanation path), so the serving app, XAI
worker, and offline tools are model-family agnostic. Subclasses provide a
``_scorer`` (the :class:`~fraud_detection_tpu.ops.scorer._BucketedScorer`
protocol) and the family's SHAP implementation.
"""

from __future__ import annotations

import numpy as np


class FraudModelBase:
    feature_names: list[str]
    _scorer = None  # set by subclass __init__

    # -- scoring (raw, unscaled inputs) ------------------------------------
    @property
    def scorer(self):
        return self._scorer

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """(n, 2) array [P(0), P(1)] like sklearn."""
        p1 = self._scorer.predict_proba(x)
        return np.stack([1.0 - p1, p1], axis=1)

    def predict(self, x: np.ndarray, threshold: float = 0.5) -> np.ndarray:
        return self._scorer.predict(x, threshold)

    def score_one(self, features: dict | list) -> tuple[int, float]:
        """Validate + order one row by feature name, return (label, P(1))."""
        row = self.prepare_row(features)
        p = float(self._scorer.predict_proba(row[None, :])[0])
        return int(p >= 0.5), p

    def prepare_row(self, features: dict | list) -> np.ndarray:
        """Reorder dict input to training feature order; validate arity
        (reference predict_single.py:22, api/app.py:185-192)."""
        if isinstance(features, dict):
            missing = [n for n in self.feature_names if n not in features]
            if missing:
                raise ValueError(f"missing features: {missing[:5]}")
            vals = [float(features[n]) for n in self.feature_names]
        else:
            vals = [float(v) for v in features]
            if len(vals) != len(self.feature_names):
                raise ValueError(
                    f"expected {len(self.feature_names)} features, got {len(vals)}"
                )
        return np.asarray(vals, dtype=np.float32)

    # -- explainability (family-specific) ----------------------------------
    def raw_explainer(self):
        """The family's explainer over *raw* inputs, built once and cached."""
        raise NotImplementedError

    def explain_one(self, row: np.ndarray) -> tuple[np.ndarray, float]:
        """((d,) φ, expected_value) in margin space — the XAI worker's surface."""
        phi, ev = self.explain_batch(np.asarray(row, np.float32)[None, :])
        return phi[0], ev

    def explain_batch(self, x: np.ndarray) -> tuple[np.ndarray, float]:
        """((n, d) φ, expected_value) in margin space — the offline tools'
        surface (explain.py summary/dependence plots)."""
        raise NotImplementedError
