"""Flagship model: scaled logistic regression for fraud scoring.

Bundles fitted :class:`LogisticParams` + :class:`ScalerParams` + the frozen
feature order into one object with the estimator surface the reference's
clients expect (``predict`` / ``predict_proba`` — predict_single.py:28-32,
api/app.py:209-240), backed by the scaler-folded jitted scorer.
"""

from __future__ import annotations

import numpy as np

import logging

from fraud_detection_tpu.ckpt.checkpoint import (
    export_joblib_artifacts,
    import_joblib_artifacts,
    load_artifacts,
    save_artifacts,
)
from fraud_detection_tpu.models.base import FraudModelBase
from fraud_detection_tpu.ops.linear_shap import (
    LinearShapExplainer,
    linear_shap,
    make_explainer,
)
from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.quant import (
    QuantCalibration,
    derive_calibration,
    load_calibration,
    save_calibration,
)
from fraud_detection_tpu.ops.scaler import ScalerParams
from fraud_detection_tpu.ops.scorer import BatchScorer

log = logging.getLogger("fraud_detection_tpu.models")


class FraudLogisticModel(FraudModelBase):
    def __init__(
        self,
        params: LogisticParams,
        scaler: ScalerParams | None,
        feature_names: list[str],
        calibration: QuantCalibration | None = None,
        io_dtype: str | None = None,
    ):
        self.params = params
        self.scaler = scaler
        self.feature_names = list(feature_names)
        # quickwire: the serving wire format comes from SCORER_WIRE unless
        # the caller pins one. int8 needs calibration — the artifact-stamped
        # one when present (load() passes it through, so a hot-swapped
        # challenger serves with ITS calibration), else derived from the
        # scaler. Without either, fall back to f32 loudly rather than
        # refuse to serve.
        if io_dtype is None:
            from fraud_detection_tpu import config

            io_dtype = config.scorer_wire()
        if io_dtype == "int8" and scaler is None and calibration is None:
            log.warning(
                "SCORER_WIRE=int8 but the model carries no scaler stats and "
                "no stamped quant_calibration.npz — serving on the float32 "
                "wire instead"
            )
            io_dtype = "float32"
        self.calibration = calibration
        self._scorer = BatchScorer(
            params, scaler, io_dtype=io_dtype, calibration=calibration
        )
        self._raw_explainer = None

    # -- explainability ----------------------------------------------------
    def explainer(self, background_mean=None) -> LinearShapExplainer:
        """SHAP explainer in *scaled* space with the training-set background
        (scaled background mean is 0 by construction when fitted with this
        model's scaler — make_explainer's default)."""
        return make_explainer(
            self.params.coef, self.params.intercept, background_mean=background_mean
        )

    def raw_explainer(self) -> LinearShapExplainer:
        """SHAP explainer taking *raw* inputs: scaler folded into the coef,
        background mean = scaler mean (equivalent attributions). Built once
        and cached — the worker explains per task with no rebuild."""
        if self._raw_explainer is None:
            from fraud_detection_tpu.ops.scorer import fold_scaler_into_linear

            folded = fold_scaler_into_linear(self.params, self.scaler)
            mu = (
                np.asarray(self.scaler.mean)
                if self.scaler is not None
                else np.zeros_like(np.asarray(folded.coef))
            )
            self._raw_explainer = make_explainer(
                folded.coef, folded.intercept, background_mean=mu
            )
        return self._raw_explainer

    def explain_batch(self, x: np.ndarray) -> tuple[np.ndarray, float]:
        explainer = self.raw_explainer()
        phi = np.asarray(linear_shap(explainer, np.asarray(x, np.float32)))
        return phi, float(explainer.expected_value)

    # -- persistence -------------------------------------------------------
    def save(self, directory: str, joblib_too: bool = True) -> str:
        save_artifacts(directory, self.params, self.scaler, self.feature_names)
        # stamp the int8 wire calibration beside the weights regardless of
        # the CURRENT serving wire: a later SCORER_WIRE=int8 deploy (or a
        # hot swap into one) must quantize against the training profile
        # this model was fitted on, not whatever scaler a future process
        # happens to re-derive
        cal = self.calibration
        if cal is None and self.scaler is not None:
            cal = derive_calibration(self.scaler)
        if cal is not None:
            save_calibration(directory, cal)
        if joblib_too:
            try:
                export_joblib_artifacts(
                    directory, self.params, self.scaler, self.feature_names
                )
            except RuntimeError:
                pass  # sklearn/joblib not installed — native format only
        return directory

    @classmethod
    def load(cls, directory: str) -> "FraudLogisticModel":
        params, scaler, feature_names = load_artifacts(directory)
        return cls(
            params, scaler, feature_names,
            calibration=load_calibration(directory),
        )

    @classmethod
    def load_joblib(
        cls, model_path: str, scaler_path: str | None, feature_names_path: str | None
    ) -> "FraudLogisticModel":
        params, scaler, names = import_joblib_artifacts(
            model_path, scaler_path, feature_names_path
        )
        if names is None:
            names = [f"f{i}" for i in range(params.coef.shape[0])]
        return cls(params, scaler, names)
