"""Flagship model: scaled logistic regression for fraud scoring.

Bundles fitted :class:`LogisticParams` + :class:`ScalerParams` + the frozen
feature order into one object with the estimator surface the reference's
clients expect (``predict`` / ``predict_proba`` — predict_single.py:28-32,
api/app.py:209-240), backed by the scaler-folded jitted scorer.
"""

from __future__ import annotations

import numpy as np

from fraud_detection_tpu.ckpt.checkpoint import (
    export_joblib_artifacts,
    import_joblib_artifacts,
    load_artifacts,
    save_artifacts,
)
from fraud_detection_tpu.models.base import FraudModelBase
from fraud_detection_tpu.ops.linear_shap import (
    LinearShapExplainer,
    linear_shap,
    make_explainer,
)
from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.scaler import ScalerParams
from fraud_detection_tpu.ops.scorer import BatchScorer


class FraudLogisticModel(FraudModelBase):
    def __init__(
        self,
        params: LogisticParams,
        scaler: ScalerParams | None,
        feature_names: list[str],
    ):
        self.params = params
        self.scaler = scaler
        self.feature_names = list(feature_names)
        self._scorer = BatchScorer(params, scaler)
        self._raw_explainer = None

    # -- explainability ----------------------------------------------------
    def explainer(self, background_mean=None) -> LinearShapExplainer:
        """SHAP explainer in *scaled* space with the training-set background
        (scaled background mean is 0 by construction when fitted with this
        model's scaler — make_explainer's default)."""
        return make_explainer(
            self.params.coef, self.params.intercept, background_mean=background_mean
        )

    def raw_explainer(self) -> LinearShapExplainer:
        """SHAP explainer taking *raw* inputs: scaler folded into the coef,
        background mean = scaler mean (equivalent attributions). Built once
        and cached — the worker explains per task with no rebuild."""
        if self._raw_explainer is None:
            from fraud_detection_tpu.ops.scorer import fold_scaler_into_linear

            folded = fold_scaler_into_linear(self.params, self.scaler)
            mu = (
                np.asarray(self.scaler.mean)
                if self.scaler is not None
                else np.zeros_like(np.asarray(folded.coef))
            )
            self._raw_explainer = make_explainer(
                folded.coef, folded.intercept, background_mean=mu
            )
        return self._raw_explainer

    def explain_batch(self, x: np.ndarray) -> tuple[np.ndarray, float]:
        explainer = self.raw_explainer()
        phi = np.asarray(linear_shap(explainer, np.asarray(x, np.float32)))
        return phi, float(explainer.expected_value)

    # -- persistence -------------------------------------------------------
    def save(self, directory: str, joblib_too: bool = True) -> str:
        save_artifacts(directory, self.params, self.scaler, self.feature_names)
        if joblib_too:
            try:
                export_joblib_artifacts(
                    directory, self.params, self.scaler, self.feature_names
                )
            except RuntimeError:
                pass  # sklearn/joblib not installed — native format only
        return directory

    @classmethod
    def load(cls, directory: str) -> "FraudLogisticModel":
        params, scaler, feature_names = load_artifacts(directory)
        return cls(params, scaler, feature_names)

    @classmethod
    def load_joblib(
        cls, model_path: str, scaler_path: str | None, feature_names_path: str | None
    ) -> "FraudLogisticModel":
        params, scaler, names = import_joblib_artifacts(
            model_path, scaler_path, feature_names_path
        )
        if names is None:
            names = [f"f{i}" for i in range(params.coef.shape[0])]
        return cls(params, scaler, names)
