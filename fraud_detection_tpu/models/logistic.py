"""Flagship model: scaled logistic regression for fraud scoring.

Bundles fitted :class:`LogisticParams` + :class:`ScalerParams` + the frozen
feature order into one object with the estimator surface the reference's
clients expect (``predict`` / ``predict_proba`` — predict_single.py:28-32,
api/app.py:209-240), backed by the scaler-folded jitted scorer.
"""

from __future__ import annotations

import numpy as np

import logging

from fraud_detection_tpu.ckpt.checkpoint import (
    export_joblib_artifacts,
    import_joblib_artifacts,
    load_artifacts,
    save_artifacts,
)
from fraud_detection_tpu.models.base import FraudModelBase
from fraud_detection_tpu.ops.linear_shap import (
    LinearShapExplainer,
    linear_shap,
    make_explainer,
)
from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.quant import (
    QuantCalibration,
    derive_calibration,
    load_calibration,
    save_calibration,
)
from fraud_detection_tpu.ops.scaler import ScalerParams
from fraud_detection_tpu.ops.scorer import BatchScorer

log = logging.getLogger("fraud_detection_tpu.models")


class FraudLogisticModel(FraudModelBase):
    def __init__(
        self,
        params: LogisticParams,
        scaler: ScalerParams | None,
        feature_names: list[str],
        calibration: QuantCalibration | None = None,
        io_dtype: str | None = None,
        ledger_spec=None,
        ledger_state=None,
        wide_spec=None,
        wide_table=None,
    ):
        self.params = params
        self.scaler = scaler
        self.feature_names = list(feature_names)
        # ledger (stateful feature engine): a widened family's
        # feature_names span base + K velocity columns; clients send the
        # BASE schema and the fused flush computes the rest on device. The
        # stamped table snapshot rides the model so a deploy/hot swap
        # resumes entity history where training's replay left it.
        self.ledger_spec = ledger_spec
        self.ledger_state = ledger_state
        # broadside (the wide family): feature_names span base + n_cross
        # hashed-cross contribution columns; the stamped wide_params.npz
        # sidecar carries the learned cross-weight table the fused flush
        # gathers (column-sharded over a 2-D mesh's model axis). Clients
        # still send the BASE schema, exactly the ledger contract.
        self.wide_spec = wide_spec
        self.wide_table = wide_table
        if wide_spec is not None and ledger_spec is not None:
            raise ValueError(
                "a model cannot be both ledger- and wide-widened"
            )
        if wide_spec is not None and len(self.feature_names) != (
            wide_spec.n_features
        ):
            raise ValueError(
                f"wide model carries {len(self.feature_names)} names but "
                f"the cross spec says {wide_spec.n_features}"
            )
        if ledger_spec is not None and len(self.feature_names) != (
            ledger_spec.n_features
        ):
            raise ValueError(
                f"widened model carries {len(self.feature_names)} names but "
                f"the ledger spec says {ledger_spec.n_features}"
            )
        # quickwire: the serving wire format comes from SCORER_WIRE unless
        # the caller pins one. int8 needs calibration — the artifact-stamped
        # one when present (load() passes it through, so a hot-swapped
        # challenger serves with ITS calibration), else derived from the
        # scaler. Without either, fall back to f32 loudly rather than
        # refuse to serve.
        if io_dtype is None:
            from fraud_detection_tpu import config

            io_dtype = config.scorer_wire()
        if io_dtype == "int8" and scaler is None and calibration is None:
            log.warning(
                "SCORER_WIRE=int8 but the model carries no scaler stats and "
                "no stamped quant_calibration.npz — serving on the float32 "
                "wire instead"
            )
            io_dtype = "float32"
        self.calibration = calibration
        if wide_spec is not None:
            from fraud_detection_tpu.ops.scorer import WideBatchScorer

            self._scorer = WideBatchScorer(
                params, scaler, wide_spec, wide_table,
                io_dtype=io_dtype, calibration=calibration,
            )
        else:
            self._scorer = BatchScorer(
                params, scaler, io_dtype=io_dtype, calibration=calibration,
                ledger_spec=ledger_spec,
            )
        self._raw_explainer = None

    @property
    def _widened_spec(self):
        """Whichever widening sidecar (ledger or wide) this family carries
        — both expose ``n_base``/``n_features`` over the same contract."""
        return self.ledger_spec if self.ledger_spec is not None else self.wide_spec

    @property
    def base_feature_names(self) -> list[str]:
        """The wire schema clients send (= feature_names for a stateless
        family; the base prefix for a ledger-/wide-widened one)."""
        spec = self._widened_spec
        if spec is None:
            return self.feature_names
        return self.feature_names[: spec.n_base]

    def prepare_row(self, features) -> "np.ndarray":
        """Clients of a widened model still send the BASE schema — the
        widened columns (ledger velocity features / wide hashed-cross
        contributions) are device-computed, never client-supplied."""
        if self._widened_spec is None:
            return super().prepare_row(features)
        names = self.base_feature_names
        if isinstance(features, dict):
            missing = [n for n in names if n not in features]
            if missing:
                raise ValueError(f"missing features: {missing[:5]}")
            vals = [float(features[n]) for n in names]
        else:
            vals = [float(v) for v in features]
            if len(vals) != len(names):
                raise ValueError(
                    f"expected {len(names)} features, got {len(vals)}"
                )
        return np.asarray(vals, dtype=np.float32)

    # -- explainability ----------------------------------------------------
    def explainer(self, background_mean=None) -> LinearShapExplainer:
        """SHAP explainer in *scaled* space with the training-set background
        (scaled background mean is 0 by construction when fitted with this
        model's scaler — make_explainer's default)."""
        return make_explainer(
            self.params.coef, self.params.intercept, background_mean=background_mean
        )

    def raw_explainer(self) -> LinearShapExplainer:
        """SHAP explainer taking *raw* inputs: scaler folded into the coef,
        background mean = scaler mean (equivalent attributions). Built once
        and cached — the worker explains per task with no rebuild."""
        if self._raw_explainer is None:
            from fraud_detection_tpu.ops.scorer import fold_scaler_into_linear

            folded = fold_scaler_into_linear(self.params, self.scaler)
            mu = (
                np.asarray(self.scaler.mean)
                if self.scaler is not None
                else np.zeros_like(np.asarray(folded.coef))
            )
            self._raw_explainer = make_explainer(
                folded.coef, folded.intercept, background_mean=mu
            )
        return self._raw_explainer

    def explain_batch(self, x: np.ndarray) -> tuple[np.ndarray, float]:
        x = np.asarray(x, np.float32)
        if (
            self.wide_spec is not None
            and x.shape[1] == self.wide_spec.n_base
        ):
            # base-width input to the wide family (the async worker's
            # backfill: the entity fingerprint never reaches the worker) —
            # explain through the null path: a zero cross block, exactly
            # what an entity-less request scores with. The worker's
            # consistency check skips cross indices for this reason.
            x = np.concatenate(
                [x, np.zeros((x.shape[0], self.wide_spec.n_cross), np.float32)],
                axis=1,
            )
        if (
            self.ledger_spec is not None
            and x.shape[1] == self.ledger_spec.n_base
        ):
            # base-width input to a widened family (the async worker's
            # backfill: the entity table lives in the serving process, not
            # here) — explain through the null slot. The velocity columns'
            # φ is then w′·(null − μ): the worker's consistency check skips
            # ledger indices for exactly this reason.
            x = np.concatenate(
                [
                    x,
                    np.broadcast_to(
                        self.ledger_spec.null_features,
                        (x.shape[0], self.ledger_spec.null_features.shape[0]),
                    ),
                ],
                axis=1,
            )
        explainer = self.raw_explainer()
        phi = np.asarray(linear_shap(explainer, x))
        return phi, float(explainer.expected_value)

    # -- persistence -------------------------------------------------------
    def save(self, directory: str, joblib_too: bool = True) -> str:
        save_artifacts(directory, self.params, self.scaler, self.feature_names)
        # stamp the int8 wire calibration beside the weights regardless of
        # the CURRENT serving wire: a later SCORER_WIRE=int8 deploy (or a
        # hot swap into one) must quantize against the training profile
        # this model was fitted on, not whatever scaler a future process
        # happens to re-derive
        cal = self.calibration
        if cal is None and self.scaler is not None:
            cal = derive_calibration(self.scaler)
        if cal is not None:
            save_calibration(directory, cal)
        if self.ledger_spec is not None:
            # stamp the entity-table snapshot + hash geometry beside the
            # weights: the widened coef is meaningless without the spec
            # (and the serving reloader rebinds BOTH on hot swap)
            from fraud_detection_tpu.ledger.state import init_state, save_ledger

            state = self.ledger_state
            if state is None:
                state = init_state(self.ledger_spec.slots)
            save_ledger(directory, self.ledger_spec, state)
        if self.wide_spec is not None:
            # stamp the learned cross-weight table + hash geometry beside
            # the weights — the widened coef is meaningless without it
            from fraud_detection_tpu.ops.crosses import save_wide

            save_wide(directory, self.wide_spec, self.wide_table)
        if joblib_too:
            try:
                export_joblib_artifacts(
                    directory, self.params, self.scaler, self.feature_names
                )
            except RuntimeError:
                pass  # sklearn/joblib not installed — native format only
        return directory

    @classmethod
    def load(cls, directory: str) -> "FraudLogisticModel":
        params, scaler, feature_names = load_artifacts(directory)
        from fraud_detection_tpu.ledger.state import load_ledger
        from fraud_detection_tpu.ops.crosses import load_wide

        ledger = load_ledger(directory)
        spec, state = ledger if ledger is not None else (None, None)
        wide = load_wide(directory)
        wide_spec, wide_table = wide if wide is not None else (None, None)
        return cls(
            params, scaler, feature_names,
            calibration=load_calibration(directory),
            ledger_spec=spec, ledger_state=state,
            wide_spec=wide_spec, wide_table=wide_table,
        )

    @classmethod
    def load_joblib(
        cls, model_path: str, scaler_path: str | None, feature_names_path: str | None
    ) -> "FraudLogisticModel":
        params, scaler, names = import_joblib_artifacts(
            model_path, scaler_path, feature_names_path
        )
        if names is None:
            names = [f"f{i}" for i in range(params.coef.shape[0])]
        return cls(params, scaler, names)
