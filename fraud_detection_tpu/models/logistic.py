"""Flagship model: scaled logistic regression for fraud scoring.

Bundles fitted :class:`LogisticParams` + :class:`ScalerParams` + the frozen
feature order into one object with the estimator surface the reference's
clients expect (``predict`` / ``predict_proba`` — predict_single.py:28-32,
api/app.py:209-240), backed by the scaler-folded jitted scorer.
"""

from __future__ import annotations

import numpy as np

from fraud_detection_tpu.ckpt.checkpoint import (
    export_joblib_artifacts,
    import_joblib_artifacts,
    load_artifacts,
    save_artifacts,
)
from fraud_detection_tpu.ops.linear_shap import LinearShapExplainer, make_explainer
from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.scaler import ScalerParams
from fraud_detection_tpu.ops.scorer import BatchScorer


class FraudLogisticModel:
    def __init__(
        self,
        params: LogisticParams,
        scaler: ScalerParams | None,
        feature_names: list[str],
    ):
        self.params = params
        self.scaler = scaler
        self.feature_names = list(feature_names)
        self._scorer = BatchScorer(params, scaler)

    # -- scoring (raw, unscaled inputs) ------------------------------------
    @property
    def scorer(self) -> BatchScorer:
        return self._scorer

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """(n, 2) array [P(0), P(1)] like sklearn."""
        p1 = self._scorer.predict_proba(x)
        return np.stack([1.0 - p1, p1], axis=1)

    def predict(self, x: np.ndarray, threshold: float = 0.5) -> np.ndarray:
        return self._scorer.predict(x, threshold)

    def score_one(self, features: dict | list) -> tuple[int, float]:
        """Validate + order one row by feature name, return (label, P(1))."""
        row = self.prepare_row(features)
        p = float(self._scorer.predict_proba(row[None, :])[0])
        return int(p >= 0.5), p

    def prepare_row(self, features: dict | list) -> np.ndarray:
        """Reorder dict input to training feature order; validate arity
        (reference predict_single.py:22, api/app.py:185-192)."""
        if isinstance(features, dict):
            missing = [n for n in self.feature_names if n not in features]
            if missing:
                raise ValueError(f"missing features: {missing[:5]}")
            vals = [float(features[n]) for n in self.feature_names]
        else:
            vals = [float(v) for v in features]
            if len(vals) != len(self.feature_names):
                raise ValueError(
                    f"expected {len(self.feature_names)} features, got {len(vals)}"
                )
        return np.asarray(vals, dtype=np.float32)

    # -- explainability ----------------------------------------------------
    def explainer(self, background_mean=None) -> LinearShapExplainer:
        """SHAP explainer in *scaled* space with the training-set background
        (scaled background mean is 0 by construction when fitted with this
        model's scaler — make_explainer's default)."""
        return make_explainer(
            self.params.coef, self.params.intercept, background_mean=background_mean
        )

    def raw_explainer(self) -> LinearShapExplainer:
        """SHAP explainer taking *raw* inputs: scaler folded into the coef,
        background mean = scaler mean (equivalent attributions)."""
        from fraud_detection_tpu.ops.scorer import fold_scaler_into_linear

        folded = fold_scaler_into_linear(self.params, self.scaler)
        mu = (
            np.asarray(self.scaler.mean)
            if self.scaler is not None
            else np.zeros_like(np.asarray(folded.coef))
        )
        return make_explainer(folded.coef, folded.intercept, background_mean=mu)

    # -- persistence -------------------------------------------------------
    def save(self, directory: str, joblib_too: bool = True) -> str:
        save_artifacts(directory, self.params, self.scaler, self.feature_names)
        if joblib_too:
            try:
                export_joblib_artifacts(
                    directory, self.params, self.scaler, self.feature_names
                )
            except RuntimeError:
                pass  # sklearn/joblib not installed — native format only
        return directory

    @classmethod
    def load(cls, directory: str) -> "FraudLogisticModel":
        params, scaler, feature_names = load_artifacts(directory)
        return cls(params, scaler, feature_names)

    @classmethod
    def load_joblib(
        cls, model_path: str, scaler_path: str | None, feature_names_path: str | None
    ) -> "FraudLogisticModel":
        params, scaler, names = import_joblib_artifacts(
            model_path, scaler_path, feature_names_path
        )
        if names is None:
            names = [f"f{i}" for i in range(params.coef.shape[0])]
        return cls(params, scaler, names)
