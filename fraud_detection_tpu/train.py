"""Offline training pipeline.

TPU-native rebuild of the reference trainer (train_model.py:20-163) with its
methodological hygiene preserved:

- stratified 80/20 split (:31-33);
- scaler fitted on the *train* split only (:36-40 — not the legacy
  preprocess.py scale-before-split variant);
- k-fold CV with SMOTE applied *inside* each fold to avoid leakage (:58-87);
- class-imbalance weighting (the XGBoost ``scale_pos_weight`` concept,
  :52-54, carried as ``class_weight``);
- final fit on the SMOTE'd full train set (:89-106);
- test AUC, tracking-run logging, and AUC-gated registry promotion with
  alias (:108-163).

The numerics all run on device: sharded scaler reduction → SMOTE k-NN →
L-BFGS (or SGD for very large row counts) with the gradient reduction
riding ICI. Host code only orchestrates and generates split indices.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import time

import jax
import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.ckpt.checkpoint import save_artifacts
from fraud_detection_tpu.ckpt.train_state import SGDCheckpointer
from fraud_detection_tpu.data.loader import (
    load_creditcard_csv,
    stratified_kfold_indices,
    stratified_split,
)
from fraud_detection_tpu.models.gbt import FraudGBTModel
from fraud_detection_tpu.models.logistic import FraudLogisticModel
from fraud_detection_tpu.monitor.baseline import build_baseline_profile, save_profile
from fraud_detection_tpu.ops.gbt import GBTConfig, gbt_fit, gbt_predict_proba
from fraud_detection_tpu.ops.quant import derive_calibration, save_calibration
from fraud_detection_tpu.ops.logistic import (
    logistic_fit_lbfgs,
    logistic_fit_sgd,
    predict_proba,
)
from fraud_detection_tpu.ops.metrics import auc_roc
from fraud_detection_tpu.ops.scaler import scaler_fit, scaler_transform
from fraud_detection_tpu.ops.smote import smote
from fraud_detection_tpu.tracking import TrackingClient

log = logging.getLogger("fraud_detection_tpu.train")

# Row count above which the full-batch L-BFGS path gives way to minibatch DP
# SGD (L-BFGS linesearch does several full-data passes per iteration).
SGD_ROW_THRESHOLD = 2_000_000


def _fit(x, y, *, seed: int, solver: str, class_weight, checkpointer=None):
    if solver == "sgd" or (solver == "auto" and x.shape[0] > SGD_ROW_THRESHOLD):
        return logistic_fit_sgd(
            x, y, epochs=8, batch_size=65536, lr=1.0, seed=seed,
            class_weight=class_weight,
            epoch_callback=checkpointer.epoch_callback if checkpointer else None,
            resume=checkpointer.latest() if checkpointer else None,
        )
    # L-BFGS is a single compiled solve — nothing to resume mid-way; a
    # checkpoint request silently applies only to the SGD path.
    return logistic_fit_lbfgs(
        x, y, max_iter=200, sharded=True, class_weight=class_weight
    )


def _scale_pos_weight(y) -> float:
    """n_negative / n_positive — the reference's imbalance knob for the
    XGBoost path (train_model.py:52-54), computed pre-SMOTE."""
    n_pos = max(int((np.asarray(y) > 0).sum()), 1)
    return float((len(y) - n_pos) / n_pos)


def _fit_gbt(x, y, *, gbt_config: GBTConfig | None, spw: float):
    cfg = gbt_config or GBTConfig()
    if cfg.scale_pos_weight == 1.0 and spw != 1.0:
        cfg = dataclasses.replace(cfg, scale_pos_weight=spw)
    return gbt_fit(x, y, cfg, sharded=True), cfg


def train(
    data_csv: str | None = None,
    n_folds: int = 5,
    seed: int = 42,
    solver: str = "auto",
    use_smote: bool = True,
    class_weight=None,
    register: bool = True,
    out_dir: str = "models",
    model_family: str = "logistic",
    gbt_config: GBTConfig | None = None,
    checkpoint_dir: str | None = None,
    ledger: bool | None = None,
    wide: bool | None = None,
) -> dict:
    """Run the full pipeline; returns a metrics dict."""
    t0 = time.time()
    data_csv = data_csv or config.data_csv()
    x, y, feature_names = load_creditcard_csv(data_csv)
    log.info("loaded %s: %d rows, %d positives", data_csv, len(y), int(y.sum()))

    train_idx, test_idx = stratified_split(y, 0.2, seed)

    # ---- ledger (stateful feature engine): widen the feature block ----
    # LEDGER_ENABLED=1 / --ledger replays the base rows through the SAME
    # traced velocity aggregator serving runs (ledger/replay — seeded
    # pseudo-entities for the entity-less base CSV, timestamps from the
    # Time column), fits on base + K velocity features, and stamps the
    # final table snapshot + hash geometry beside the weights. The serving
    # tier widens automatically when it loads the sidecar.
    ledger_spec = ledger_state = None
    use_ledger = ledger if ledger is not None else config.ledger_enabled()
    if use_ledger and model_family != "logistic":
        log.warning("ledger widening supports the logistic family only; off")
        use_ledger = False
    if use_ledger:
        from fraud_detection_tpu.ledger import (
            LEDGER_FEATURE_NAMES,
            LedgerSpec,
            materialize_features,
            synthesize_entities,
        )

        spec0 = LedgerSpec.from_config(x.shape[1])
        ents, ts = synthesize_entities(
            x, feature_names, seed, config.ledger_synth_events_per_entity()
        )
        feats, ledger_state = materialize_features(spec0, x, ents, ts)
        x = np.concatenate([x, feats], axis=1).astype(np.float32)
        feature_names = list(feature_names) + list(LEDGER_FEATURE_NAMES)
        ledger_spec = dataclasses.replace(
            spec0,
            # entity-less serving rows read the TRAINING distribution's
            # mean velocity features (the reserved null slot)
            null_features=feats[train_idx].mean(axis=0).astype(np.float32),
            # serve-time wall clocks continue the replay clock seamlessly
            ts_origin=time.time() - (float(ts.max()) + 1.0),
        )
        log.info(
            "ledger widening on: %d slots, halflife %.0fs, +%d velocity "
            "features", spec0.slots, spec0.halflife_s,
            len(LEDGER_FEATURE_NAMES),
        )

    # ---- broadside (the wide family): hashed feature crosses ----
    # WIDE_ENABLED=1 / --wide fits the wide family: multiply-shift hashed
    # crosses of (entity × amount-bucket / hour / sign-pattern) at
    # d = WIDE_BUCKETS feeding the linear scorer through learned bucket
    # weights, trained with the 2-D (data × model) sharded update
    # (mesh/retrain.wide_sgd_fit) and stamped as wide_params.npz beside
    # the weights. The serving tier widens automatically on load.
    wide_spec = None
    wide_fps = None
    use_wide = wide if wide is not None else config.wide_enabled()
    if use_wide and (use_ledger or model_family != "logistic"):
        log.warning("wide family requires the plain logistic base; off")
        use_wide = False
    if use_wide:
        from fraud_detection_tpu.ledger.replay import synthesize_entities
        from fraud_detection_tpu.ops.crosses import (
            entity_fingerprints,
            spec_from_config,
        )

        wide_spec = spec_from_config(x.shape[1])
        ents, _ = synthesize_entities(
            x, feature_names, seed, config.ledger_synth_events_per_entity()
        )
        wide_fps = entity_fingerprints(ents, x.shape[0])
        if use_smote:
            log.info("wide family: SMOTE off (crosses are discrete), "
                     "class_weight=balanced instead")
            use_smote = False
        # --no-smote must not mean "neither": the ~0.2%-positive fraud CSV
        # collapses toward the majority class under uniform weights, and
        # the conductor's wide retrain always fits balanced — keep the
        # offline and online objectives identical.
        class_weight = class_weight or "balanced"
        log.info(
            "wide family on: %d hashed-cross buckets, %d templates",
            wide_spec.buckets, wide_spec.n_cross,
        )

    x_train, y_train = x[train_idx], y[train_idx]
    x_test, y_test = x[test_idx], y[test_idx]

    scaler = scaler_fit(x_train)
    # Logistic path: device-resident from here on — fold gathers, SMOTE,
    # and the fit all consume these directly, so the scaled matrices never
    # round-trip to host (seconds per pass at the 10M-row config). The GBT
    # family bins on host, so it takes numpy (one d2h, same as before).
    xs_train = scaler_transform(scaler, x_train)
    xs_test = scaler_transform(scaler, x_test)
    if model_family == "gbt":
        xs_train = np.asarray(xs_train)
        xs_test = np.asarray(xs_test)

    client = TrackingClient()
    metrics: dict = {}
    with client.start_run() as run:
        # scale_pos_weight and SMOTE are alternative imbalance corrections:
        # SMOTE'd data is already ~balanced, so stacking the pre-SMOTE
        # n_neg/n_pos weight on top (as the reference quirkily does,
        # train_model.py:52-54 + :65-66) double-corrects and miscalibrates
        # probabilities. Apply the weight only on the no-SMOTE path.
        spw = (
            _scale_pos_weight(y_train)
            if model_family == "gbt" and not use_smote
            else 1.0
        )
        run.log_params(
            {
                "model_type": (
                    "gbt" if model_family == "gbt" else "logistic_regression"
                ),
                "solver": solver,
                "n_folds": n_folds,
                "use_smote": use_smote,
                "class_weight": class_weight,
                "seed": seed,
                "n_rows": len(y),
                "n_features": x.shape[1],
                "device": jax.devices()[0].platform,
                "n_devices": jax.device_count(),
            }
        )

        # ---- CV with SMOTE inside each fold (no leakage) ----
        cv_aucs = []
        if use_wide:
            run.set_tag("cv_skipped", "wide family: single 2-D sharded fit")
        for fold, (tr, va) in enumerate(
            () if use_wide else stratified_kfold_indices(y_train, n_folds, seed)
        ):
            x_tr, y_tr = xs_train[tr], y_train[tr]
            try:
                if use_smote:
                    x_tr, y_tr = smote(x_tr, y_tr, jax.random.key(seed + fold))
                if model_family == "gbt":
                    gmodel, _ = _fit_gbt(
                        x_tr, y_tr, gbt_config=gbt_config, spw=spw
                    )
                    val_scores = np.asarray(
                        gbt_predict_proba(gmodel, xs_train[va])
                    )
                else:
                    params = _fit(
                        x_tr, y_tr,
                        seed=seed + fold, solver=solver, class_weight=class_weight,
                    )
                    val_scores = np.asarray(predict_proba(params, xs_train[va]))
                fold_auc = float(auc_roc(val_scores, y_train[va]))
            except ValueError as e:
                # Degenerate fold (too few positives for SMOTE neighbors or a
                # single-class validation slice): report and move on rather
                # than failing the whole run.
                log.warning("fold %d skipped: %s", fold, e)
                run.set_tag(f"fold_{fold}_skipped", str(e))
                continue
            cv_aucs.append(fold_auc)
            run.log_metric("cv_auc", fold_auc, step=fold)
            log.info("fold %d AUC %.4f", fold, fold_auc)
        if cv_aucs:
            metrics["cv_auc_mean"] = float(np.mean(cv_aucs))
            run.log_metric("cv_auc_mean", metrics["cv_auc_mean"])

        # ---- final fit on SMOTE'd full train split ----
        x_fin, y_fin = (
            smote(xs_train, y_train, jax.random.key(seed + 1000))
            if use_smote
            else (xs_train, y_train)
        )
        wide_table = None
        if use_wide:
            # the 2-D (data × model) sharded wide fit: grads psum_scatter
            # on the data axis, the cross table column-owned on the model
            # axis (2004.13336 in 2-D — the conductor's retrain runs the
            # identical program on the same mesh)
            from fraud_detection_tpu.mesh.retrain import (
                wide_sgd_fit,
                wide_training_mesh,
            )
            from fraud_detection_tpu.ops.crosses import cross_indices

            idx_train = cross_indices(
                x_train, wide_fps[train_idx], wide_spec
            )
            params, wide_table = wide_sgd_fit(
                np.asarray(x_fin), idx_train,
                (wide_fps[train_idx] != 0).astype(np.float32),
                np.asarray(y_fin), wide_spec, epochs=20, seed=seed,
                class_weight=class_weight,
                mesh=wide_training_mesh(),
            )
            from fraud_detection_tpu.ops.crosses import widen_with_crosses

            xw_test = widen_with_crosses(
                x_test, wide_fps[test_idx], wide_table, wide_spec
            )
            # score the widened block exactly as serving would: scaled
            # base columns + raw cross contributions through the widened
            # coef (predict_proba on the wide scorer below)
            from fraud_detection_tpu.ops.crosses import widen_scaler

            wide_scaler = widen_scaler(scaler, wide_spec.n_cross)
            feature_names = list(feature_names) + list(wide_spec.cross_names)
            model = FraudLogisticModel(
                params, wide_scaler, feature_names,
                wide_spec=wide_spec, wide_table=wide_table,
            )
            test_scores = np.asarray(model.scorer.predict_proba(xw_test))
        elif model_family == "gbt":
            gmodel, used_cfg = _fit_gbt(
                x_fin, y_fin, gbt_config=gbt_config, spw=spw
            )
            run.log_params(
                {
                    "n_trees": used_cfg.n_trees,
                    "max_depth": used_cfg.max_depth,
                    "learning_rate": used_cfg.learning_rate,
                    "scale_pos_weight": used_cfg.scale_pos_weight,
                }
            )
            test_scores = np.asarray(gbt_predict_proba(gmodel, xs_test))
        else:
            # Elastic recovery applies to the long stage (the final fit on
            # the SMOTE'd full train split); a preempted run restarted with
            # the same checkpoint_dir continues at the next epoch.
            ck = SGDCheckpointer(checkpoint_dir) if checkpoint_dir else None
            params = _fit(
                x_fin, y_fin, seed=seed, solver=solver, class_weight=class_weight,
                checkpointer=ck,
            )
            if ck is not None:
                # The fit finished: leftover checkpoints must not hijack a
                # future run with this directory into "resuming" stale params.
                ck.clear()
            test_scores = np.asarray(predict_proba(params, xs_test))
        test_auc = float(auc_roc(test_scores, y_test))
        metrics["test_auc"] = test_auc
        run.log_metric("test_auc", test_auc)
        log.info("test AUC %.4f", test_auc)

        # ---- watchtower baseline profile (monitor/) ----
        # Profiled in RAW feature space: the serving scorer folds the scaler
        # into its weights and consumes raw rows, so the drift reference must
        # bin what the microbatcher actually sees. Score reference comes from
        # the held-out test scores (the distribution a healthy model emits).
        if use_wide:
            # the drift baseline covers the WIDENED block (base + cross
            # contributions) — the distribution the fused wide flush bins
            from fraud_detection_tpu.ops.crosses import widen_with_crosses

            profile = build_baseline_profile(
                widen_with_crosses(
                    x_train, wide_fps[train_idx], wide_table, wide_spec
                ),
                test_scores, feature_names=feature_names,
            )
        else:
            profile = build_baseline_profile(
                x_train, test_scores, feature_names=feature_names
            )
        run.log_metric("monitor_profile_rows", profile.n_rows)

        # ---- artifacts: native + joblib interchange ----
        model_artifact = run.artifact_path("model")
        if model_family == "gbt":
            # The wrapper folds the scaler into the bin edges, so the saved
            # forest scores raw inputs directly (no scaler sidecar needed).
            # A raw-space training subsample ships as the TreeSHAP background.
            # The wrapper also derives the int8 wire calibration from the
            # scaler BEFORE the fold consumes it, and save() stamps
            # quant_calibration.npz beside the forest (evergreen) — same
            # sidecar contract as the linear branch below.
            bg_idx = np.random.default_rng(seed).choice(
                len(x_train), min(128, len(x_train)), replace=False
            )
            model = FraudGBTModel(
                gmodel, feature_names, scaler=scaler, background=x_train[bg_idx]
            )
            model.save(out_dir)
            model.save(model_artifact)
        elif use_wide:
            # model was built above (the widened scorer scored the test
            # slice); save() stamps wide_params.npz + the widened
            # calibration beside the weights in both destinations
            model.save(out_dir)
            model.save(model_artifact)
        else:
            model = FraudLogisticModel(
                params, scaler, feature_names,
                ledger_spec=ledger_spec, ledger_state=ledger_state,
            )
            model.save(out_dir)
            save_artifacts(model_artifact, params, scaler, feature_names)
            if ledger_spec is not None:
                from fraud_detection_tpu.ledger.state import save_ledger

                save_ledger(model_artifact, ledger_spec, ledger_state)
            if scaler is not None:
                # quickwire int8 wire calibration: stamped beside the
                # weights so the serving quantizer is pinned to THIS
                # model's training profile (rebound on hot swap)
                save_calibration(model_artifact, derive_calibration(scaler))
        # Beside model.npz in BOTH destinations: registry registration
        # copytrees the run artifact dir, so every resolution path (alias,
        # native dir, promoted copy) carries its own drift baseline.
        save_profile(out_dir, profile)
        save_profile(model_artifact, profile)

        # ---- AUC promotion gate ----
        threshold = config.auc_threshold()
        run.log_param("auc_threshold", threshold)
        version = None
        if register:
            # Same lineage record the conductor writes (lifecycle/), so a
            # registry version always says where it came from — an offline
            # run's parent is whatever @prod pointed at when it trained.
            parent = client.registry.get_version_by_alias(
                config.model_name(), config.model_stage()
            )
            version = client.registry.register_if_gate(
                config.model_name(),
                model_artifact,
                test_auc,
                threshold,
                alias=config.model_stage(),
                run_id=run.run_id,
                lineage={
                    "trained_by": "offline",
                    "parent_version": parent,
                    "data_csv": data_csv,
                    "n_rows": len(y),
                },
            )
            if version:
                run.set_tag("registered_version", version)
                log.info(
                    "registered %s v%d (alias %s)",
                    config.model_name(), version, config.model_stage(),
                )
            else:
                log.warning(
                    "AUC %.4f below threshold %.2f — not registered",
                    test_auc, threshold,
                )
        metrics["registered_version"] = version
        metrics["train_seconds"] = time.time() - t0
        run.log_metric("train_seconds", metrics["train_seconds"])
    return metrics


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    config.apply_device_backend()  # DEVICE=cpu trains without the TPU tunnel
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None)
    ap.add_argument("--folds", type=int, default=5)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--solver", choices=["auto", "lbfgs", "sgd"], default="auto")
    ap.add_argument(
        "--model", choices=["logistic", "gbt"], default="logistic",
        help="model family: the logistic flagship or the XGBoost-recipe "
        "histogram GBDT (reference train_model.py:69-80)",
    )
    ap.add_argument("--no-smote", action="store_true")
    ap.add_argument("--no-register", action="store_true")
    ap.add_argument(
        "--wide", action="store_true",
        help="fit the broadside wide family: hashed feature crosses at "
        "d=WIDE_BUCKETS over a 2-D (data x model) mesh "
        "(fraud_detection_tpu/ops/crosses); also WIDE_ENABLED=1",
    )
    ap.add_argument(
        "--ledger", action="store_true",
        help="widen the feature block with the ledger's per-entity "
        "velocity aggregates (replayed through the serving body — see "
        "fraud_detection_tpu/ledger); also LEDGER_ENABLED=1",
    )
    ap.add_argument("--out-dir", default="models")
    ap.add_argument(
        "--profile-dir", default=None,
        help="capture a jax.profiler device trace of the run to this dir "
        "(view with tensorboard --logdir or Perfetto)",
    )
    ap.add_argument(
        "--checkpoint-dir", default=None,
        help="write per-epoch SGD training checkpoints here; re-running "
        "with the same dir resumes an interrupted fit at the next epoch "
        "(sgd/auto solver only)",
    )
    args = ap.parse_args(argv)

    def go():
        return train(
            data_csv=args.data,
            n_folds=args.folds,
            seed=args.seed,
            solver=args.solver,
            use_smote=not args.no_smote,
            register=not args.no_register,
            out_dir=args.out_dir,
            model_family=args.model,
            checkpoint_dir=args.checkpoint_dir,
            ledger=True if args.ledger else None,
            wide=True if args.wide else None,
        )

    if args.profile_dir:
        from fraud_detection_tpu.utils.profiling import device_trace

        with device_trace(args.profile_dir):
            metrics = go()
    else:
        metrics = go()
    print(metrics)


if __name__ == "__main__":
    main()
