"""Performance lint rules: allocation churn on marked hot paths.

The fastlane work (service/microbatch flush, monitor/drift fused update,
service/worker batched explain) replaced per-flush ``np.zeros``/``np.stack``
churn with preallocated per-bucket staging buffers
(:class:`fraud_detection_tpu.ops.scorer.StagingPool`). This rule is the
mechanical guard that keeps fresh allocations from creeping back: a
``# graftcheck: hot-path`` comment anywhere inside a function marks that
function (innermost enclosing one) as a steady-state hot region, and every
array-constructor call inside it is flagged. Reviewed exceptions use the
standard ``# graftcheck: ignore[hot-path-alloc]`` tag.

The marker is a comment, not a decorator, so it costs nothing at runtime
and can sit directly on the line that explains WHY the region is hot.
"""

from __future__ import annotations

import ast
import re
import tokenize
from io import StringIO
from typing import Iterator

from fraud_detection_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Severity,
    dotted_name,
    register_rule,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

_HOT_PATH_RE = re.compile(r"#\s*graftcheck:\s*hot-path\b")

#: array constructors that always materialize a fresh buffer. Reshapes,
#: views, and in-place ops are the sanctioned replacements and deliberately
#: not listed; ``asarray``/``array`` stay off the list too — the d2h fetch
#: of device results legitimately materializes its output on the hot path.
_ALLOC_FNS = {
    "zeros", "empty", "ones", "full",
    "zeros_like", "empty_like", "ones_like", "full_like",
}
#: combinators that allocate UNLESS redirected into a preallocated buffer
#: with ``out=`` — ``np.stack(rows)`` per flush is the exact churn fastlane
#: removed, ``np.stack(rows, out=slot.f32[:n])`` is its replacement.
#: ``multiply``/``divide`` joined with quickwire: the return-wire decode
#: (uint8 score codes → f32 probabilities) must write into the staging
#: slot's preallocated ``scores`` buffer, not mint a fresh result vector
#: per flush.
_ALLOC_UNLESS_OUT_FNS = {
    "stack", "concatenate", "vstack", "hstack", "multiply", "divide",
}
_ALLOC_MODULES = {"np", "numpy", "jnp", "onp"}


def _hot_path_lines(mod: ModuleInfo) -> list[int]:
    """Line numbers carrying a ``# graftcheck: hot-path`` marker, found via
    tokenize (same discipline as the suppression scan: a '#' inside a
    string can't fake a marker)."""
    out: list[int] = []
    try:
        for tok in tokenize.generate_tokens(StringIO(mod.source).readline):
            if tok.type == tokenize.COMMENT and _HOT_PATH_RE.search(tok.string):
                out.append(tok.start[0])
    except tokenize.TokenError:
        pass
    return out


def _marked_functions(mod: ModuleInfo) -> set[ast.AST]:
    """The innermost function enclosing each marker line. A marker outside
    every function body (module level) marks nothing — hot paths are
    functions."""
    lines = _hot_path_lines(mod)
    if not lines:
        return set()
    funcs = [n for n in ast.walk(mod.tree) if isinstance(n, _FuncDef)]
    marked: set[ast.AST] = set()
    for ln in lines:
        best = None
        for fn in funcs:
            end = getattr(fn, "end_lineno", fn.lineno)
            if fn.lineno <= ln <= end and (
                best is None or fn.lineno > best.lineno
            ):
                best = fn
        if best is not None:
            marked.add(best)
    return marked


@register_rule(
    "hot-path-alloc",
    Severity.WARNING,
    "fresh array allocation (np.zeros/np.empty/jnp.zeros/...) inside a "
    "region marked '# graftcheck: hot-path' — steady-state hot paths must "
    "reuse preallocated staging buffers (ops/scorer.StagingPool)",
)
def check_hot_path_alloc(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_hot_path_alloc.rule
    for fn in _marked_functions(mod):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            parts = callee.split(".")
            if len(parts) != 2 or parts[0] not in _ALLOC_MODULES:
                continue
            if parts[1] in _ALLOC_FNS:
                yield mod.finding(
                    rule, node,
                    f"{callee}(...) allocates a fresh array inside hot-path "
                    f"region {fn.name!r} — stage into a preallocated buffer "
                    "(ops/scorer.StagingPool) instead",
                )
            elif parts[1] in _ALLOC_UNLESS_OUT_FNS and not any(
                kw.arg == "out" for kw in node.keywords
            ):
                yield mod.finding(
                    rule, node,
                    f"{callee}(...) without out= allocates a fresh batch "
                    f"array inside hot-path region {fn.name!r} — pass "
                    "out=<staging slot> (ops/scorer.StagingPool) instead",
                )


#: per-row interpreter work the hyperloop ingest path exists to remove:
#: a json.loads/dumps call costs ~µs per KB, and a list/dict/set
#: comprehension over the batch rebuilds one Python object per ROW — both
#: re-introduce exactly the per-row costs the binary lane deleted. The
#: sanctioned replacements are the fixed-layout frame decode
#: (service/binlane: np.frombuffer views + bulk copies into pooled
#: staging) and vectorized numpy column math.
_JSON_CALLS = {"json.loads", "json.dumps"}
_COMP_NODES = (ast.ListComp, ast.DictComp, ast.SetComp)
_COMP_NAME = {
    ast.ListComp: "list comprehension",
    ast.DictComp: "dict comprehension",
    ast.SetComp: "set comprehension",
}


@register_rule(
    "hot-path-json",
    Severity.WARNING,
    "json.loads/json.dumps or a per-row list/dict comprehension inside a "
    "region marked '# graftcheck: hot-path' — the steady-state ingest/"
    "flush path must decode fixed-layout frames into pooled staging "
    "(service/binlane) and use vectorized column math, never rebuild "
    "per-row Python objects",
)
def check_hot_path_json(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_hot_path_json.rule
    for fn in _marked_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                if callee in _JSON_CALLS:
                    yield mod.finding(
                        rule, node,
                        f"{callee}(...) inside hot-path region {fn.name!r} "
                        "— JSON (de)serialization is per-request "
                        "interpreter work; use the fixed-layout binary "
                        "frame decode (service/binlane) instead",
                    )
            elif isinstance(node, _COMP_NODES):
                yield mod.finding(
                    rule, node,
                    f"{_COMP_NAME[type(node)]} inside hot-path region "
                    f"{fn.name!r} builds one Python object per element — "
                    "vectorize over the staged numpy columns instead",
                )
