"""Lint engine core: findings, severities, rule registry, AST module model.

The engine parses each file once into a :class:`ModuleInfo` — AST plus the
derived facts every rule needs (parent links, which functions are
jit-compiled, comment suppressions) — and hands it to each registered
:class:`Rule`. Rules are pure functions of the module model; registering a
new one is a decorator (:func:`register_rule`), no engine changes.

Suppression: a finding is dropped when its line (or the line above) carries
``# graftcheck: ignore[rule-id]`` (or a bare ``# graftcheck: ignore`` for
any rule). The tag doubles as the reviewed-and-narrowed marker the
``silent-except`` audit rule accepts in lieu of logging.
"""

from __future__ import annotations

import ast
import enum
import hashlib
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Callable, Iterable, Iterator


class Severity(enum.IntEnum):
    """Finding severity. ERROR means "wrong on real hardware" (host syncs in
    jit, tracer leaks); WARNING is a latent operational hazard; INFO is an
    optimization opportunity."""

    INFO = 1
    WARNING = 2
    ERROR = 3

    @classmethod
    def parse(cls, s: str) -> "Severity":
        try:
            return cls[s.upper()]
        except KeyError:
            raise ValueError(
                f"severity must be info|warning|error, got {s!r}"
            ) from None


@dataclass(frozen=True)
class Finding:
    rule_id: str
    severity: Severity
    path: str       # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str    # the source line, stripped

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: rule + file + normalized
        source line. Deliberately line-NUMBER-insensitive so unrelated edits
        above a baselined finding don't invalidate the baseline."""
        norm = re.sub(r"\s+", " ", self.snippet).strip()
        h = hashlib.sha1(
            f"{self.rule_id}|{self.path}|{norm}".encode()
        ).hexdigest()
        return h[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "severity": self.severity.name.lower(),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }


# --------------------------------------------------------------------------
# Module model
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*ignore(?:\[([A-Za-z0-9_,\- ]*)\])?"
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains; ``.item`` style (leading dot)
    when the chain root is a call/subscript rather than a name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:  # method on an arbitrary expression: "(expr).item" → ".item"
        return "." + ".".join(reversed(parts))
    return None


class ModuleInfo:
    """One parsed module plus the derived facts rules dispatch on."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._suppressions = self._scan_suppressions()
        self.jit_functions = self._find_jit_functions()

    # -- suppressions ------------------------------------------------------
    def _scan_suppressions(self) -> dict[int, set[str] | None]:
        """line -> None (suppress all) or set of rule ids, from comments.
        Tokenized (not regexed over raw lines) so a '#' inside a string
        can't fake a suppression."""
        out: dict[int, set[str] | None] = {}
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                ids = m.group(1)
                if ids is None or not ids.strip():
                    out[tok.start[0]] = None
                else:
                    out[tok.start[0]] = {
                        s.strip() for s in ids.split(",") if s.strip()
                    }
        except tokenize.TokenError:
            pass
        return out

    def suppressed(self, line: int, rule_id: str) -> bool:
        for ln in (line, line - 1):
            ids = self._suppressions.get(ln, "missing")
            if ids is None:
                return True
            if isinstance(ids, set) and rule_id in ids:
                return True
        return False

    # -- jit context -------------------------------------------------------
    def _jit_names_in_call_args(self) -> set[str]:
        """Names referenced inside jax.jit(...)/shard_map(...)/jit(...) call
        arguments — functions compiled by reference rather than decorator
        (``_boost_jit = jax.jit(_boost, ...)``, ``shard_map(partial(f, ...))``)."""
        names: set[str] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee not in ("jax.jit", "jit", "shard_map", "jax.pmap", "pmap"):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
        return names

    @staticmethod
    def _decorator_is_jit(dec: ast.AST) -> bool:
        name = dotted_name(dec)
        if name in ("jax.jit", "jit", "jax.pmap", "pmap"):
            return True
        if isinstance(dec, ast.Call):
            callee = dotted_name(dec.func)
            if callee in ("jax.jit", "jit", "jax.pmap", "pmap"):
                return True
            # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
            if callee in ("partial", "functools.partial") and dec.args:
                return dotted_name(dec.args[0]) in ("jax.jit", "jit")
        return False

    def _find_jit_functions(self) -> set[ast.AST]:
        by_ref = self._jit_names_in_call_args()
        out: set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, _FuncDef):
                continue
            if any(self._decorator_is_jit(d) for d in node.decorator_list):
                out.add(node)
            elif node.name in by_ref:
                out.add(node)
        return out

    def enclosing_functions(self, node: ast.AST) -> Iterator[ast.AST]:
        """Innermost-out chain of FunctionDefs containing ``node``."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FuncDef):
                yield cur
            cur = self.parents.get(cur)

    def in_jit_context(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside a jit-compiled function (including
        functions nested within one — their bodies trace too)."""
        return any(
            fn in self.jit_functions for fn in self.enclosing_functions(node)
        )

    # -- misc helpers ------------------------------------------------------
    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(
        self, rule: "Rule", node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule_id=rule.id,
            severity=rule.severity,
            path=self.rel_path,
            line=line,
            col=col,
            message=message,
            snippet=self.line_text(line),
        )


# --------------------------------------------------------------------------
# Rules + registry
# --------------------------------------------------------------------------


@dataclass
class Rule:
    id: str
    severity: Severity
    description: str
    check: Callable[[ModuleInfo], Iterable[Finding]] = field(repr=False)


_REGISTRY: dict[str, Rule] = {}


def register_rule(id: str, severity: Severity, description: str):
    """Decorator: register ``fn(mod: ModuleInfo) -> Iterable[Finding]`` as a
    rule. The decorated function receives the rule object as attribute
    ``fn.rule`` so it can mint findings via ``mod.finding(fn.rule, ...)``."""

    def deco(fn):
        rule = Rule(id=id, severity=severity, description=description, check=fn)
        if id in _REGISTRY:
            raise ValueError(f"duplicate rule id {id!r}")
        _REGISTRY[id] = rule
        fn.rule = rule
        return fn

    return deco


def iter_rules() -> list[Rule]:
    return list(_REGISTRY.values())


def get_rule(rule_id: str) -> Rule:
    return _REGISTRY[rule_id]


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------

#: paths (relative, substring match on normalized separators) never scanned:
#: the lint fixtures are deliberately bad code.
DEFAULT_EXCLUDES = ("tests/analysis_fixtures/",)


def analyze_file(
    path: str,
    root: str | None = None,
    rules: Iterable[Rule] | None = None,
) -> list[Finding]:
    root = root or os.getcwd()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        mod = ModuleInfo(path, rel, source)
    except SyntaxError as e:
        return [
            Finding(
                rule_id="syntax-error",
                severity=Severity.ERROR,
                path=rel,
                line=e.lineno or 1,
                col=e.offset or 0,
                message=f"file does not parse: {e.msg}",
                snippet="",
            )
        ]
    findings: list[Finding] = []
    for rule in rules if rules is not None else iter_rules():
        for f_ in rule.check(mod):
            if not mod.suppressed(f_.line, f_.rule_id):
                findings.append(f_)
    return findings


def iter_python_files(
    paths: Iterable[str], excludes: Iterable[str] = DEFAULT_EXCLUDES
) -> Iterator[str]:
    excludes = tuple(excludes)

    def excluded(p: str) -> bool:
        norm = p.replace(os.sep, "/")
        return any(e in norm for e in excludes)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not excluded(p):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                if fn.endswith(".py") and not excluded(full):
                    yield full


def analyze_paths(
    paths: Iterable[str],
    root: str | None = None,
    rules: Iterable[Rule] | None = None,
    excludes: Iterable[str] = DEFAULT_EXCLUDES,
) -> list[Finding]:
    rules = list(rules) if rules is not None else iter_rules()
    out: list[Finding] = []
    for path in iter_python_files(paths, excludes):
        out.extend(analyze_file(path, root=root, rules=rules))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return out
