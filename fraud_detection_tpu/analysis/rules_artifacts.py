"""Artifact-durability lint: every trusted ``.npz`` must land atomically.

The loaders trust whatever bytes sit at ``model.npz`` /
``quant_calibration.npz`` / ``ledger_state.npz`` / ``monitor_profile.npz``
— a crash mid-``np.savez`` leaves a torn archive at the trusted name and
the next process start serves garbage (or dies in ``np.load``). The
lifeboat work (ISSUE 15) centralized the fix in
:mod:`fraud_detection_tpu.ckpt.atomic` (tmp → fsync → rename → dir fsync);
this rule is the mechanical guard that keeps bare writes from regrowing:

- any ``np.savez``/``np.savez_compressed`` call outside ``ckpt/atomic.py``
  is an ERROR (``atomic_savez`` is the drop-in replacement; serializing to
  an in-memory buffer belongs in ``ckpt/atomic.savez_bytes``);
- ``open(..., "wb")`` / ``"ab"`` of a path naming a ``.npz`` artifact
  (string literal, f-string suffix, ``os.path.join`` tail, or a
  module-level ``*_FILE`` constant) is an ERROR for the same reason.

Reviewed exceptions carry the standard
``# graftcheck: ignore[artifact-nonatomic-write]`` tag.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fraud_detection_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Severity,
    dotted_name,
    register_rule,
)

#: the one sanctioned home of a bare np.savez (the helper itself)
_ATOMIC_HELPER_SUFFIX = "ckpt/atomic.py"

_SAVEZ_FNS = {"savez", "savez_compressed"}
_NP_MODULES = {"np", "numpy", "jnp", "onp"}

_WRITE_MODES = {"wb", "ab", "wb+", "ab+", "w+b", "a+b"}


def _module_str_consts(mod: ModuleInfo) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings — resolves the common
    ``LEDGER_FILE = "ledger_state.npz"`` indirection."""
    out: dict[str, str] = {}
    for node in mod.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _npz_suffix(node: ast.AST, consts: dict[str, str]) -> bool:
    """Does this path expression *provably* end with ``.npz``? Conservative:
    unresolvable expressions are not flagged (no false positives on
    arbitrary variables)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.endswith(".npz")
    if isinstance(node, ast.Name):
        return consts.get(node.id, "").endswith(".npz")
    if isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        return (
            isinstance(last, ast.Constant)
            and isinstance(last.value, str)
            and last.value.endswith(".npz")
        )
    if isinstance(node, ast.Call):
        callee = dotted_name(node.func)
        if callee in ("os.path.join", "posixpath.join", "Path") and node.args:
            return _npz_suffix(node.args[-1], consts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _npz_suffix(node.right, consts)
    return False


@register_rule(
    "artifact-nonatomic-write",
    Severity.ERROR,
    "bare np.savez / open('...npz', 'wb') write of a trusted artifact — a "
    "crash mid-write leaves a torn file at the name every loader trusts; "
    "use ckpt/atomic.atomic_savez (tmp + fsync + rename + dir fsync)",
)
def check_artifact_nonatomic_write(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_artifact_nonatomic_write.rule
    if mod.rel_path.replace("\\", "/").endswith(_ATOMIC_HELPER_SUFFIX):
        return
    consts = _module_str_consts(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        if callee is None:
            continue
        parts = callee.split(".")
        if (
            len(parts) == 2
            and parts[0] in _NP_MODULES
            and parts[1] in _SAVEZ_FNS
        ):
            yield mod.finding(
                rule, node,
                f"{callee}(...) writes the archive in place — a crash "
                "mid-write leaves a torn file at the trusted name; use "
                "ckpt/atomic.atomic_savez (or savez_bytes + "
                "atomic_write_bytes for framed containers)",
            )
            continue
        if callee == "open" and len(node.args) >= 2:
            mode = node.args[1]
            if not (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and mode.value in _WRITE_MODES
            ):
                continue
            if _npz_suffix(node.args[0], consts):
                yield mod.finding(
                    rule, node,
                    "open(..., 'wb') of a .npz artifact bypasses the "
                    "atomic write discipline — route the bytes through "
                    "ckpt/atomic.atomic_write_bytes",
                )
