"""JAX-specific lint rules: the failure modes CPU pytest cannot surface.

Each rule is a registered checker over :class:`~.core.ModuleInfo`. They are
heuristic by design — static analysis of a dynamic language — tuned so the
repo's own idioms (static_argnames casts, lru-cached shard_map builders)
don't false-positive, with ``# graftcheck: ignore[...]`` as the escape
hatch for reviewed exceptions.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from fraud_detection_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Severity,
    dotted_name,
    register_rule,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

#: calls that force a device→host sync (or a host round trip) when executed
#: on a traced value — poison inside a jit region.
_HOST_SYNC_CALLS = {
    "np.asarray": "np.asarray materializes the traced value on host",
    "np.array": "np.array materializes the traced value on host",
    "numpy.asarray": "numpy.asarray materializes the traced value on host",
    "numpy.array": "numpy.array materializes the traced value on host",
    "jax.device_get": "device_get is a host transfer",
    "onp.asarray": "np.asarray materializes the traced value on host",
}

#: zero-arg methods that sync scalar-by-scalar — the classic silent
#: hot-path killer (`.item()` in a loop).
_HOST_SYNC_METHODS = {"item", "tolist", "to_py"}

_PY_CASTS = {"float", "int", "bool", "complex"}


def _jit_static_names(fn: ast.AST, mod: ModuleInfo) -> set[str]:
    """Parameter names marked static in the function's jit decorator
    (``static_argnames`` strings, or ``static_argnums`` indices resolved
    against the signature)."""
    out: set[str] = set()
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        callee = dotted_name(dec.func)
        if callee in ("partial", "functools.partial"):
            if not dec.args or dotted_name(dec.args[0]) not in ("jax.jit", "jit"):
                continue
        elif callee not in ("jax.jit", "jit"):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                        out.add(sub.value)
            elif kw.arg == "static_argnums":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and isinstance(sub.value, int):
                        if 0 <= sub.value < len(args):
                            out.add(args[sub.value])
    return out


def _nearest_jit_fn(mod: ModuleInfo, node: ast.AST) -> ast.AST | None:
    for fn in mod.enclosing_functions(node):
        if fn in mod.jit_functions:
            return fn
    return None


@register_rule(
    "jit-host-sync",
    Severity.ERROR,
    "host-device synchronization inside a jit region (.item()/np.asarray/"
    "float() on traced values) — stalls the device pipeline every call",
)
def check_host_sync(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_host_sync.rule
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        jit_fn = _nearest_jit_fn(mod, node)
        if jit_fn is None:
            continue
        callee = dotted_name(node.func)
        if callee in _HOST_SYNC_CALLS:
            yield mod.finding(
                rule, node,
                f"{_HOST_SYNC_CALLS[callee]} inside a jit region",
            )
            continue
        # method-style syncs: x.item(), scores.tolist()
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HOST_SYNC_METHODS
            and not node.args
        ):
            yield mod.finding(
                rule, node,
                f".{node.func.attr}() forces a device→host sync per element "
                "inside a jit region",
            )
            continue
        # float(x)/int(x)/bool(x) on a (non-static) parameter of the jitted
        # function: on a tracer this is a ConcretizationTypeError at best, a
        # silent recompile-per-value trigger at worst.
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _PY_CASTS
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
        ):
            params = {
                a.arg
                for a in (
                    jit_fn.args.posonlyargs
                    + jit_fn.args.args
                    + jit_fn.args.kwonlyargs
                )
            }
            statics = _jit_static_names(jit_fn, mod)
            if node.args[0].id in params - statics:
                yield mod.finding(
                    rule, node,
                    f"{node.func.id}() on traced argument "
                    f"{node.args[0].id!r} inside jit — concretizes the "
                    "tracer (mark it static or keep it on device)",
                )


@register_rule(
    "jit-scalar-closure",
    Severity.WARNING,
    "jit-decorated function closes over an enclosing function's argument — "
    "every new value bakes a new trace (recompile storm); hoist the capture "
    "into an argument or cache the builder with functools.lru_cache",
)
def check_scalar_closure(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_scalar_closure.rule
    for fn in mod.jit_functions:
        if not isinstance(fn, _FuncDef):
            continue
        enclosing = list(mod.enclosing_functions(fn))
        if not enclosing:
            continue  # module-level jit: closures are module constants
        # the sanctioned pattern: an lru_cache'd builder keys the cache on
        # exactly the values the closure captures, so each capture set
        # compiles once (ops/logistic._sharded_epoch)
        if any(_is_cached(f2) for f2 in enclosing):
            continue
        captured = _captured_enclosing_args(fn, enclosing)
        for name, line_node in captured:
            yield mod.finding(
                rule, line_node,
                f"jitted {fn.name!r} captures {name!r} from its enclosing "
                "function's arguments — each distinct value triggers a full "
                "retrace/recompile",
            )


def _is_cached(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        name = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
        if name and name.split(".")[-1] in ("lru_cache", "cache"):
            return True
    return False


def _captured_enclosing_args(
    fn: ast.AST, enclosing: list[ast.AST]
) -> list[tuple[str, ast.AST]]:
    """(name, first-load-node) for loads in ``fn`` of names that are
    parameters of an enclosing function and not shadowed locally."""
    local: set[str] = {
        a.arg for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    }
    if fn.args.vararg:
        local.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        local.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            local.add(node.id)
        elif isinstance(node, _FuncDef) and node is not fn:
            local.add(node.name)
    outer_args: set[str] = set()
    for f2 in enclosing:
        outer_args |= {
            a.arg
            for a in f2.args.posonlyargs + f2.args.args + f2.args.kwonlyargs
        }
    seen: set[str] = set()
    out: list[tuple[str, ast.AST]] = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in outer_args
            and node.id not in local
            and node.id not in seen
        ):
            seen.add(node.id)
            out.append((node.id, node))
    return out


@register_rule(
    "jit-tracer-global",
    Severity.ERROR,
    "mutation of module-global state inside a jit region — the write runs "
    "once at trace time and can leak tracers into host state",
)
def check_tracer_global(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_tracer_global.rule
    module_names = _module_level_names(mod)
    mutators = {"append", "extend", "add", "update", "setdefault", "insert"}
    for node in ast.walk(mod.tree):
        if not mod.in_jit_context(node):
            continue
        if isinstance(node, ast.Global):
            yield mod.finding(
                rule, node,
                f"`global {', '.join(node.names)}` inside a jit region — "
                "assignments here run at trace time and capture tracers",
            )
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                root = _subscript_or_attr_root(t)
                if root is not None and root in module_names:
                    yield mod.finding(
                        rule, node,
                        f"write to module-global {root!r} inside a jit "
                        "region — runs at trace time, not per call",
                    )
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in mutators
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in module_names
            ):
                yield mod.finding(
                    rule, node,
                    f"{node.func.value.id}.{node.func.attr}(...) mutates "
                    "module-global state inside a jit region",
                )


def _module_level_names(mod: ModuleInfo) -> set[str]:
    """Names bound by module-level assignments (the mutable-global
    candidates; imports/defs excluded — calling or reading those is fine)."""
    out: set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
    return out


def _subscript_or_attr_root(t: ast.AST) -> str | None:
    if isinstance(t, (ast.Subscript, ast.Attribute)):
        cur = t
        while isinstance(cur, (ast.Subscript, ast.Attribute)):
            cur = cur.value
        if isinstance(cur, ast.Name):
            return cur.id
    return None


@register_rule(
    "jit-missing-donate",
    Severity.INFO,
    "state-threading jit (returns an updated version of one of its "
    "arguments) without donate_argnums/donate_argnames — the old buffer "
    "stays live across the call, doubling peak memory for large states",
)
def check_missing_donate(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_missing_donate.rule
    for fn in mod.jit_functions:
        if not isinstance(fn, _FuncDef):
            continue
        if _jit_has_donate(fn):
            continue
        threaded = _threaded_params(fn)
        if threaded:
            yield mod.finding(
                rule, fn,
                f"jitted {fn.name!r} returns updated argument(s) "
                f"{sorted(threaded)} without donating them — consider "
                "donate_argnums so XLA reuses the input buffers",
            )


def _jit_has_donate(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            for kw in dec.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    return True
    return False


def _threaded_params(fn: ast.AST) -> set[str]:
    """Parameter names that are reassigned in the body AND appear in a
    return value — the update-in-place pattern donation exists for."""
    params = {
        a.arg for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    }
    reassigned: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            if node.id in params:
                reassigned.add(node.id)
    if not reassigned:
        return set()
    returned: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            # only DIRECT returns (`return params` / `return params, v`) —
            # a param passed as an argument in the return expression is
            # being consumed, not threaded
            elts = (
                node.value.elts
                if isinstance(node.value, ast.Tuple)
                else [node.value]
            )
            for sub in elts:
                if isinstance(sub, ast.Name) and sub.id in reassigned:
                    returned.add(sub.id)
    return returned
