"""graftcheck CLI: ``python -m fraud_detection_tpu.analysis`` (also installed
as the ``graftcheck`` console script).

Exit status 0 ⇔ the tree is clean modulo the checked-in baseline AND every
registered jit entrypoint shape-verifies at every virtual mesh size. CI runs
exactly this on every push; the gate test runs the same passes in-process.
"""

from __future__ import annotations

import argparse
import os
import sys


def _ensure_virtual_devices() -> None:
    """The mesh verifier needs 8 virtual CPU devices; both env vars must be
    set before jax initializes its backend (same dance as tests/conftest)."""
    if "jax" in sys.modules:
        return  # too late to influence backend init; verifier will report
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="JAX-aware static analysis + virtual-mesh shape verification",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the fraud_detection_tpu package)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--baseline", default=None,
        help="baseline suppression file (default: analysis_baseline.json "
        "next to the package)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="accept all current findings into the baseline file and exit 0",
    )
    ap.add_argument(
        "--no-shape-check", action="store_true",
        help="skip the virtual-mesh shape verification pass",
    )
    ap.add_argument(
        "--shape-check-only", action="store_true",
        help="run only the virtual-mesh shape verification pass",
    )
    ap.add_argument(
        "--contracts", action="store_true",
        help="also run the jaxpr contract prover (per-entrypoint collective/"
        "donation/dtype contracts) and the lock-order pass",
    )
    ap.add_argument(
        "--contracts-only", action="store_true",
        help="run only the contract prover + lock-order pass",
    )
    ap.add_argument(
        "--mesh-sizes", default=None,
        help="comma-separated mesh sizes for the verifier (default 1,2,8)",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--fail-on", default="info", choices=("info", "warning", "error"),
        help="minimum severity of NEW findings that fails the run",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument(
        "--output", default=None, help="write the report here as well as stdout"
    )
    args = ap.parse_args(argv)

    if args.contracts_only:
        args.contracts = True
        args.no_shape_check = True

    if not args.no_shape_check or args.shape_check_only or args.contracts:
        _ensure_virtual_devices()

    # Lint pass imports are pure-stdlib; meshcheck (imports jax + ops) is
    # deferred until we know the shape pass is wanted.
    from fraud_detection_tpu.analysis import baseline as baseline_mod
    from fraud_detection_tpu.analysis import report
    from fraud_detection_tpu.analysis.core import (
        Severity,
        analyze_paths,
        iter_rules,
    )

    if args.list_rules:
        for r in iter_rules():
            print(f"{r.id:24s} {r.severity.name.lower():8s} {r.description}")
        return 0

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    paths = args.paths or [pkg_dir]
    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.DEFAULT_BASELINE
    )

    rules = None
    if args.rules:
        wanted = {s.strip() for s in args.rules.split(",") if s.strip()}
        rules = [r for r in iter_rules() if r.id in wanted]
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    if args.write_baseline and (args.shape_check_only or args.contracts_only):
        print(
            "--write-baseline requires the lint pass; drop "
            "--shape-check-only/--contracts-only (writing here would wipe "
            "the baseline with an empty list)",
            file=sys.stderr,
        )
        return 2

    findings = (
        [] if args.shape_check_only or args.contracts_only
        else analyze_paths(paths, root=root, rules=rules)
    )

    if args.write_baseline:
        baseline_mod.save(baseline_path, findings)
        print(
            f"wrote {len(findings)} finding(s) to {baseline_path}",
            file=sys.stderr,
        )
        return 0

    result = baseline_mod.apply(findings, baseline_mod.load(baseline_path))

    mesh_results = None
    if not args.no_shape_check:
        from fraud_detection_tpu.analysis import meshcheck

        sizes = None
        if args.mesh_sizes:
            sizes = tuple(int(s) for s in args.mesh_sizes.split(","))
        mesh_results = meshcheck.verify_all(sizes)

    contract_results = contract_new = lock_report = lock_new = None
    if args.contracts:
        from fraud_detection_tpu.analysis import contracts, lockcheck

        contract_results = contracts.verify_contracts()
        contract_new, _ = baseline_mod.apply_keys(
            contracts.violation_keys(contract_results),
            baseline_mod.load_section(baseline_path, "contracts"),
        )
        lock_report = lockcheck.build_lock_report(root)
        lock_new, _ = baseline_mod.apply_keys(
            lockcheck.violation_keys(lock_report),
            baseline_mod.load_section(baseline_path, "lockcheck"),
        )

    if args.format == "json":
        out = report.render_json(
            result, mesh_results,
            contract_results=contract_results, contract_new=contract_new,
            lock_report=lock_report, lock_new=lock_new,
        )
    else:
        out = report.render_text(
            result, mesh_results, verbose=args.verbose,
            contract_results=contract_results, contract_new=contract_new,
            lock_report=lock_report, lock_new=lock_new,
        )
    print(out)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(out + "\n")
    return report.exit_code(
        result, mesh_results, fail_on=Severity.parse(args.fail_on),
        contract_new=contract_new, lock_new=lock_new,
    )


if __name__ == "__main__":
    sys.exit(main())
