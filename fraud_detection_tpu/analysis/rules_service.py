"""Service-tier lint rules: operational hazards in the network/threading code.

The store tier (netserver/sentinel/netclient) is hand-rolled sockets and
threads; these rules encode the review checklist that kept biting in chaos
testing — unbounded blocking I/O, exceptions swallowed without a trace, and
threads that can wedge interpreter shutdown.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fraud_detection_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Severity,
    dotted_name,
    register_rule,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _assign_target_name(mod: ModuleInfo, call: ast.Call) -> str | None:
    """Dotted name the call's result is bound to (``s = socket.socket(...)``
    → ``s``; ``self._sock = ...`` → ``self._sock``; ``conn, addr =
    sock.accept()`` → ``conn``)."""
    parent = mod.parents.get(call)
    if isinstance(parent, ast.withitem):
        var = parent.optional_vars
        return dotted_name(var) if var is not None else None
    if not isinstance(parent, ast.Assign) or len(parent.targets) != 1:
        return None
    target = parent.targets[0]
    if isinstance(target, ast.Tuple) and target.elts:
        return dotted_name(target.elts[0])
    return dotted_name(target)


def _settimeout_targets(mod: ModuleInfo) -> set[str]:
    """Every dotted name X in the module with an ``X.settimeout(...)`` call."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "settimeout"
        ):
            base = dotted_name(node.func.value)
            if base:
                out.add(base)
    return out


@register_rule(
    "socket-no-timeout",
    Severity.WARNING,
    "socket created or accepted without a timeout — a silently-dead peer "
    "blocks the calling thread until TCP gives up (~15 min) or forever",
)
def check_socket_timeout(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_socket_timeout.rule
    timeout_targets = _settimeout_targets(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        if callee == "socket.create_connection":
            # signature: create_connection(address, timeout=..., ...)
            has_timeout = len(node.args) >= 2 or any(
                kw.arg == "timeout" for kw in node.keywords
            )
            target = _assign_target_name(mod, node)
            if not has_timeout and (
                target is None or target not in timeout_targets
            ):
                yield mod.finding(
                    rule, node,
                    "socket.create_connection without a timeout — connect "
                    "can hang for the kernel default (minutes)",
                )
        elif callee == "socket.socket":
            target = _assign_target_name(mod, node)
            if target is None or target not in timeout_targets:
                yield mod.finding(
                    rule, node,
                    "socket.socket() whose handle never gets settimeout() — "
                    "blocking send/recv on it can wedge the thread",
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "accept"
            and not node.args
        ):
            target = _assign_target_name(mod, node)
            if target is None or target not in timeout_targets:
                yield mod.finding(
                    rule, node,
                    "accepted connection never gets settimeout() — a "
                    "stalled peer wedges this handler thread",
                )


_LOGGING_HINTS = ("log", "logger", "logging", "warn", "print_exc", "exception")


def _body_handles_error(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises or leaves a trace (logging call)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            head = callee.split(".")[0].lower()
            tail = callee.split(".")[-1].lower()
            if any(h in head for h in _LOGGING_HINTS) or any(
                h in tail for h in _LOGGING_HINTS
            ):
                return True
    return False


@register_rule(
    "silent-except",
    Severity.WARNING,
    "`except Exception:` (or bare except) that neither logs nor re-raises — "
    "swallows real faults invisibly; add debug logging or a "
    "`# graftcheck: ignore[silent-except]` tag after review",
)
def check_silent_except(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_silent_except.rule
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is not None:
            name = dotted_name(node.type)
            if name not in ("Exception", "BaseException"):
                continue  # narrowed handlers may legitimately stay quiet
        if not _body_handles_error(node):
            kind = (
                "bare except" if node.type is None else "except Exception"
            )
            yield mod.finding(
                rule, node,
                f"{kind} swallows the error without logging or re-raising",
            )


@register_rule(
    "thread-nondaemon-nojoin",
    Severity.WARNING,
    "non-daemon thread that is never joined — keeps the process alive after "
    "main exits; mark daemon=True or join it on shutdown",
)
def check_thread_daemon(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_thread_daemon.rule
    joined = _join_targets(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        if callee not in ("threading.Thread", "Thread"):
            continue
        daemon_kw = next(
            (kw for kw in node.keywords if kw.arg == "daemon"), None
        )
        if daemon_kw is not None and (
            not isinstance(daemon_kw.value, ast.Constant)
            or daemon_kw.value.value is True
        ):
            continue  # daemon=True (or dynamic — trust it)
        target = _assign_target_name(mod, node)
        if target is not None and target in joined:
            continue
        # `t.daemon = True` after construction also counts
        if target is not None and _daemon_attr_set(mod, target):
            continue
        yield mod.finding(
            rule, node,
            "threading.Thread without daemon=True and no matching join() — "
            "can block interpreter shutdown indefinitely",
        )


#: prometheus_client metric constructors the registry rule watches.
_PROM_METRIC_CLASSES = {
    "Counter", "Gauge", "Histogram", "Summary", "Info", "Enum",
}

#: the one module allowed to mint metrics on the shared service registry.
_CANONICAL_METRICS_MODULE = "service/metrics.py"


def _prometheus_bindings(mod: ModuleInfo) -> dict[str, str]:
    """Local name → prometheus_client class, for names bound via
    ``from prometheus_client import Counter [as C]``. Import-tracked so a
    ``collections.Counter`` can never false-positive."""
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.ImportFrom)
            and node.module == "prometheus_client"
        ):
            for alias in node.names:
                if alias.name in _PROM_METRIC_CLASSES:
                    out[alias.asname or alias.name] = alias.name
    return out


def _local_registry_names(mod: ModuleInfo) -> set[str]:
    """Names bound to a ``CollectorRegistry(...)`` call in this module —
    private registries are the sanctioned way to export metrics outside
    the shared-registry module (netserver's store gauges)."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and (dotted_name(node.func) or "").split(".")[-1]
            == "CollectorRegistry"
        ):
            target = _assign_target_name(mod, node)
            if target:
                out.add(target)
    return out


@register_rule(
    "prom-foreign-registry",
    Severity.WARNING,
    "prometheus metric constructed without registry= (the default REGISTRY "
    "double-registers under gunicorn/module re-import) or minted on the "
    "shared service registry outside service/metrics.py (the registry "
    "contract tests and alert-rule cross-checks only see metrics.py)",
)
def check_prom_foreign_registry(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_prom_foreign_registry.rule
    bindings = _prometheus_bindings(mod)
    local_registries = _local_registry_names(mod)
    is_canonical = mod.rel_path.replace("\\", "/").endswith(
        _CANONICAL_METRICS_MODULE
    )
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        if callee is None:
            continue
        if callee in bindings:
            cls = bindings[callee]
        elif (
            callee.startswith("prometheus_client.")
            and callee.split(".")[-1] in _PROM_METRIC_CLASSES
        ):
            cls = callee.split(".")[-1]
        else:
            continue
        registry_kw = next(
            (kw for kw in node.keywords if kw.arg == "registry"), None
        )
        if registry_kw is None:
            yield mod.finding(
                rule, node,
                f"{cls}() without registry= lands on the global default "
                "REGISTRY — duplicate-metric crash on re-import and "
                "per-process double counting under gunicorn; pass an "
                "explicit registry",
            )
            continue
        if is_canonical:
            continue
        reg_name = dotted_name(registry_kw.value) or ""
        if reg_name in local_registries:
            continue  # module-private CollectorRegistry: sanctioned
        yield mod.finding(
            rule, node,
            f"{cls}(registry={reg_name or '...'}) minted outside "
            "service/metrics.py — shared-registry metrics must be declared "
            "there (the alerting-contract tests and /metrics exposition "
            "only audit that module), or use a module-local "
            "CollectorRegistry",
        )


def _module_constants(mod: ModuleInfo) -> set[str]:
    """Module-level names bound (once) to a numeric literal — the
    ``RETRY_DELAY = 5.0`` pattern a constant-backoff loop sleeps on."""
    consts: dict[str, int] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (
                isinstance(t, ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, (int, float))
            ):
                consts[t.id] = consts.get(t.id, 0) + 1
    return {name for name, n in consts.items() if n == 1}


def _is_constant_delay(arg: ast.AST, module_consts: set[str]) -> bool:
    """True when a sleep argument provably evaluates to the same number on
    every iteration: a literal, a module-level numeric constant, or a
    unary +/- of one. Anything referencing loop state (``2 ** attempt``),
    calls (``random()``, ``min(...)``), or unknown names is treated as a
    real backoff — the rule must not guess."""
    if isinstance(arg, ast.Constant):
        return isinstance(arg.value, (int, float))
    if isinstance(arg, ast.UnaryOp) and isinstance(
        arg.op, (ast.USub, ast.UAdd)
    ):
        return _is_constant_delay(arg.operand, module_consts)
    if isinstance(arg, ast.Name):
        return arg.id in module_consts
    return False


def _walk_skip_nested_funcs(node: ast.AST):
    """Walk a loop body without descending into nested function defs —
    a closure defined inside the loop runs on its own schedule."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if not isinstance(cur, _FuncDef):
            stack.extend(ast.iter_child_nodes(cur))


def _sleep_names(mod: ModuleInfo) -> set[str]:
    """Dotted callee names that mean ``time.sleep`` in this module
    (``time.sleep`` itself plus ``from time import sleep [as s]``)."""
    names = {"time.sleep"}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    names.add(alias.asname or alias.name)
    return names


@register_rule(
    "retry-no-backoff",
    Severity.WARNING,
    "retry loop sleeping a constant (or zero) delay — every client retries "
    "in lockstep and hammers the failing dependency exactly when it is "
    "least able to answer; use bounded exponential backoff with jitter "
    "(the netclient.call pattern)",
)
def check_retry_no_backoff(mod: ModuleInfo) -> Iterator[Finding]:
    """A *retry* loop is a for/while whose body handles exceptions (the
    try/except-continue idiom); a constant ``time.sleep`` inside one never
    backs off. Poll/serve loops without exception handling are exempt —
    waking every N seconds to check a queue is a schedule, not a retry."""
    rule = check_retry_no_backoff.rule
    module_consts = _module_constants(mod)
    sleep_names = _sleep_names(mod)
    flagged: set[int] = set()  # id() — nested loops walk shared subtrees
    for loop in ast.walk(mod.tree):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        body_nodes = list(_walk_skip_nested_funcs(loop))
        if not any(isinstance(n, ast.ExceptHandler) for n in body_nodes):
            continue  # not a retry loop
        for node in body_nodes:
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if id(node) in flagged:
                continue
            if dotted_name(node.func) not in sleep_names:
                continue
            if _is_constant_delay(node.args[0], module_consts):
                flagged.add(id(node))
                yield mod.finding(
                    rule, node,
                    "retry loop sleeps a constant delay — no exponential "
                    "backoff, no jitter; a dependency outage gets hammered "
                    "at a fixed frequency by every replica at once",
                )


def _join_targets(mod: ModuleInfo) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
        ):
            base = dotted_name(node.func.value)
            if base:
                out.add(base)
    return out


def _daemon_attr_set(mod: ModuleInfo, target: str) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (
                isinstance(t, ast.Attribute)
                and t.attr == "daemon"
                and dotted_name(t.value) == target
                and isinstance(node.value, ast.Constant)
                and node.value.value is True
            ):
                return True
    return False
