"""Pass 4: static lock-order + lock-hygiene analysis over the named locks.

Three checks ride the :mod:`.locknames` inventory:

1. **Acquisition-order graph** (:func:`build_lock_report`): every
   ``with <lock>:`` site in the package is resolved to a canonical lock
   name; directly nested acquisitions record an order edge, and a one-hop
   interprocedural closure adds edges for calls made while a lock is held
   to functions that themselves acquire a named lock (``take_snapshot``
   holding ``lifeboat.flush`` calls ``journal.rotate`` which takes
   ``lifeboat.journal`` → edge ``lifeboat.flush → lifeboat.journal``).
   A cycle in the graph is an ABBA deadlock waiting for timing; the gate
   requires the graph acyclic. The runtime witness
   (:mod:`fraud_detection_tpu.utils.lockdep`) checks the same property on
   *executed* orders — static for coverage, dynamic for call-chains deeper
   than one hop.

2. **Inventory drift**: every ``lockdep.lock("name")`` /
   ``lockdep.rlock("name")`` creation site must have a matching
   :class:`~fraud_detection_tpu.analysis.locknames.LockDecl` (same module,
   same kind), and every declaration must have a creation site. The
   inventory the docs render and the witness instruments cannot rot.

3. **graftcheck rules** (per-module, baseline/suppression discipline):

   - ``blocking-under-lock``: a blocking operation (fsync, socket I/O,
     sleep, device sync, future.result) — or a call to a same-module
     function that performs one — inside a held named-lock region. Every
     occurrence is either a bug or a reviewed design point carrying a
     ``# graftcheck: ignore[blocking-under-lock]`` sanction (the journal's
     group-commit fsync under its own lock is the canonical sanction).
   - ``lock-in-jit``: threading primitives referenced inside a
     jit-compiled function body — locks don't trace; at best they run at
     trace time (once), at worst they capture a tracer.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from fraud_detection_tpu.analysis import locknames
from fraud_detection_tpu.analysis.core import (
    ModuleInfo,
    Severity,
    dotted_name,
    iter_python_files,
    register_rule,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

#: dotted-name suffixes that block the calling thread. Deliberately narrow:
#: every entry is unambiguous enough that a hit under a held lock is worth
#: a human decision (fix or sanction) — no ``.join`` (str.join) or broad
#: "I/O-ish" names.
BLOCKING_SUFFIXES: frozenset[str] = frozenset({
    "os.fsync",
    "os.fdatasync",
    "time.sleep",
    ".sendall",
    ".recv",
    ".recv_into",
    ".accept",
    ".connect",
    ".block_until_ready",
    "jax.block_until_ready",
    "jax.device_get",
    ".result",
})

#: method names too generic to resolve across modules without a receiver
#: hint (``rows.append`` must not resolve to ``Journal.append``)
_COMMON_METHODS: frozenset[str] = frozenset({
    "append", "close", "flush", "sync", "get", "put", "update", "stats",
    "write", "read", "pop", "add", "remove", "clear", "reset", "start",
    "stop", "run", "send",
})

_THREADING_PRIMITIVES: frozenset[str] = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "lockdep.lock", "lockdep.rlock",
})


def _hint_matches(hint: str, cls: str) -> bool:
    """Receiver-name ↔ class-name affinity: ``boat`` ↔ ``Lifeboat``,
    ``drift`` ↔ ``DriftMonitor``, ``journal`` ↔ ``Journal``. Receivers
    shorter than 3 chars (``self._f``, loop vars) carry no type evidence
    and never match — a one-letter handle must not resolve to a lock
    owner just because the letter occurs in some class name."""
    h, c = hint.lower().lstrip("_"), cls.lower()
    return len(h) >= 3 and (h in c or c in h)


# --------------------------------------------------------------------------
# Lock-name resolution
# --------------------------------------------------------------------------


class _ClassMap:
    """class name → base-class names, per module (names, not objects — a
    subclass in another module names its base textually, which is all the
    resolver needs: ``MeshDriftMonitor(DriftMonitor)`` inherits the
    ``drift.window`` binding)."""

    def __init__(self):
        self.bases: dict[str, set[str]] = {}

    def add_module(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                names = set()
                for b in node.bases:
                    dn = dotted_name(b)
                    if dn:
                        names.add(dn.split(".")[-1])
                self.bases.setdefault(node.name, set()).update(names)

    def is_a(self, cls: str, base: str) -> bool:
        seen: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c == base:
                return True
            if c in seen:
                continue
            seen.add(c)
            stack.extend(self.bases.get(c, ()))
        return False


def resolve_lock_name(
    expr: ast.AST, enclosing_cls: str | None, classes: _ClassMap
) -> str | None:
    """Canonical lock name for a ``with <expr>:`` context item, or None
    when the expression is not (recognizably) a named lock."""
    dn = dotted_name(expr)
    if dn is None:
        return None
    parts = dn.split(".")
    attr = parts[-1]
    decls = locknames.by_attr().get(attr)
    if not decls:
        return None
    # self.<attr> — the owning class (or a subclass of it) declares it
    if parts[:-1] == ["self"] and enclosing_cls is not None:
        for d in decls:
            if d.cls and classes.is_a(enclosing_cls, d.cls):
                return d.name
    # unique attribute name repo-wide (flush_lock, _retrain_lock, ...)
    if len(decls) == 1:
        return decls[0].name
    # receiver hint: boat.flush_lock / self.pool._lock / journal._lock
    if len(parts) >= 2 and parts[-2] != "self":
        for d in decls:
            if d.cls and _hint_matches(parts[-2], d.cls):
                return d.name
    return None


# --------------------------------------------------------------------------
# Package index: every function, its acquisitions, its blocking ops
# --------------------------------------------------------------------------


@dataclass
class _Func:
    module: str  # repo-relative path
    cls: str | None
    name: str
    node: ast.AST
    #: named locks this function acquires anywhere in its own body
    acquires: list[tuple[str, int]] = field(default_factory=list)
    #: (held-lock names at that point, order edges, calls-under-lock)
    edges: list[tuple[str, str, int]] = field(default_factory=list)
    calls_under: list[tuple[str, ast.Call]] = field(default_factory=list)


def _walk_function(fn: _Func, classes: _ClassMap) -> None:
    """Single pass over one function body tracking the held-lock stack;
    nested function defs get their own _Func and are skipped here."""

    def visit(node: ast.AST, held: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (*_FuncDef, ast.Lambda)):
                continue  # nested def: analyzed as its own function
            if isinstance(child, (ast.With, ast.AsyncWith)):
                names = []
                for item in child.items:
                    ln = resolve_lock_name(
                        item.context_expr, fn.cls, classes
                    )
                    if ln is not None:
                        names.append(ln)
                for ln in names:
                    fn.acquires.append((ln, child.lineno))
                    for h in held:
                        if h != ln:
                            fn.edges.append((h, ln, child.lineno))
                visit(child, held + tuple(names))
                continue
            if isinstance(child, ast.Call) and held:
                fn.calls_under.append((held[-1], child))
            visit(child, held)

    visit(fn.node, ())


def _index_package(
    package_dir: str, root: str
) -> tuple[list[_Func], _ClassMap, list[dict]]:
    classes = _ClassMap()
    funcs: list[_Func] = []
    creation_sites: list[dict] = []
    trees: list[tuple[str, ast.AST]] = []
    # excludes=(): the only caller-visible roots are the package dir (no
    # fixture paths inside) and explicit fixture files in tests
    for path in iter_python_files([package_dir], excludes=()):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue  # graftcheck: ignore[silent-except] — syntax errors are rule findings, not lockcheck's job
        trees.append((rel, tree))
        classes.add_module(tree)
    for rel, tree in trees:
        parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn in ("lockdep.lock", "lockdep.rlock") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ):
                        creation_sites.append({
                            "name": arg.value,
                            "module": rel,
                            "kind": "rlock" if dn.endswith("rlock") else "lock",
                            "line": node.lineno,
                        })
            if not isinstance(node, _FuncDef):
                continue
            cls = None
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    cls = cur.name
                    break
                cur = parents.get(cur)
            funcs.append(_Func(module=rel, cls=cls, name=node.name, node=node))
    for fn in funcs:
        _walk_function(fn, classes)
    return funcs, classes, creation_sites


def _callee_candidates(
    call: ast.Call, caller: _Func, funcs_by_name: dict[str, list[_Func]],
    classes: _ClassMap,
) -> list[_Func]:
    dn = dotted_name(call.func)
    if dn is None:
        return []
    parts = dn.split(".")
    name = parts[-1]
    cands = [f for f in funcs_by_name.get(name, []) if f.acquires]
    if not cands:
        return []
    if len(parts) == 1:
        # bare call: same-module function (module-level or same class)
        return [
            f for f in cands
            if f.module == caller.module and f.cls in (None, caller.cls)
        ]
    recv = parts[-2]
    if recv == "self" and len(parts) == 2 and caller.cls is not None:
        return [
            f for f in cands
            if f.cls and (
                classes.is_a(caller.cls, f.cls)
                or classes.is_a(f.cls, caller.cls)
            )
        ]
    # attribute call on another object: require receiver-name affinity,
    # always for _COMMON_METHODS, and even for rarer names (cheap and
    # kills false edges from coincidental method names)
    return [f for f in cands if f.cls and _hint_matches(recv, f.cls)]


# --------------------------------------------------------------------------
# The report
# --------------------------------------------------------------------------


def _find_cycles(edges: dict[tuple[str, str], list[str]]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: list[list[str]] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(node: str, stack: list[str], on_stack: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                # canonicalize rotation so each cycle reports once
                body = cyc[:-1]
                i = body.index(min(body))
                canon = tuple(body[i:] + body[:i])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(canon) + [canon[0]])
                continue
            if any(nxt == s for s in stack):
                continue
            stack.append(nxt)
            on_stack.add(nxt)
            dfs(nxt, stack, on_stack)
            on_stack.discard(nxt)
            stack.pop()

    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


def _check_inventory(creation_sites: list[dict]) -> list[dict]:
    drift: list[dict] = []
    decls = locknames.by_name()
    seen: dict[str, dict] = {}
    for site in creation_sites:
        d = decls.get(site["name"])
        if d is None:
            drift.append({
                "diagnostic": "undeclared-lock",
                "detail": f"{site['module']}:{site['line']} creates "
                f"lockdep.{site['kind']}({site['name']!r}) with no "
                f"LockDecl in analysis/locknames.py",
            })
            continue
        if d.module != site["module"] or d.kind != site["kind"]:
            drift.append({
                "diagnostic": "lock-inventory-drift",
                "detail": f"{site['name']!r} declared as {d.kind} in "
                f"{d.module} but created as {site['kind']} in "
                f"{site['module']}:{site['line']}",
            })
        seen[site["name"]] = site
    for name, d in decls.items():
        if name not in seen:
            drift.append({
                "diagnostic": "lock-inventory-drift",
                "detail": f"{name!r} declared in locknames.py but no "
                f"lockdep.{d.kind}({name!r}) creation site exists "
                f"(expected in {d.module})",
            })
    return drift


def build_edges(
    funcs: list[_Func], classes: _ClassMap
) -> dict[tuple[str, str], list[str]]:
    """(src, dst) → example sites, from direct nesting plus the one-hop
    interprocedural closure over calls made while a lock is held."""
    funcs_by_name: dict[str, list[_Func]] = {}
    for f in funcs:
        funcs_by_name.setdefault(f.name, []).append(f)

    edges: dict[tuple[str, str], list[str]] = {}

    def add_edge(a: str, b: str, site: str) -> None:
        if a == b:
            return
        edges.setdefault((a, b), [])
        if len(edges[(a, b)]) < 4 and site not in edges[(a, b)]:
            edges[(a, b)].append(site)

    for fn in funcs:
        where = f"{fn.module}:{fn.cls + '.' if fn.cls else ''}{fn.name}"
        for a, b, line in fn.edges:
            add_edge(a, b, f"{where}:{line} (nested with)")
        for held, call in fn.calls_under:
            for cand in _callee_candidates(call, fn, funcs_by_name, classes):
                for acq, _line in cand.acquires:
                    add_edge(
                        held, acq,
                        f"{where}:{call.lineno} -> "
                        f"{cand.cls + '.' if cand.cls else ''}{cand.name}",
                    )
    return edges


def build_lock_report(
    root: str | None = None, package_dir: str | None = None
) -> dict:
    """The whole-package lock-order report: edges (with sites), cycles,
    inventory drift, and the lock inventory itself. ``package_dir``
    overrides the scanned tree (fixture tests); inventory drift is only
    meaningful for the real package and is skipped for overrides."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
    is_fixture = package_dir is not None
    if package_dir is None:
        package_dir = os.path.join(root, "fraud_detection_tpu")
    funcs, classes, creation_sites = _index_package(package_dir, root)
    edges = build_edges(funcs, classes)
    cycles = _find_cycles(edges)
    drift = [] if is_fixture else _check_inventory(creation_sites)
    return {
        "locks": [
            {
                "name": d.name, "module": d.module, "cls": d.cls,
                "attr": d.attr, "kind": d.kind, "purpose": d.purpose,
            }
            for d in locknames.LOCKS
        ],
        "edges": [
            {"src": a, "dst": b, "sites": sites}
            for (a, b), sites in sorted(edges.items())
        ],
        "cycles": [" -> ".join(c) for c in cycles],
        "inventory_drift": drift,
        "ok": not cycles and not drift,
    }


def violation_keys(report: dict) -> list[str]:
    """Stable baseline keys: one per cycle, one per drift entry."""
    keys = [f"lock-cycle:{c}" for c in report["cycles"]]
    keys.extend(
        f"{d['diagnostic']}:{d['detail'].split(' ', 1)[0]}"
        for d in report["inventory_drift"]
    )
    return keys


# --------------------------------------------------------------------------
# graftcheck rules (per-module; suppressions + baseline apply)
# --------------------------------------------------------------------------


def _module_classes(mod: ModuleInfo) -> _ClassMap:
    cm = _ClassMap()
    cm.add_module(mod.tree)
    return cm


def _blocking_call(node: ast.Call) -> str | None:
    dn = dotted_name(node.func)
    if dn is None:
        return None
    for suffix in BLOCKING_SUFFIXES:
        if suffix.startswith("."):
            if dn.endswith(suffix) and dn != suffix.lstrip("."):
                return dn
        elif dn == suffix or dn.endswith("." + suffix):
            return dn
    return None


def _directly_blocking_functions(mod: ModuleInfo) -> dict[str, str]:
    """function name -> the blocking op it performs (same-module one-hop
    closure for blocking-under-lock: ``_sync_locked`` fsyncs, so calling
    it under a lock is flagged at the call site)."""
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, _FuncDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                op = _blocking_call(sub)
                if op is not None:
                    out[node.name] = op
                    break
    return out


@register_rule(
    "blocking-under-lock",
    Severity.WARNING,
    "blocking operation (fsync/socket/sleep/device-sync) while holding a "
    "named lock — every hit is a latency cliff for every other thread "
    "queued on that lock; fix it or sanction it with an ignore tag",
)
def check_blocking_under_lock(mod: ModuleInfo):
    classes = _module_classes(mod)
    blocking_fns = _directly_blocking_functions(mod)

    def enclosing_class(node: ast.AST) -> str | None:
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = mod.parents.get(cur)
        return None

    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        held = None
        for item in node.items:
            held = resolve_lock_name(
                item.context_expr, enclosing_class(node), classes
            )
            if held is not None:
                break
        if held is None:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, _FuncDef):
                continue  # a def under a lock doesn't run under it
            if not isinstance(sub, ast.Call):
                continue
            op = _blocking_call(sub)
            if op is not None:
                yield mod.finding(
                    check_blocking_under_lock.rule, sub,
                    f"{op}() while holding {held!r}",
                )
                continue
            dn = dotted_name(sub.func)
            if dn is None:
                continue
            callee = dn.split(".")[-1]
            via = blocking_fns.get(callee)
            if via is not None and dn in (callee, f"self.{callee}"):
                yield mod.finding(
                    check_blocking_under_lock.rule, sub,
                    f"{callee}() blocks ({via}) and is called while "
                    f"holding {held!r}",
                )


@register_rule(
    "lock-in-jit",
    Severity.ERROR,
    "threading primitive inside a jit-compiled function — locks don't "
    "trace: at best they fire once at trace time, at worst they capture "
    "trace-time state into the compiled program",
)
def check_lock_in_jit(mod: ModuleInfo):
    classes = _module_classes(mod)
    for node in ast.walk(mod.tree):
        if not mod.in_jit_context(node):
            continue
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn in _THREADING_PRIMITIVES or (
                dn is not None
                and dn.split(".")[0] == "threading"
                and len(dn.split(".")) == 2
            ):
                yield mod.finding(
                    check_lock_in_jit.rule, node,
                    f"{dn}() created inside a traced body",
                )
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ln = resolve_lock_name(item.context_expr, None, classes)
                if ln is not None:
                    yield mod.finding(
                        check_lock_in_jit.rule, node,
                        f"named lock {ln!r} acquired inside a traced body "
                        "(runs at trace time, not per call)",
                    )
