"""Pass 2: virtual-mesh shape verification of the jitted entrypoints.

Every numerics entrypoint the service tier dispatches to (scorer, train
steps, SMOTE, SHAP, scaler) is registered here with a builder that produces
``(fn, abstract_args)`` for a given mesh. The verifier abstractly evaluates
each one with ``jax.eval_shape`` under CPU meshes of sizes 1, 2 and 8 —
built over *subsets* of the virtual host devices, so a single process
proves that shapes and named shardings compose at every mesh size without
TPU hardware:

- ``shard_map`` entrypoints (SGD epoch, GBT boost) check mesh-divisibility
  and replication claims at trace time — the exact errors that otherwise
  only surface on a real pod topology;
- ``NamedSharding`` inputs are additionally pre-checked for axis-rank and
  divisibility against the mesh (:func:`_check_sharding`), catching
  mismatches jit would defer to compile time;
- abstract evaluation never runs the program, so the whole matrix
  (entrypoints × mesh sizes) completes in seconds on CPU.

Registering a new entrypoint is one decorated builder (see
``docs/STATIC_ANALYSIS.md``); the gate test and CI then verify it at every
mesh size forever.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fraud_detection_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshSpec,
    create_mesh,
)

DEFAULT_MESH_SIZES = (1, 2, 8)

#: 2-D (data × model) factorizations the broadside entrypoints are proven
#: on — non-trivial model axes up to the 8 virtual devices, including both
#: orientations of the full grid.
WIDE_MESH_SHAPES = ((1, 1), (2, 2), (4, 2), (2, 4))

#: batch row count used by the abstract inputs — divisible by every mesh
#: size under test (and by the SGD batch below at every size).
_ROWS = 1024
_FEATURES = 30  # the Kaggle credit-card schema the whole repo is built on


@dataclass(frozen=True)
class Entrypoint:
    name: str
    build: Callable[[Mesh], tuple[Callable, tuple]] = field(repr=False)
    mesh_sizes: tuple[int, ...] = DEFAULT_MESH_SIZES


_ENTRYPOINTS: dict[str, Entrypoint] = {}


def register_entrypoint(name: str, mesh_sizes: tuple[int, ...] = DEFAULT_MESH_SIZES):
    """Decorator: register ``build(mesh) -> (fn, args)`` under ``name``."""

    def deco(build):
        if name in _ENTRYPOINTS:
            raise ValueError(f"duplicate entrypoint {name!r}")
        _ENTRYPOINTS[name] = Entrypoint(
            name=name, build=build, mesh_sizes=mesh_sizes
        )
        return build

    return deco


def iter_entrypoints() -> list[Entrypoint]:
    return list(_ENTRYPOINTS.values())


def sds(
    shape: tuple[int, ...],
    dtype=jnp.float32,
    mesh: Mesh | None = None,
    spec: P | None = None,
) -> jax.ShapeDtypeStruct:
    """Abstract array; with ``mesh`` + ``spec`` it carries a NamedSharding."""
    if mesh is not None:
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, spec if spec is not None else P())
        )
    return jax.ShapeDtypeStruct(shape, dtype)


def _check_sharding(args, mesh: Mesh) -> None:
    """Pre-flight NamedSharding validation jit would defer to compile time:
    spec rank must fit the array rank, and every sharded dimension must
    divide by its mesh-axis size."""
    for leaf in jax.tree_util.tree_leaves(args):
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            continue
        spec = sharding.spec
        if len(spec) > len(leaf.shape):
            raise ValueError(
                f"PartitionSpec {spec} has more axes than array rank "
                f"{len(leaf.shape)} (shape {leaf.shape})"
            )
        for dim, axes in enumerate(spec):
            if axes is None:
                continue
            names = axes if isinstance(axes, tuple) else (axes,)
            div = 1
            for ax in names:
                div *= mesh.shape[ax]
            if leaf.shape[dim] % div != 0:
                raise ValueError(
                    f"dimension {dim} of shape {leaf.shape} not divisible "
                    f"by mesh axes {names} (size {div}) on mesh "
                    f"{dict(mesh.shape)}"
                )


def _out_summary(out) -> str:
    leaves = jax.tree_util.tree_leaves(out)
    return ", ".join(
        f"{tuple(l.shape)}:{jnp.dtype(l.dtype).name}" for l in leaves[:8]
    ) + ("..." if len(leaves) > 8 else "")


def verify_entrypoint(ep: Entrypoint, sizes: Iterable | None = None) -> list[dict]:
    results = []
    for size in sizes if sizes is not None else ep.mesh_sizes:
        # a mesh size is an int (1-D data mesh, the historical contract)
        # or a (data, model) tuple — the broadside 2-D factorizations
        if isinstance(size, tuple):
            d_ax, m_ax = size
            label: int | str = f"{d_ax}x{m_ax}"
        else:
            d_ax, m_ax = size, 1
            label = size
        total = d_ax * m_ax
        res = {"entrypoint": ep.name, "mesh_size": label, "ok": False,
               "error": None, "out": None}
        try:
            devices = jax.devices()
            if len(devices) < total:
                raise RuntimeError(
                    f"need {total} devices, have {len(devices)} — run under "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=8"
                )
            mesh = create_mesh(
                MeshSpec(data=d_ax, model=m_ax), devices=devices[:total]
            )
            fn, args = ep.build(mesh)
            _check_sharding(args, mesh)
            out = jax.eval_shape(fn, *args)
            res["ok"] = True
            res["out"] = _out_summary(out)
        except Exception as e:  # graftcheck: ignore[silent-except] — error is the result (reported + gates CI)
            res["error"] = f"{type(e).__name__}: {e}"
        results.append(res)
    return results


def verify_all(sizes: Iterable[int] | None = None) -> list[dict]:
    out: list[dict] = []
    for ep in iter_entrypoints():
        out.extend(verify_entrypoint(ep, sizes))
    return out


# --------------------------------------------------------------------------
# Registered entrypoints — the programs the service tier actually dispatches
# --------------------------------------------------------------------------


@register_entrypoint("scorer.score")
def _build_scorer(mesh: Mesh):
    from fraud_detection_tpu.ops.scorer import _score

    coef = sds((_FEATURES,), jnp.float32, mesh, P())
    intercept = sds((), jnp.float32, mesh, P())
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    return (lambda c, i, xx: _score(c, i, xx)), (coef, intercept, x)


@register_entrypoint("telemetry.instrumented_score")
def _build_instrumented_scorer(mesh: Mesh):
    """The scorer as serving actually dispatches it once the compile
    sentinel is installed: proves the instrumentation wrapper is
    transparent to abstract evaluation (and therefore to tracing/sharding)
    at every mesh size — a sentinel that broke eval_shape would also break
    jit tracing in production."""
    from fraud_detection_tpu.ops.scorer import _score
    from fraud_detection_tpu.telemetry.compile_sentinel import instrument

    wrapped = instrument("meshcheck.scorer", _score)
    coef = sds((_FEATURES,), jnp.float32, mesh, P())
    intercept = sds((), jnp.float32, mesh, P())
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    return (lambda c, i, xx: wrapped(c, i, xx)), (coef, intercept, x)


@register_entrypoint("logistic.lbfgs_fit")
def _build_lbfgs(mesh: Mesh):
    from fraud_detection_tpu.ops.logistic import LogisticParams, _fit_lbfgs

    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    y = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    sw = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    init = LogisticParams(  # warm-start seed (replicated, like the output)
        coef=sds((_FEATURES,), jnp.float32, mesh, P()),
        intercept=sds((), jnp.float32, mesh, P()),
    )
    return (
        lambda xx, yy, ss, ii: _fit_lbfgs(xx, yy, ss, ii, 1.0, 5, 1e-4),
        (x, y, sw, init),
    )


@register_entrypoint("logistic.sgd_epoch")
def _build_sgd_epoch(mesh: Mesh):
    from fraud_detection_tpu.ops.logistic import LogisticParams, _sharded_epoch

    size = mesh.shape[DATA_AXIS]
    batch = 64  # divides the per-device shard at every registered mesh size
    fn = _sharded_epoch(mesh, 1.0, _ROWS, 0.9, batch)
    params = LogisticParams(
        coef=sds((_FEATURES,), jnp.float32, mesh, P()),
        intercept=sds((), jnp.float32, mesh, P()),
    )
    velocity = LogisticParams(
        coef=sds((_FEATURES,), jnp.float32, mesh, P()),
        intercept=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    y_pm = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    sw = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    perm = sds((_ROWS // size,), jnp.int32, mesh, P())
    lr = sds((), jnp.float32, mesh, P())
    return fn, (params, velocity, x, y_pm, sw, valid, perm, lr)


@register_entrypoint("gbt.boost_step")
def _build_gbt_boost(mesh: Mesh):
    from fraud_detection_tpu.ops.gbt import GBTConfig, _sharded_boost

    cfg = GBTConfig(n_trees=4, max_depth=3, n_bins=16)
    # segment histograms: the CPU impl — the sharded program structure
    # (psum'd histograms, replicated trees out) is impl-independent
    fn = _sharded_boost(mesh, cfg, "segment")
    binned = sds((_ROWS, _FEATURES), jnp.uint8, mesh, P(DATA_AXIS))
    y = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    w = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    base_logit = sds((), jnp.float32, mesh, P())
    return fn, (binned, y, w, base_logit)


def _abstract_gbt_model(mesh: Mesh, n_trees: int = 4, depth: int = 3,
                        n_bins: int = 16):
    from fraud_detection_tpu.ops.gbt import GBTModel

    n_nodes = 2**depth - 1
    n_leaves = 2**depth
    return GBTModel(
        split_feature=sds((n_trees, n_nodes), jnp.int32, mesh, P()),
        split_bin=sds((n_trees, n_nodes), jnp.int32, mesh, P()),
        leaf_value=sds((n_trees, n_leaves), jnp.float32, mesh, P()),
        bin_edges=sds((_FEATURES, n_bins - 1), jnp.float32, mesh, P()),
        base_logit=sds((), jnp.float32, mesh, P()),
    )


@register_entrypoint("gbt.predict_proba")
def _build_gbt_predict(mesh: Mesh):
    from fraud_detection_tpu.ops.gbt import gbt_predict_proba

    model = _abstract_gbt_model(mesh)
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    return gbt_predict_proba, (model, x)


@register_entrypoint("smote.oversample")
def _build_smote(mesh: Mesh):
    from fraud_detection_tpu.ops.smote import _smote_device

    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    y = sds((_ROWS,), jnp.int32, mesh, P(DATA_AXIS))
    key = sds((2,), jnp.uint32, mesh, P())
    fn = lambda xx, yy, kk: _smote_device(  # noqa: E731
        xx, yy, kk, minority=1, n_min=64, n_synth=512, k=5,
        use_pallas=False, block=64,
    )
    return fn, (x, y, key)


@register_entrypoint("linear_shap.batch")
def _build_linear_shap(mesh: Mesh):
    from fraud_detection_tpu.ops.linear_shap import (
        LinearShapExplainer,
        linear_shap,
    )

    explainer = LinearShapExplainer(
        coef=sds((_FEATURES,), jnp.float32, mesh, P()),
        background_mean=sds((_FEATURES,), jnp.float32, mesh, P()),
        expected_value=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    return linear_shap, (explainer, x)


@register_entrypoint("tree_shap.batch")
def _build_tree_shap(mesh: Mesh):
    from fraud_detection_tpu.ops.tree_shap import TreeShapExplainer, tree_shap

    depth = 3
    n_leaves = 2**depth
    explainer = TreeShapExplainer(
        model=_abstract_gbt_model(mesh, depth=depth),
        bg_table=sds((4, n_leaves, n_leaves), jnp.float32, mesh, P()),
        expected_value=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    return tree_shap, (explainer, x)


@register_entrypoint("watchtower.baseline_profile")
def _build_baseline_profile(mesh: Mesh):
    from fraud_detection_tpu.monitor.baseline import (
        N_FEATURE_BINS,
        N_SCORE_BINS,
        _profile,
    )

    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    scores = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    return _profile, (x, scores, feature_edges, score_edges)


@register_entrypoint("watchtower.window_update")
def _build_window_update(mesh: Mesh):
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _window_update,
    )

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    per_row = lambda: sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))  # noqa: E731
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    calib_edges = sds((N_CALIB_BINS - 1,), jnp.float32, mesh, P())
    return _window_update, (
        window, x, per_row(), per_row(), per_row(), per_row(),
        decay, decay, feature_edges, score_edges, calib_edges,
    )


@register_entrypoint("fastlane.flush")
def _build_fastlane_flush(mesh: Mesh):
    """The fused single-dispatch flush program (scores + drift-window fold,
    window donated through): the serving hot path once a watchtower is
    attached, so its shapes/shardings must compose at every mesh size."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _fused_flush,
    )
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    fn = lambda w, xx, vv, dd, fe, se, sa: _fused_flush(  # noqa: E731
        w, xx, vv, dd, fe, se, sa, score_fn=_raw_score_linear
    )
    return fn, (window, x, valid, decay, feature_edges, score_edges, score_args)


@register_entrypoint("quickwire.flush")
def _build_quickwire_flush(mesh: Mesh):
    """The fused dequant·score·drift program (quickwire): int8 wire codes
    in, per-feature dequant scale traced through to the drift histograms,
    uint8 score codes out (the compressed d2h return wire) — the quantized
    serving hot path, proven at every mesh size like ``fastlane.flush``."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _fused_flush_quant,
    )
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.int8, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    dq = sds((_FEATURES,), jnp.float32, mesh, P())
    fn = lambda w, xx, vv, dd, fe, se, sa, qs: _fused_flush_quant(  # noqa: E731
        w, xx, vv, dd, fe, se, sa, qs,
        score_fn=_raw_score_linear, score_codes=True, out_dtype=jnp.uint8,
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args, dq,
    )


@register_entrypoint("lantern.flush")
def _build_lantern_flush(mesh: Mesh):
    """The fused score+explain flush (lantern): scores, per-row top-k SHAP
    reason codes, AND the drift-window fold in ONE donated dispatch — the
    serving hot path once SCORER_EXPLAIN=topk, proven at every mesh size
    like ``fastlane.flush``."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _fused_flush_explain,
    )
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    explain_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((_FEATURES,), jnp.float32, mesh, P()),
    )
    fn = lambda w, xx, vv, dd, fe, se, sa, ea: _fused_flush_explain(  # noqa: E731
        w, xx, vv, dd, fe, se, sa, ea,
        score_fn=_raw_score_linear, explain_k=3,
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        explain_args,
    )


def _abstract_tree_explainer(mesh: Mesh, n_trees: int = 4, depth: int = 3,
                             n_bins: int = 16):
    from fraud_detection_tpu.ops.tree_shap import TreeShapExplainer

    n_leaves = 2**depth
    return TreeShapExplainer(
        model=_abstract_gbt_model(mesh, n_trees, depth, n_bins),
        bg_table=sds((n_trees, n_leaves, n_leaves), jnp.float32, mesh, P()),
        expected_value=sds((), jnp.float32, mesh, P()),
    )


@register_entrypoint("evergreen.flush")
def _build_evergreen_flush(mesh: Mesh):
    """The GBT family's fully-fused serving flush (evergreen): int8 wire
    codes dequantized in-program (explicit-dequant branch — the forest
    scores raw-space values), exact TreeSHAP top-k reason codes traced
    inline (``drift._topk_attributions`` family dispatch over the
    TreeShapExplainer pytree), uint8 return wire, drift fold donated
    through — the harshest wire/explain combo the GBT family serves,
    proven at every mesh size like the linear ``lantern.flush``."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _fused_flush_quant_explain,
    )
    from fraud_detection_tpu.ops.scorer import _raw_score_gbt

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.int8, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = _abstract_gbt_model(mesh)
    dq = sds((_FEATURES,), jnp.float32, mesh, P())
    explain_args = _abstract_tree_explainer(mesh)
    fn = lambda w, xx, vv, dd, fe, se, sa, qs, ea: (  # noqa: E731
        _fused_flush_quant_explain(
            w, xx, vv, dd, fe, se, sa, qs, ea,
            score_fn=_raw_score_gbt, score_codes=False, explain_k=3,
            out_dtype=jnp.uint8,
        )
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        dq, explain_args,
    )


# -- chisel: the TreeSHAP Pallas-kernel entrypoints -------------------------
# The same programs as tree_shap.batch / the GBT explain flushes, FORCED
# onto the chisel kernel body (``force_tree_shap_kernel`` is entered inside
# the returned fn, so it is live whenever the checker traces it — abstract,
# nothing executes; off-TPU the body traces in interpret mode). This proves
# the kernel path composes at every mesh size and lets its contract budget
# exactly one ``pallas_call`` with zero hot-path collectives — a gate
# regression that silently falls back to XLA fails as ``missing-pallas``.


@register_entrypoint("chisel.tree_shap")
def _build_chisel_tree_shap(mesh: Mesh):
    from fraud_detection_tpu.ops import pallas_kernels as pk
    from fraud_detection_tpu.ops.tree_shap import _raw_tree_shap

    explainer = _abstract_tree_explainer(mesh)
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))

    def fn(e, xx):
        with pk.force_tree_shap_kernel(True):
            return _raw_tree_shap(e.model, e.bg_table, xx)

    return fn, (explainer, x)


@register_entrypoint("chisel.lantern_flush")
def _build_chisel_lantern_flush(mesh: Mesh):
    """The GBT lantern flush (f32 wire, TreeSHAP reason codes) on the
    chisel kernel body — the serve-time program the kernel actually rides,
    wire and donation identical to ``lantern.flush``."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _fused_flush_explain,
    )
    from fraud_detection_tpu.ops import pallas_kernels as pk
    from fraud_detection_tpu.ops.scorer import _raw_score_gbt

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = _abstract_gbt_model(mesh)
    explain_args = _abstract_tree_explainer(mesh)

    # trace the UNJITTED body: the jitted wrapper caches its jaxpr by
    # avals+statics, which are identical to the plain GBT lantern trace —
    # the force flag is trace-time state the cache key cannot see, so a
    # cache hit in either direction would swap kernel/XLA bodies silently.
    # inspect.unwrap, not one .__wrapped__ hop: if the app ever ran in
    # this process, the compile sentinel has rebound the name to its own
    # wrapper and a single hop lands back on the jitted (cached) function
    import inspect

    raw_flush = inspect.unwrap(_fused_flush_explain)

    def fn(w, xx, vv, dd, fe, se, sa, ea):
        with pk.force_tree_shap_kernel(True):
            return raw_flush(
                w, xx, vv, dd, fe, se, sa, ea,
                score_fn=_raw_score_gbt, explain_k=3,
            )

    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        explain_args,
    )


@register_entrypoint("chisel.evergreen_flush")
def _build_chisel_evergreen_flush(mesh: Mesh):
    """The evergreen quant-wire GBT explain flush on the chisel kernel
    body — the harshest wire/kernel combo (explicit dequant feeding the
    kernel, uint8/f16 return wire)."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import (
        N_CALIB_BINS,
        DriftWindow,
        _fused_flush_quant_explain,
    )
    from fraud_detection_tpu.ops import pallas_kernels as pk
    from fraud_detection_tpu.ops.scorer import _raw_score_gbt

    window = DriftWindow(
        feature_counts=sds((_FEATURES, N_FEATURE_BINS), jnp.float32, mesh, P()),
        score_counts=sds((N_SCORE_BINS,), jnp.float32, mesh, P()),
        calib_count=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_conf=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        calib_label=sds((N_CALIB_BINS,), jnp.float32, mesh, P()),
        n_rows=sds((), jnp.float32, mesh, P()),
    )
    x = sds((_ROWS, _FEATURES), jnp.int8, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = _abstract_gbt_model(mesh)
    dq = sds((_FEATURES,), jnp.float32, mesh, P())
    explain_args = _abstract_tree_explainer(mesh)

    # unjitted body for the same cache-hazard reason as chisel.lantern_flush:
    # evergreen.flush traces the SAME avals/statics through the jitted
    # wrapper, and whichever traced first would hand the other its body
    # (inspect.unwrap to punch through a sentinel wrapper too — see there)
    import inspect

    raw_flush = inspect.unwrap(_fused_flush_quant_explain)

    def fn(w, xx, vv, dd, fe, se, sa, qs, ea):
        with pk.force_tree_shap_kernel(True):
            return raw_flush(
                w, xx, vv, dd, fe, se, sa, qs, ea,
                score_fn=_raw_score_gbt, score_codes=False, explain_k=3,
                out_dtype=jnp.uint8,
            )

    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        dq, explain_args,
    )


@register_entrypoint("mesh.evergreen_flush")
def _build_mesh_evergreen_flush(mesh: Mesh):
    """The evergreen mesh flush: the GBT dequant·score·TreeSHAP·drift
    program as ONE shard_map dispatch — int8 codes and reason codes
    row-sharded, the forest + explainer pytrees replicated, per-shard
    windows donated through, no collectives."""
    from fraud_detection_tpu.mesh.shardflush import _sharded_flush_quant_explain
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import N_CALIB_BINS, DriftWindow
    from fraud_detection_tpu.ops.scorer import _raw_score_gbt

    n_shards = mesh.shape[DATA_AXIS]
    shard = P(DATA_AXIS)
    window = DriftWindow(
        feature_counts=sds(
            (n_shards, _FEATURES, N_FEATURE_BINS), jnp.float32, mesh, shard
        ),
        score_counts=sds((n_shards, N_SCORE_BINS), jnp.float32, mesh, shard),
        calib_count=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_conf=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_label=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        n_rows=sds((n_shards,), jnp.float32, mesh, shard),
    )
    x = sds((_ROWS, _FEATURES), jnp.int8, mesh, shard)
    valid = sds((_ROWS,), jnp.float32, mesh, shard)
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = _abstract_gbt_model(mesh)
    dq = sds((_FEATURES,), jnp.float32, mesh, P())
    explain_args = _abstract_tree_explainer(mesh)
    fn = lambda w, xx, vv, dd, fe, se, sa, qs, ea: (  # noqa: E731
        _sharded_flush_quant_explain(
            w, xx, vv, dd, fe, se, sa, qs, ea,
            score_fn=_raw_score_gbt, mesh=mesh, score_codes=False,
            explain_k=3, out_dtype=jnp.uint8,
        )
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        dq, explain_args,
    )


@register_entrypoint("mesh.sharded_flush")
def _build_mesh_sharded_flush(mesh: Mesh):
    """The switchyard serving flush: the fused score+drift program as ONE
    shard_map-mapped dispatch over the data axis — rows row-sharded,
    params replicated, per-shard windows (leading shard axis) donated
    through. The live serving topology at every virtual mesh size."""
    from fraud_detection_tpu.mesh.shardflush import _sharded_flush
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import N_CALIB_BINS, DriftWindow
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    n_shards = mesh.shape[DATA_AXIS]
    shard = P(DATA_AXIS)
    window = DriftWindow(
        feature_counts=sds(
            (n_shards, _FEATURES, N_FEATURE_BINS), jnp.float32, mesh, shard
        ),
        score_counts=sds((n_shards, N_SCORE_BINS), jnp.float32, mesh, shard),
        calib_count=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_conf=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_label=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        n_rows=sds((n_shards,), jnp.float32, mesh, shard),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, shard)
    valid = sds((_ROWS,), jnp.float32, mesh, shard)
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    fn = lambda w, xx, vv, dd, fe, se, sa: _sharded_flush(  # noqa: E731
        w, xx, vv, dd, fe, se, sa, score_fn=_raw_score_linear, mesh=mesh
    )
    return fn, (window, x, valid, decay, feature_edges, score_edges, score_args)


@register_entrypoint("mesh.quickwire_flush")
def _build_mesh_quickwire_flush(mesh: Mesh):
    """The quickwire mesh flush: the fused dequant·score·drift program as
    ONE shard_map dispatch — int8 codes row-sharded, dequant scale + params
    replicated, per-shard windows donated through, uint8 return wire. The
    ``MESH_FLUSH_DEVICES>1`` quantized serving topology at every virtual
    mesh size."""
    from fraud_detection_tpu.mesh.shardflush import _sharded_flush_quant
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import N_CALIB_BINS, DriftWindow
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    n_shards = mesh.shape[DATA_AXIS]
    shard = P(DATA_AXIS)
    window = DriftWindow(
        feature_counts=sds(
            (n_shards, _FEATURES, N_FEATURE_BINS), jnp.float32, mesh, shard
        ),
        score_counts=sds((n_shards, N_SCORE_BINS), jnp.float32, mesh, shard),
        calib_count=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_conf=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_label=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        n_rows=sds((n_shards,), jnp.float32, mesh, shard),
    )
    x = sds((_ROWS, _FEATURES), jnp.int8, mesh, shard)
    valid = sds((_ROWS,), jnp.float32, mesh, shard)
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    dq = sds((_FEATURES,), jnp.float32, mesh, P())
    fn = lambda w, xx, vv, dd, fe, se, sa, qs: _sharded_flush_quant(  # noqa: E731
        w, xx, vv, dd, fe, se, sa, qs,
        score_fn=_raw_score_linear, mesh=mesh, score_codes=True,
        out_dtype=jnp.uint8,
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args, dq,
    )


@register_entrypoint("mesh.lantern_flush")
def _build_mesh_lantern_flush(mesh: Mesh):
    """The lantern mesh flush: fused score+explain+drift as ONE shard_map
    dispatch over the data axis — rows AND reason codes row-sharded,
    explain params replicated, per-shard windows donated through. The
    ``MESH_FLUSH_DEVICES>1`` explain-at-serve topology at every virtual
    mesh size."""
    from fraud_detection_tpu.mesh.shardflush import _sharded_flush_explain
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import N_CALIB_BINS, DriftWindow
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    n_shards = mesh.shape[DATA_AXIS]
    shard = P(DATA_AXIS)
    window = DriftWindow(
        feature_counts=sds(
            (n_shards, _FEATURES, N_FEATURE_BINS), jnp.float32, mesh, shard
        ),
        score_counts=sds((n_shards, N_SCORE_BINS), jnp.float32, mesh, shard),
        calib_count=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_conf=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        calib_label=sds((n_shards, N_CALIB_BINS), jnp.float32, mesh, shard),
        n_rows=sds((n_shards,), jnp.float32, mesh, shard),
    )
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, shard)
    valid = sds((_ROWS,), jnp.float32, mesh, shard)
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((_FEATURES, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    explain_args = (
        sds((_FEATURES,), jnp.float32, mesh, P()),
        sds((_FEATURES,), jnp.float32, mesh, P()),
    )
    fn = lambda w, xx, vv, dd, fe, se, sa, ea: _sharded_flush_explain(  # noqa: E731
        w, xx, vv, dd, fe, se, sa, ea,
        score_fn=_raw_score_linear, mesh=mesh, explain_k=3,
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        explain_args,
    )


_LEDGER_SLOTS = 1024  # abstract table size (power of two, like production)
_LEDGER_K = 4


def _abstract_ledger(mesh: Mesh, lead: tuple[int, ...] = (), spec: P = P()):
    from fraud_detection_tpu.ledger.state import LedgerState

    return LedgerState(
        acc=sds((*lead, _LEDGER_SLOTS, 3), jnp.float32, mesh, spec),
        last_ts=sds((*lead, _LEDGER_SLOTS), jnp.float32, mesh, spec),
        fingerprint=sds((*lead, _LEDGER_SLOTS), jnp.uint32, mesh, spec),
        collisions=sds(lead, jnp.float32, mesh, spec if lead else P()),
        evictions=sds(lead, jnp.float32, mesh, spec if lead else P()),
    )


def _widened_window(mesh: Mesh, lead: tuple[int, ...] = (), spec: P = P()):
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import N_CALIB_BINS, DriftWindow

    d = _FEATURES + _LEDGER_K
    return DriftWindow(
        feature_counts=sds((*lead, d, N_FEATURE_BINS), jnp.float32, mesh, spec),
        score_counts=sds((*lead, N_SCORE_BINS), jnp.float32, mesh, spec),
        calib_count=sds((*lead, N_CALIB_BINS), jnp.float32, mesh, spec),
        calib_conf=sds((*lead, N_CALIB_BINS), jnp.float32, mesh, spec),
        calib_label=sds((*lead, N_CALIB_BINS), jnp.float32, mesh, spec),
        n_rows=sds(lead, jnp.float32, mesh, spec if lead else P()),
    )


@register_entrypoint("ledger.flush")
def _build_ledger_flush(mesh: Mesh):
    """The stateful ledger flush (ledger/): per-entity velocity read+
    update, feature widening, scoring AND the drift fold in ONE donated
    dispatch — the serving hot path for a widened family, proven at every
    mesh size like the other fused flush programs."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import _fused_flush_ledger
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    d = _FEATURES + _LEDGER_K
    window = _widened_window(mesh)
    ledger = _abstract_ledger(mesh)
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    valid = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((d, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((d,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    slot_idx = sds((_ROWS,), jnp.int32, mesh, P(DATA_AXIS))
    fp = sds((_ROWS,), jnp.uint32, mesh, P(DATA_AXIS))
    ts = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    has = sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))
    null = sds((_LEDGER_K,), jnp.float32, mesh, P())
    hl = sds((), jnp.float32, mesh, P())
    fn = lambda w, led, xx, vv, dd, fe, se, sa, sl, ff, tt, hh, nn, ll: (  # noqa: E731
        _fused_flush_ledger(
            w, led, xx, vv, dd, fe, se, sa, sl, ff, tt, hh, nn, ll,
            score_fn=_raw_score_linear,
        )
    )
    return fn, (
        window, ledger, x, valid, decay, feature_edges, score_edges,
        score_args, slot_idx, fp, ts, has, null, hl,
    )


@register_entrypoint("mesh.ledger_flush")
def _build_mesh_ledger_flush(mesh: Mesh):
    """The switchyard ledger flush: the widened stateful program as ONE
    shard_map dispatch — rows placement-aligned (hash-mod-shard), per-shard
    windows AND entity sub-tables donated through, no collectives."""
    from fraud_detection_tpu.mesh.shardflush import _sharded_flush_ledger
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.ops.scorer import _raw_score_linear

    n_shards = mesh.shape[DATA_AXIS]
    shard = P(DATA_AXIS)
    d = _FEATURES + _LEDGER_K
    window = _widened_window(mesh, (n_shards,), shard)
    ledger = _abstract_ledger(mesh, (n_shards,), shard)
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, shard)
    valid = sds((_ROWS,), jnp.float32, mesh, shard)
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((d, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((d,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    slot_idx = sds((_ROWS,), jnp.int32, mesh, shard)
    fp = sds((_ROWS,), jnp.uint32, mesh, shard)
    ts = sds((_ROWS,), jnp.float32, mesh, shard)
    has = sds((_ROWS,), jnp.float32, mesh, shard)
    null = sds((_LEDGER_K,), jnp.float32, mesh, P())
    hl = sds((), jnp.float32, mesh, P())
    fn = lambda w, led, xx, vv, dd, fe, se, sa, sl, ff, tt, hh, nn, ll: (  # noqa: E731
        _sharded_flush_ledger(
            w, led, xx, vv, dd, fe, se, sa, sl, ff, tt, hh, nn, ll,
            score_fn=_raw_score_linear, mesh=mesh,
        )
    )
    return fn, (
        window, ledger, x, valid, decay, feature_edges, score_edges,
        score_args, slot_idx, fp, ts, has, null, hl,
    )


_WIDE_LOG2 = 10  # abstract cross-table size (power of two, like production)


def _abstract_cross_spec():
    from fraud_detection_tpu.ops.crosses import CrossSpec

    return CrossSpec(
        n_base=_FEATURES, log2_buckets=_WIDE_LOG2, amount_col=_FEATURES - 1,
        time_col=0,
    )


def _wide_abstract_args(mesh: Mesh, lead: tuple[int, ...] = (), spec: P = P()):
    """Shared abstract inputs of the broadside flush programs: the widened
    window (base + n_cross contribution columns), base-width rows, the
    cross-weight table, fingerprints, and the widened score args."""
    from fraud_detection_tpu.monitor.baseline import N_FEATURE_BINS, N_SCORE_BINS
    from fraud_detection_tpu.monitor.drift import N_CALIB_BINS, DriftWindow

    cross = _abstract_cross_spec()
    d = cross.n_features
    window = DriftWindow(
        feature_counts=sds((*lead, d, N_FEATURE_BINS), jnp.float32, mesh, spec),
        score_counts=sds((*lead, N_SCORE_BINS), jnp.float32, mesh, spec),
        calib_count=sds((*lead, N_CALIB_BINS), jnp.float32, mesh, spec),
        calib_conf=sds((*lead, N_CALIB_BINS), jnp.float32, mesh, spec),
        calib_label=sds((*lead, N_CALIB_BINS), jnp.float32, mesh, spec),
        n_rows=sds(lead, jnp.float32, mesh, spec if lead else P()),
    )
    row = P(DATA_AXIS)
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, row)
    valid = sds((_ROWS,), jnp.float32, mesh, row)
    decay = sds((), jnp.float32, mesh, P())
    feature_edges = sds((d, N_FEATURE_BINS - 1), jnp.float32, mesh, P())
    score_edges = sds((N_SCORE_BINS - 1,), jnp.float32, mesh, P())
    score_args = (
        sds((d,), jnp.float32, mesh, P()),
        sds((), jnp.float32, mesh, P()),
    )
    fp = sds((_ROWS,), jnp.uint32, mesh, row)
    has = sds((_ROWS,), jnp.float32, mesh, row)
    return cross, window, x, valid, decay, feature_edges, score_edges, \
        score_args, fp, has


@register_entrypoint("broadside.flush")
def _build_broadside_flush(mesh: Mesh):
    """The wide-family fused flush (broadside): hashed cross indices,
    table gather, widened-block scoring, top-k reason codes AND the drift
    fold in ONE donated dispatch — the serving hot path for a wide
    champion, proven at every mesh size like the other fused programs."""
    from fraud_detection_tpu.monitor.drift import _fused_flush_wide

    (cross, window, x, valid, decay, feature_edges, score_edges,
     score_args, fp, has) = _wide_abstract_args(mesh)
    table = sds((cross.buckets,), jnp.float32, mesh, P())
    explain_args = (
        sds((cross.n_features,), jnp.float32, mesh, P()),
        sds((cross.n_features,), jnp.float32, mesh, P()),
    )
    fn = lambda w, xx, vv, dd, fe, se, sa, tt, ff, hh, ea: (  # noqa: E731
        _fused_flush_wide(
            w, xx, vv, dd, fe, se, sa, tt, ff, hh, None, ea,
            cross_spec=cross, explain_k=3,
        )
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        table, fp, has, explain_args,
    )


@register_entrypoint("mesh.broadside_flush", mesh_sizes=WIDE_MESH_SHAPES)
def _build_mesh_broadside_flush(mesh: Mesh):
    """The 2-D broadside mesh flush: rows sharded over data, the
    cross-weight table column-sharded over the MODEL axis (the
    tensor-parallel score_args leaves the topology always promised),
    per-(data,model)-shard windows donated through, exactly ONE model-axis
    psum assembling the widened block. Proven at the non-trivial model
    factorizations (1×1, 2×2, 4×2, 2×4)."""
    from fraud_detection_tpu.mesh.shardflush import _sharded_flush_wide

    shape = dict(mesh.shape)
    n_shards = shape[DATA_AXIS] * shape.get(MODEL_AXIS, 1)
    grid = P((DATA_AXIS, MODEL_AXIS))
    (cross, window, x, valid, decay, feature_edges, score_edges,
     score_args, fp, has) = _wide_abstract_args(mesh, (n_shards,), grid)
    table = sds((cross.buckets,), jnp.float32, mesh, P(MODEL_AXIS))
    explain_args = (
        sds((cross.n_features,), jnp.float32, mesh, P()),
        sds((cross.n_features,), jnp.float32, mesh, P()),
    )
    fn = lambda w, xx, vv, dd, fe, se, sa, tt, ff, hh, ea: (  # noqa: E731
        _sharded_flush_wide(
            w, xx, vv, dd, fe, se, sa, tt, ff, hh, None, ea,
            cross_spec=cross, mesh=mesh, explain_k=3, has_explain=True,
        )
    )
    return fn, (
        window, x, valid, decay, feature_edges, score_edges, score_args,
        table, fp, has, explain_args,
    )


@register_entrypoint("mesh.wide_update", mesh_sizes=WIDE_MESH_SHAPES)
def _build_mesh_wide_update(mesh: Mesh):
    """The 2-D wide-family weight update (2004.13336 in 2-D): the cross
    table column-owned on the model axis, subdivided with its momentum
    state over the data axis, grads psum_scatter'd on data, the widened
    logit assembled with one model-axis psum per step."""
    from fraud_detection_tpu.mesh.retrain import (
        WIDE_PARAM_SPEC,
        _wide_update_epoch,
    )

    cross = _abstract_cross_spec()
    batch = 64
    shard = P(DATA_AXIS)
    coef = sds((_FEATURES,), jnp.float32, mesh, P())
    vel = sds((_FEATURES,), jnp.float32, mesh, P())
    wl = sds((cross.buckets,), jnp.float32, mesh, WIDE_PARAM_SPEC)
    wvl = sds((cross.buckets,), jnp.float32, mesh, WIDE_PARAM_SPEC)
    intercept = sds((), jnp.float32, mesh, P())
    vel_b = sds((), jnp.float32, mesh, P())
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, shard)
    idx = sds((_ROWS, cross.n_cross), jnp.int32, mesh, shard)
    per_row = lambda: sds((_ROWS,), jnp.float32, mesh, shard)  # noqa: E731
    size = dict(mesh.shape)[DATA_AXIS]
    perm = sds((_ROWS // size,), jnp.int32, mesh, P())
    lr = sds((), jnp.float32, mesh, P())
    fn = lambda c, v, w, wv, b, vb, xx, ii, hh, yy, ss, vv, pp, ll: (  # noqa: E731
        _wide_update_epoch(
            c, v, w, wv, b, vb, xx, ii, hh, yy, ss, vv, pp, ll,
            mesh=mesh, c=1.0, n_total=_ROWS, momentum=0.9, batch=batch,
        )
    )
    return fn, (
        coef, vel, wl, wvl, intercept, vel_b, x, idx, per_row(), per_row(),
        per_row(), per_row(), perm, lr,
    )


@register_entrypoint("mesh.sharded_update")
def _build_mesh_sharded_update(mesh: Mesh):
    """The cross-replica-sharded weight update (2004.13336): params and
    optimizer state sharded over the data axis, gradient psum_scatter'd
    onto the owning shards, full vector all_gather'd per forward."""
    from fraud_detection_tpu.mesh.retrain import (
        _pad_features,
        _sharded_update_epoch,
    )

    size = mesh.shape[DATA_AXIS]
    d_pad = _pad_features(_FEATURES, size)
    batch = 64  # divides the per-device shard at every registered mesh size
    shard = P(DATA_AXIS)
    coef_sh = sds((d_pad,), jnp.float32, mesh, shard)
    vel_sh = sds((d_pad,), jnp.float32, mesh, shard)
    intercept = sds((), jnp.float32, mesh, P())
    vel_b = sds((), jnp.float32, mesh, P())
    x = sds((_ROWS, d_pad), jnp.float32, mesh, shard)
    per_row = lambda: sds((_ROWS,), jnp.float32, mesh, shard)  # noqa: E731
    perm = sds((_ROWS // size,), jnp.int32, mesh, P())
    lr = sds((), jnp.float32, mesh, P())
    fn = lambda c_sh, v_sh, b, vb, xx, yy, ss, vv, pp, ll: (  # noqa: E731
        _sharded_update_epoch(
            c_sh, v_sh, b, vb, xx, yy, ss, vv, pp, ll,
            mesh=mesh, c=1.0, n_total=_ROWS, momentum=0.9, batch=batch,
        )
    )
    return fn, (
        coef_sh, vel_sh, intercept, vel_b, x, per_row(), per_row(),
        per_row(), perm, lr,
    )


@register_entrypoint("lifecycle.gate_eval")
def _build_gate_eval(mesh: Mesh):
    from fraud_detection_tpu.lifecycle.gate import (
        N_GATE_CALIB_BINS,
        N_GATE_SCORE_BINS,
        _gate_stats,
    )

    per_row = lambda: sds((_ROWS,), jnp.float32, mesh, P(DATA_AXIS))  # noqa: E731
    score_edges = sds((N_GATE_SCORE_BINS - 1,), jnp.float32, mesh, P())
    calib_edges = sds((N_GATE_CALIB_BINS - 1,), jnp.float32, mesh, P())
    return _gate_stats, (
        per_row(), per_row(), per_row(), per_row(), score_edges, calib_edges,
    )


@register_entrypoint("scaler.fit_transform")
def _build_scaler(mesh: Mesh):
    from fraud_detection_tpu.ops.scaler import _fit, scaler_transform

    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))

    def fit_transform(xx):
        params = _fit(xx, _ROWS - 24)  # n_valid < rows: padded-tail masking
        return scaler_transform(params, xx)

    return fit_transform, (x,)


@register_entrypoint("longhaul.partial_pool")
def _build_longhaul_partial_pool(mesh: Mesh):
    """The fleet pool map body: one HOST's partial sums, so its inputs are
    that host's local rows (replicated here — the body must compile with
    ZERO collectives at every mesh size, which is exactly what makes it a
    map body)."""
    from fraud_detection_tpu.longhaul.fleet import _host_partial_pool

    per_row = lambda: sds((_ROWS,), jnp.float32, mesh, P())  # noqa: E731
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P())
    return _host_partial_pool, (x, per_row(), per_row(), per_row())


@register_entrypoint("longhaul.fleet_grad")
def _build_longhaul_fleet_grad(mesh: Mesh):
    """The fleet SGD map body: one host's un-normalized gradient sums —
    zero collectives; the reduce is the transport's job."""
    from fraud_detection_tpu.longhaul.fleet import _host_grad

    coef = sds((_FEATURES,), jnp.float32, mesh, P())
    intercept = sds((), jnp.float32, mesh, P())
    x = sds((_ROWS, _FEATURES), jnp.float32, mesh, P())
    per_row = lambda: sds((_ROWS,), jnp.float32, mesh, P())  # noqa: E731
    return _host_grad, (coef, intercept, x, per_row(), per_row())


@register_entrypoint("longhaul.pool_merge")
def _build_longhaul_pool_merge(mesh: Mesh):
    """The fleet pool merge: per-host partials stacked on the data axis
    (standing in for the hosts axis — under jax.distributed the same axis
    spans processes), ONE psum per summary component."""
    from fraud_detection_tpu.longhaul.fleet import _fleet_pool_merge

    size = mesh.shape[DATA_AXIS]
    shard = P(DATA_AXIS)
    scalar = lambda: sds((size,), jnp.float32, mesh, shard)  # noqa: E731
    vec = lambda: sds((size, _FEATURES), jnp.float32, mesh, shard)  # noqa: E731
    fn = lambda n, np_, s, fx, fx2: _fleet_pool_merge(  # noqa: E731
        n, np_, s, fx, fx2, mesh=mesh
    )
    return fn, (scalar(), scalar(), scalar(), vec(), vec())


@register_entrypoint("longhaul.grad_merge")
def _build_longhaul_grad_merge(mesh: Mesh):
    """The fleet gradient merge: 2 psums (coef block, intercept), nothing
    else — the whole collective footprint of one fleet SGD step."""
    from fraud_detection_tpu.longhaul.fleet import _fleet_grad_merge

    size = mesh.shape[DATA_AXIS]
    g_coef = sds((size, _FEATURES), jnp.float32, mesh, P(DATA_AXIS))
    g_b = sds((size,), jnp.float32, mesh, P(DATA_AXIS))
    fn = lambda gc, gb: _fleet_grad_merge(gc, gb, mesh=mesh)  # noqa: E731
    return fn, (g_coef, g_b)
