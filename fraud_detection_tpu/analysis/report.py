"""Rendering + exit-code policy for analyzer results (text and JSON)."""

from __future__ import annotations

import json
from typing import Any

from fraud_detection_tpu.analysis.baseline import BaselineResult
from fraud_detection_tpu.analysis.core import Finding, Severity, iter_rules

_SEV_TAG = {
    Severity.ERROR: "error",
    Severity.WARNING: "warning",
    Severity.INFO: "info",
}


def render_text(
    result: BaselineResult,
    mesh_results: list[dict] | None = None,
    verbose: bool = False,
) -> str:
    lines: list[str] = []
    for f in result.new:
        lines.append(
            f"{f.path}:{f.line}:{f.col}: {_SEV_TAG[f.severity]} "
            f"[{f.rule_id}] {f.message}"
        )
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if mesh_results:
        for r in mesh_results:
            if not r["ok"]:
                lines.append(
                    f"meshcheck: error [{r['entrypoint']}] mesh size "
                    f"{r['mesh_size']}: {r['error']}"
                )
            elif verbose:
                lines.append(
                    f"meshcheck: ok [{r['entrypoint']}] mesh size "
                    f"{r['mesh_size']} ({r['out']})"
                )
    n_mesh_fail = sum(1 for r in (mesh_results or []) if not r["ok"])
    summary = (
        f"graftcheck: {len(result.new)} finding(s), "
        f"{len(result.suppressed)} baselined"
    )
    if result.stale:
        summary += f", {len(result.stale)} stale baseline entr(y/ies)"
    if mesh_results is not None:
        summary += (
            f"; mesh verification: {len(mesh_results) - n_mesh_fail}/"
            f"{len(mesh_results)} checks passed"
        )
    lines.append(summary)
    if result.stale and verbose:
        for e in result.stale:
            lines.append(
                f"  stale baseline entry: [{e.get('rule')}] "
                f"{e.get('path')} — {e.get('snippet', '')!r}"
            )
    return "\n".join(lines)


def render_json(
    result: BaselineResult, mesh_results: list[dict] | None = None
) -> str:
    doc: dict[str, Any] = {
        "findings": [f.to_dict() for f in result.new],
        "baselined": [f.to_dict() for f in result.suppressed],
        "stale_baseline": result.stale,
        "rules": [
            {
                "id": r.id,
                "severity": r.severity.name.lower(),
                "description": r.description,
            }
            for r in iter_rules()
        ],
        "summary": {
            "new": len(result.new),
            "baselined": len(result.suppressed),
            "stale": len(result.stale),
        },
    }
    if mesh_results is not None:
        doc["mesh_verification"] = mesh_results
        doc["summary"]["mesh_failures"] = sum(
            1 for r in mesh_results if not r["ok"]
        )
    return json.dumps(doc, indent=2)


def exit_code(
    result: BaselineResult,
    mesh_results: list[dict] | None = None,
    fail_on: Severity = Severity.INFO,
) -> int:
    """1 when any non-baselined finding at/above ``fail_on`` exists or any
    mesh verification failed, else 0."""
    if any(f.severity >= fail_on for f in result.new):
        return 1
    if mesh_results and any(not r["ok"] for r in mesh_results):
        return 1
    return 0
