"""Rendering + exit-code policy for analyzer results (text and JSON)."""

from __future__ import annotations

import json
from typing import Any

from fraud_detection_tpu.analysis.baseline import BaselineResult
from fraud_detection_tpu.analysis.core import Finding, Severity, iter_rules

_SEV_TAG = {
    Severity.ERROR: "error",
    Severity.WARNING: "warning",
    Severity.INFO: "info",
}


def render_text(
    result: BaselineResult,
    mesh_results: list[dict] | None = None,
    verbose: bool = False,
    contract_results: list[dict] | None = None,
    contract_new: list[str] | None = None,
    lock_report: dict | None = None,
    lock_new: list[str] | None = None,
) -> str:
    lines: list[str] = []
    for f in result.new:
        lines.append(
            f"{f.path}:{f.line}:{f.col}: {_SEV_TAG[f.severity]} "
            f"[{f.rule_id}] {f.message}"
        )
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if mesh_results:
        for r in mesh_results:
            if not r["ok"]:
                lines.append(
                    f"meshcheck: error [{r['entrypoint']}] mesh size "
                    f"{r['mesh_size']}: {r['error']}"
                )
            elif verbose:
                lines.append(
                    f"meshcheck: ok [{r['entrypoint']}] mesh size "
                    f"{r['mesh_size']} ({r['out']})"
                )
    if contract_results is not None:
        covered = {k for k in (contract_new or [])}
        for r in contract_results:
            for v in r["violations"]:
                key = f"{r['entrypoint']}:{v['diagnostic']}"
                tag = "error" if key in covered else "baselined"
                lines.append(
                    f"contracts: {tag} [{r['entrypoint']}] "
                    f"{v['diagnostic']}: {v['detail']}"
                )
            if r["ok"] and verbose:
                lines.append(
                    f"contracts: ok [{r['entrypoint']}] mesh "
                    f"{r['mesh_size']}"
                )
    if lock_report is not None:
        new_keys = set(lock_new or [])
        for cyc in lock_report["cycles"]:
            tag = "error" if f"lock-cycle:{cyc}" in new_keys else "baselined"
            lines.append(f"lockcheck: {tag} acquisition cycle: {cyc}")
        for d in lock_report["inventory_drift"]:
            lines.append(
                f"lockcheck: error [{d['diagnostic']}] {d['detail']}"
            )
        if verbose:
            for e in lock_report["edges"]:
                lines.append(
                    f"lockcheck: edge {e['src']} -> {e['dst']} "
                    f"({e['sites'][0]})"
                )
    n_mesh_fail = sum(1 for r in (mesh_results or []) if not r["ok"])
    summary = (
        f"graftcheck: {len(result.new)} finding(s), "
        f"{len(result.suppressed)} baselined"
    )
    if result.stale:
        summary += f", {len(result.stale)} stale baseline entr(y/ies)"
    if mesh_results is not None:
        summary += (
            f"; mesh verification: {len(mesh_results) - n_mesh_fail}/"
            f"{len(mesh_results)} checks passed"
        )
    if contract_results is not None:
        n_ok = sum(1 for r in contract_results if r["ok"])
        summary += (
            f"; contracts: {n_ok}/{len(contract_results)} entrypoints hold"
        )
    if lock_report is not None:
        summary += (
            f"; lockcheck: {len(lock_report['edges'])} order edge(s), "
            f"{len(lock_report['cycles'])} cycle(s), "
            f"{len(lock_report['inventory_drift'])} drift"
        )
    lines.append(summary)
    if result.stale and verbose:
        for e in result.stale:
            lines.append(
                f"  stale baseline entry: [{e.get('rule')}] "
                f"{e.get('path')} — {e.get('snippet', '')!r}"
            )
    return "\n".join(lines)


def render_json(
    result: BaselineResult,
    mesh_results: list[dict] | None = None,
    contract_results: list[dict] | None = None,
    contract_new: list[str] | None = None,
    lock_report: dict | None = None,
    lock_new: list[str] | None = None,
) -> str:
    doc: dict[str, Any] = {
        "findings": [f.to_dict() for f in result.new],
        "baselined": [f.to_dict() for f in result.suppressed],
        "stale_baseline": result.stale,
        "rules": [
            {
                "id": r.id,
                "severity": r.severity.name.lower(),
                "description": r.description,
            }
            for r in iter_rules()
        ],
        "summary": {
            "new": len(result.new),
            "baselined": len(result.suppressed),
            "stale": len(result.stale),
        },
    }
    if mesh_results is not None:
        doc["mesh_verification"] = mesh_results
        doc["summary"]["mesh_failures"] = sum(
            1 for r in mesh_results if not r["ok"]
        )
    if contract_results is not None:
        doc["contracts"] = contract_results
        doc["summary"]["contract_violations"] = sum(
            len(r["violations"]) for r in contract_results
        )
        doc["summary"]["contract_new"] = list(contract_new or [])
    if lock_report is not None:
        doc["lockcheck"] = lock_report
        doc["summary"]["lock_cycles"] = len(lock_report["cycles"])
        doc["summary"]["lock_drift"] = len(lock_report["inventory_drift"])
        doc["summary"]["lock_new"] = list(lock_new or [])
    return json.dumps(doc, indent=2)


def exit_code(
    result: BaselineResult,
    mesh_results: list[dict] | None = None,
    fail_on: Severity = Severity.INFO,
    contract_new: list[str] | None = None,
    lock_new: list[str] | None = None,
) -> int:
    """1 when any non-baselined finding at/above ``fail_on`` exists, any
    mesh verification failed, or any non-baselined contract/lock violation
    exists, else 0."""
    if any(f.severity >= fail_on for f in result.new):
        return 1
    if mesh_results and any(not r["ok"] for r in mesh_results):
        return 1
    if contract_new or lock_new:
        return 1
    return 0
