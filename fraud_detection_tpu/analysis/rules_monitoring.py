"""Monitoring-contract lint: alert expressions must reference live metrics.

The dead-series alert class: a rule file names a metric nothing exports —
a rename on one side, a typo on the other — and the alert silently never
fires (an absent series is just an empty vector to PromQL, not an error).
The registry-contract tests in ``tests/test_monitoring_configs.py`` catch
this at test time by importing the live registry; this rule catches it at
LINT time, purely from source: when graftcheck walks
``service/metrics.py`` it collects every metric name registered there (AST
only — no imports, no prometheus_client), then cross-checks every ``expr:``
in ``monitoring/prometheus/rules/*.yml`` against that set.

Token extraction is deliberately conservative: quoted strings, label
selectors ``{...}``, range windows ``[5m]``, grouping clauses
(``by (...)``/``on (...)``/...), and function calls are stripped first;
what remains counts as a metric reference only when it contains an
underscore (every metric this repo exports does; bare PromQL keywords and
label names like ``le`` never do). Counter ``_total`` and histogram
``_bucket``/``_sum``/``_count`` suffixes are normalized before the
membership check, mirroring Prometheus exposition.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Iterator

from fraud_detection_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Severity,
    register_rule,
)

#: prometheus_client constructors whose first string arg registers a name.
_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info", "Enum"}

_EXPR_RE = re.compile(r"^\s*expr:\s*(.+?)\s*$", re.M)
_STRING_RE = re.compile(r"'[^']*'|\"[^\"]*\"")
_SELECTOR_RE = re.compile(r"\{[^}]*\}")
_RANGE_RE = re.compile(r"\[[^\]]*\]")
_GROUP_RE = re.compile(
    r"\b(?:by|without|on|ignoring|group_left|group_right)\s*\([^)]*\)"
)
_FUNC_RE = re.compile(r"\b[A-Za-z_][A-Za-z0-9_:]*\s*\(")
_TOKEN_RE = re.compile(r"\b[A-Za-z_][A-Za-z0-9_:]*\b")

#: underscore-bearing PromQL builtins / modifiers a conservative extractor
#: could still catch (none of the repo's metric names collide with these).
_PROMQL_WORDS = {
    "group_left", "group_right", "bool", "offset", "unless",
}

_SUFFIXES = ("_bucket", "_sum", "_count", "_total")

#: sanctioned exporter modules beside the shared service registry: the
#: store server exports its ``fraud_store_*`` gauges from a module-local
#: CollectorRegistry (the prom-foreign-registry rule sanctions exactly
#: this), so its registrations count toward the alert contract too.
_EXTRA_EXPORTERS = ("netserver.py",)


def _normalize(name: str) -> str:
    for sfx in _SUFFIXES:
        if name.endswith(sfx):
            return name[: -len(sfx)]
    return name


def registered_metric_names(tree: ast.AST) -> set[str]:
    """Metric names registered by ``Counter/Gauge/Histogram(...)`` calls in
    the module's AST (first positional string argument)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        ctor = getattr(fn, "id", None) or getattr(fn, "attr", None)
        if ctor not in _METRIC_CTORS or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            names.add(first.value)
    return names


def metric_tokens(expr: str) -> set[str]:
    """Candidate metric names referenced by one PromQL expression."""
    s = _STRING_RE.sub(" ", expr)
    s = _SELECTOR_RE.sub(" ", s)
    s = _RANGE_RE.sub(" ", s)
    s = _GROUP_RE.sub(" ", s)
    s = _FUNC_RE.sub(" ", s)  # drops the function NAME, keeps its args
    out: set[str] = set()
    for tok in _TOKEN_RE.findall(s):
        if "_" in tok and tok not in _PROMQL_WORDS:
            out.add(tok)
    return out


def _rules_dir_for(path: str) -> str | None:
    """Walk up from the analyzed file to the repo root holding
    ``monitoring/prometheus/rules`` (tests point the rule at fixture
    trees the same way)."""
    d = os.path.dirname(os.path.abspath(path))
    for _ in range(8):
        cand = os.path.join(d, "monitoring", "prometheus", "rules")
        if os.path.isdir(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


@register_rule(
    "alert-metric-registered",
    Severity.ERROR,
    "alert rule expressions reference only metric names registered in "
    "service/metrics.py (the dead-series alert class, caught at lint time)",
)
def check_alert_metrics_registered(mod: ModuleInfo) -> Iterator[Finding]:
    rule = check_alert_metrics_registered.rule
    if not mod.rel_path.replace(os.sep, "/").endswith("service/metrics.py"):
        return
    rules_dir = _rules_dir_for(mod.path)
    if rules_dir is None:
        return
    registered = registered_metric_names(mod.tree)
    if not registered:
        return
    for sibling in _EXTRA_EXPORTERS:
        path = os.path.join(os.path.dirname(os.path.abspath(mod.path)), sibling)
        try:
            with open(path, encoding="utf-8") as f:
                registered |= registered_metric_names(ast.parse(f.read()))
        except (OSError, SyntaxError):
            continue  # fixture trees need not ship every exporter
    for yml in sorted(glob.glob(os.path.join(rules_dir, "*.yml"))):
        try:
            with open(yml, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        dead: set[str] = set()
        for m in _EXPR_RE.finditer(text):
            for tok in metric_tokens(m.group(1)):
                if _normalize(tok) not in registered and tok not in registered:
                    dead.add(tok)
        if dead:
            yield mod.finding(
                rule,
                ast.Module(body=[], type_ignores=[]),
                f"{os.path.basename(yml)} references metric(s) not "
                f"registered in service/metrics.py: {sorted(dead)} — the "
                "alert would silently never fire (empty vector, not an "
                "error)",
            )
