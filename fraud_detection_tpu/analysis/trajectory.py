"""Bench-trajectory regression tracking (panopticon satellite).

The repo's performance story lived in loose ``BENCH_r*.json`` snapshots —
informative archaeology, but nothing GATES on them: a PR that halves the
fused speedup merges as long as the absolute CI floors still hold. This
module makes the trajectory itself the artifact: each bench run's headline
numbers append to a committed ``BENCH_TRAJECTORY.json``, and the CI step
fails when a headline regresses more than the tolerance vs the previous
comparable entry.

Comparability matters: CI runners and dev laptops differ by integer
factors, so a naive last-entry comparison would fail every time the host
changes. Entries therefore carry a host fingerprint (cpu count + platform
+ backend); the gate compares only against the latest entry with the SAME
fingerprint and appends ungated otherwise (the new host seeds its own
baseline). Ratio-like headlines (overhead fractions) are compared with an
absolute floor so sub-percent noise on a near-zero number can't fail the
job.

CLI (the CI step)::

    python -m fraud_detection_tpu.analysis.trajectory \
        bench-telemetry.json bench-online.json \
        --trajectory BENCH_TRAJECTORY.json --tolerance 0.15

Exit 1 on regression; the updated trajectory is written either way so the
artifact upload shows exactly what was compared.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

#: headline keys harvested from the bench JSON lines:
#: name → (source key, direction, absolute slack). Direction "higher"
#: regresses when the new value drops below previous*(1-tol); "lower"
#: when it rises above previous*(1+tol)+slack. The slack keeps
#: near-zero fractions (telemetry overhead) from failing on noise.
HEADLINES: dict[str, tuple[str, str, float]] = {
    "fused_speedup": ("microbatch_flush_speedup", "higher", 0.0),
    "online_rows_per_sec": ("online_binary_rows_per_sec", "higher", 0.0),
    "online_json_rows_per_sec": ("online_json_rows_per_sec", "higher", 0.0),
    "telemetry_overhead_frac": ("telemetry_overhead_frac", "lower", 0.01),
    "explain_cost_ratio": ("explain_cost_ratio", "higher", 0.0),
    # the GBT exact-TreeSHAP explain ratio the chisel floor reconciles
    # against (GBT_EXPLAIN_CPU_FLOOR)
    "gbt_explain_cost_ratio": ("gbt_explain_cost_ratio", "higher", 0.0),
    "recovery_replay_rows_per_sec": (
        "recovery_replay_rows_per_sec", "higher", 0.0,
    ),
    "recovery_snapshot_overhead_frac": (
        "recovery_snapshot_overhead_frac", "lower", 0.01,
    ),
    "multihost_replay_rows_per_sec": (
        "multihost_replay_rows_per_sec", "higher", 0.0,
    ),
    # failover wall time includes a directory round-trip + socket setup —
    # sub-second but jittery, so an absolute slack carries the noise
    "multihost_failover_s": ("multihost_failover_s", "lower", 0.5),
}


def host_fingerprint() -> str:
    import platform

    backend = os.environ.get("JAX_PLATFORMS", "default")
    return f"{platform.machine()}-cpu{os.cpu_count()}-{backend}"


def harvest(bench_files: list[str]) -> dict[str, float]:
    """Headline numbers present in the given bench JSON lines (missing
    sections simply contribute nothing — the gate only compares keys both
    entries carry)."""
    merged: dict = {}
    for path in bench_files:
        try:
            with open(path, encoding="utf-8") as f:
                merged.update(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            print(f"trajectory: skipping {path}: {e}", file=sys.stderr)
    out: dict[str, float] = {}
    for name, (key, _, _) in HEADLINES.items():
        v = merged.get(key)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def load(path: str) -> list[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    if not isinstance(data, list):
        raise ValueError(f"{path}: trajectory must be a JSON list")
    return data


def compare(
    prev: dict, new_headlines: dict[str, float], tolerance: float
) -> list[str]:
    """Regressions of ``new_headlines`` vs one previous entry; [] = clean."""
    regressions: list[str] = []
    old = prev.get("headlines", {})
    for name, value in new_headlines.items():
        if name not in old:
            continue
        base = float(old[name])
        _, direction, slack = HEADLINES[name]
        if direction == "higher":
            floor = base * (1.0 - tolerance)
            if value < floor:
                regressions.append(
                    f"{name}: {value:g} < {floor:g} "
                    f"(prev {base:g}, tolerance {tolerance:.0%})"
                )
        else:
            ceil = base * (1.0 + tolerance) + slack
            if value > ceil:
                regressions.append(
                    f"{name}: {value:g} > {ceil:g} "
                    f"(prev {base:g}, tolerance {tolerance:.0%} + {slack:g})"
                )
    return regressions


def append(
    bench_files: list[str],
    trajectory_path: str,
    tolerance: float = 0.15,
    note: str | None = None,
) -> tuple[dict, list[str]]:
    """Harvest, gate against the latest same-host entry, append, write.
    Returns (new entry, regressions)."""
    headlines = harvest(bench_files)
    entries = load(trajectory_path)
    fp = host_fingerprint()
    baseline = next(
        (e for e in reversed(entries) if e.get("host") == fp), None
    )
    regressions = (
        compare(baseline, headlines, tolerance) if baseline else []
    )
    entry = {
        "ts": int(time.time()),
        "host": fp,
        "note": note,
        "headlines": headlines,
        "regressions": regressions,
        "compared_to": baseline["ts"] if baseline else None,
    }
    entries.append(entry)
    with open(trajectory_path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=1)
        f.write("\n")
    return entry, regressions


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench-trajectory",
        description="append bench headlines to the committed trajectory and "
        "fail on regression vs the previous same-host entry",
    )
    ap.add_argument("bench_files", nargs="+", help="bench JSON line files")
    ap.add_argument("--trajectory", default="BENCH_TRAJECTORY.json")
    ap.add_argument("--tolerance", type=float, default=0.15)
    ap.add_argument("--note", default=None)
    args = ap.parse_args(argv)
    entry, regressions = append(
        args.bench_files, args.trajectory, args.tolerance, args.note
    )
    print(json.dumps(entry, indent=1))
    if regressions:
        print(
            "BENCH TRAJECTORY REGRESSION (>{:.0%} vs previous entry on this "
            "host):".format(args.tolerance),
            file=sys.stderr,
        )
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
