"""Baseline suppression file: accepted findings checked in at repo root.

The gate is "no findings beyond the baseline", so adopting the analyzer on
a codebase with pre-existing accepted findings doesn't require fixing (or
inline-tagging) every one of them up front. Matching is by
:attr:`Finding.fingerprint` — rule + file + normalized source line — as a
multiset, so

- editing unrelated lines above a baselined finding keeps it matched
  (fingerprints carry no line numbers);
- fixing a baselined finding never breaks the gate (stale entries are
  reported separately so they can be pruned);
- a NEW instance of an already-baselined pattern on a *different* line
  text is still caught.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from dataclasses import dataclass
from typing import Iterable

from fraud_detection_tpu.analysis.core import Finding

DEFAULT_BASELINE = "analysis_baseline.json"


@dataclass
class BaselineResult:
    new: list[Finding]          # findings not covered by the baseline
    suppressed: list[Finding]   # findings matched against baseline entries
    stale: list[dict]           # baseline entries matching nothing (prunable)


def _load_doc(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):  # pre-sectioned format: bare findings list
        return {"findings": doc}
    return doc


def load(path: str) -> list[dict]:
    return _load_doc(path).get("findings", [])


def load_section(path: str, section: str) -> list[str]:
    """Accepted violation keys for a non-lint pass (``"contracts"`` /
    ``"lockcheck"``). Keys are the stable strings each pass mints
    (``entrypoint:diagnostic``, ``lock-cycle:a -> b -> a``); empty is the
    repo norm — the sections exist so adopting a new pass on a tree with
    accepted debt never requires fixing it in the same PR."""
    vals = _load_doc(path).get(section, [])
    return [v for v in vals if isinstance(v, str)]


def save(path: str, findings: Iterable[Finding]) -> None:
    prior = _load_doc(path)
    doc = {
        "comment": (
            "graftcheck baseline: accepted findings, per pass. `findings` "
            "is the lint pass (regenerate with `python -m "
            "fraud_detection_tpu.analysis --write-baseline` after reviewing "
            "that every entry is an accepted exception); `contracts` and "
            "`lockcheck` hold accepted violation keys for the contract "
            "prover and the lock-order pass (edit by hand; empty is the "
            "norm)."
        ),
        "findings": [f.to_dict() for f in findings],
        "contracts": prior.get("contracts", []),
        "lockcheck": prior.get("lockcheck", []),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_keys(keys: list[str], accepted: list[str]) -> tuple[list[str], list[str]]:
    """Multiset-diff stable violation keys against a baseline section:
    returns ``(new, stale)`` — keys not covered by the baseline, and
    baseline entries matching no current violation (prunable)."""
    budget = Counter(accepted)
    new: list[str] = []
    for k in keys:
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(k)
    stale = list(budget.elements())
    return new, stale


def apply(findings: list[Finding], entries: list[dict]) -> BaselineResult:
    budget = Counter(e.get("fingerprint") for e in entries)
    new: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    stale: list[dict] = []
    for e in entries:
        fp = e.get("fingerprint")
        if budget.get(fp, 0) > 0:  # unconsumed: matched no current finding
            budget[fp] -= 1
            stale.append(e)
    return BaselineResult(new=new, suppressed=suppressed, stale=stale)
