"""The named-lock inventory: every cross-thread mutex the repo relies on.

This is tripwire's ground truth. Each :class:`LockDecl` names one real lock
(where it lives, which attribute binds it, why it exists); the static pass
(:mod:`.lockcheck`) resolves ``with <lock>:`` sites against this table to
build the acquisition-order graph, and the runtime witness
(:mod:`fraud_detection_tpu.utils.lockdep`) instruments exactly these names
under ``LOCKDEP=1``. The two are cross-checked: a ``lockdep.lock("name")``
creation site with no declaration here — or a declaration whose creation
site disappeared — is a ``lock-inventory-drift`` violation, so the
inventory cannot silently rot.

The canonical acquisition order (outer → inner) the serving tier relies
on::

    lifeboat.flush  →  lifeboat.journal      (journal_staged / rotate)
    lifeboat.flush  →  drift.window          (snapshot cut materialization)
    longhaul.inherit →  lifeboat.flush       (segment merge + rebind publish)

Everything else is a leaf: held for short critical sections, never while
acquiring another named lock. ``ShardFront`` health state and the
micro-batcher's admission bookkeeping are deliberately NOT here — they are
asyncio event-loop-confined (single-threaded by construction), which is the
discipline that keeps them out of this table.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class LockDecl:
    #: canonical name — the string passed to ``lockdep.lock()``
    name: str
    #: repo-relative path of the module that creates the lock
    module: str
    #: owning class (subclasses inherit the binding); None = module global
    cls: str | None
    #: instance attribute / module global the lock binds to
    attr: str
    #: "lock" | "rlock"
    kind: str = "lock"
    #: what the lock protects + why (rendered into STATIC_ANALYSIS.md)
    purpose: str = ""


LOCKS: tuple[LockDecl, ...] = (
    LockDecl(
        "lifeboat.flush", "fraud_detection_tpu/lifeboat/boat.py",
        "Lifeboat", "flush_lock",
        purpose="couples {journal append → fused dispatch} on the flush "
        "path to {table+window read → seq capture → rotate} on the "
        "snapshot path — a snapshot cut can never split a flush from its "
        "journal record",
    ),
    LockDecl(
        "lifeboat.journal", "fraud_detection_tpu/lifeboat/journal.py",
        "Journal", "_lock",
        purpose="serializes record appends against the maintenance "
        "thread's fsync tick and snapshot-boundary rotation",
    ),
    LockDecl(
        "drift.window", "fraud_detection_tpu/monitor/drift.py",
        "DriftMonitor", "_lock",
        purpose="the fused flush donates the window/ledger buffers; a "
        "stats()/scrape reader racing the ingest thread would hand "
        "just-invalidated arrays to _drift_stats (MeshDriftMonitor "
        "inherits the binding)",
    ),
    LockDecl(
        "staging.pool", "fraud_detection_tpu/ops/scorer.py",
        "StagingPool", "_lock",
        purpose="guards the per-bucket staging freelist (acquire/release "
        "of pinned host slots on the ingest path)",
    ),
    LockDecl(
        "binlane.server", "fraud_detection_tpu/service/binlane.py",
        "BinaryIngestServer", "_lock",
        purpose="guards the binary-lane listener's connection set during "
        "accept/shed/close",
    ),
    LockDecl(
        "sentinel.conns", "fraud_detection_tpu/service/sentinel.py",
        "Sentinel", "_lock",
        purpose="guards the sentinel's accepted-connection registry",
    ),
    LockDecl(
        "taskq.broker", "fraud_detection_tpu/service/taskq.py",
        "SqliteBroker", "_lock",
        purpose="serializes task claim/ack against the shared sqlite "
        "connection",
    ),
    LockDecl(
        "netstore.pub", "fraud_detection_tpu/service/netserver.py",
        "StoreServer", "_pub_lock", kind="rlock",
        purpose="writes capture their row image and publish under one "
        "critical section so a slower writer can't publish an older row "
        "image with a newer seq (reentrant: _dispatch → _publish)",
    ),
    LockDecl(
        "netstore.conns", "fraud_detection_tpu/service/netserver.py",
        "StoreServer", "_conns_lock",
        purpose="guards the store's accepted-socket set",
    ),
    LockDecl(
        "lifecycle.store", "fraud_detection_tpu/lifecycle/store.py",
        "LifecycleStore", "_lock",
        purpose="serializes the conductor's CAS state machine + feedback "
        "pools on the shared DB connection (the promotion CAS rides this)",
    ),
    LockDecl(
        "lifecycle.reloader", "fraud_detection_tpu/lifecycle/swap.py",
        "ModelReloader", "_lock",
        purpose="makes hot-swap slot flips atomic against concurrent "
        "reload triggers",
    ),
    LockDecl(
        "watchtower.retrain", "fraud_detection_tpu/monitor/watchtower.py",
        "Watchtower", "_retrain_lock",
        purpose="latch check/set for retrain recommendations — concurrent "
        "status() evaluations must not enqueue duplicate retrain tasks",
    ),
    LockDecl(
        "longhaul.members", "fraud_detection_tpu/longhaul/membership.py",
        "DirectoryServer", "_members_lock",
        purpose="one critical section per membership mutation: epoch bump "
        "+ member-table update + durable members.json replace publish "
        "together, so no reader ever sees a new epoch with an old view "
        "(or vice versa)",
    ),
    LockDecl(
        "longhaul.inherit", "fraud_detection_tpu/longhaul/host.py",
        "HostServer", "_inherit_lock",
        purpose="serializes segment inheritance on the surviving host — "
        "state flip to INHERITING, peer journal replay, and the "
        "merge+rebind are one take-over; acquired BEFORE lifeboat.flush "
        "(the merge publishes under the flush lock so a snapshot cut "
        "can't split the rebind)",
    ),
)


def by_name() -> dict[str, LockDecl]:
    return {d.name: d for d in LOCKS}


def by_attr() -> dict[str, list[LockDecl]]:
    out: dict[str, list[LockDecl]] = {}
    for d in LOCKS:
        out.setdefault(d.attr, []).append(d)
    return out
