"""Pass 3: jaxpr contract prover — program-structure contracts per entrypoint.

meshcheck proves the *shapes* of every registered jitted entrypoint compose
at every mesh size; this pass proves their *program structure*. Each
entrypoint carries a declarative :class:`Contract`:

- **collective budget**: exactly which collective primitives, and how many
  static occurrences, the lowered program may contain (e.g.
  ``mesh.broadside_flush: {psum: 1}`` — the one model-axis partial-dot
  assembly — and ``{}`` for every single-device/zero-collective shard
  body). A refactor that smuggles an ``all_gather`` into a serving hot
  path fails CI with a named contract, not a perf mystery.
- **forbidden primitives**: host callbacks (``io_callback`` /
  ``pure_callback`` / ``debug_callback`` / ``outside_call``) and
  infeed/outfeed never appear on serving paths — a stray
  ``jax.debug.print`` left in a fused body is a sync per dispatch.
- **donation**: the state-threading args (drift window, ledger table,
  optimizer state) must actually be donatable — every donated leaf needs
  an identically-shaped/dtyped output to alias, and the serving jit site
  (``donate_site``) must still declare exactly the contracted
  ``donate_argnums`` (checked against the source AST, so dropping a
  donation in a refactor is caught even though the meshcheck builders wrap
  the raw body).
- **output dtypes**: the wire contract — e.g. quickwire's uint8 score
  codes, lantern's float16 reason values — pinned per flat output leaf.
- **pallas budget**: ``pallas_call`` is a first-class primitive in the
  contract — allowed (with an exact static count) where an entrypoint
  declares ``pallas_calls``, and counted as a ``forbidden-primitive``
  where it does not. A hand kernel sneaking into an uncontracted serving
  body, or a dispatch-gate regression silently dropping a contracted
  kernel back to the XLA fallback, both fail CI by name (the chisel
  entrypoints pin the TreeSHAP kernel this way).

The checker reuses meshcheck's registry and virtual CPU meshes: it builds
each entrypoint at its largest registered mesh size, traces it with
``jax.make_jaxpr`` (abstract — nothing executes), walks the closed jaxpr
recursively through ``pjit``/``shard_map``/``scan``/``cond`` inner jaxprs,
and diffs what it finds against the contract. Counts are *static
occurrences in the program text* (a psum inside a scan body counts once),
matching the hand-written jaxpr pins this pass replaces
(``tests/test_broadside.py`` one-psum → ``mesh.broadside_flush``).

Every registered entrypoint MUST have a contract — an uncovered
entrypoint is itself a violation, so the registry cannot lag meshcheck.
"""

from __future__ import annotations

import ast
import os
from collections import Counter
from dataclasses import dataclass, field
from typing import Iterable, Mapping

#: primitive-name → canonical collective name (psum traces as ``psum2``
#: under shard_map; reduce_scatter and psum_scatter are one budget line)
COLLECTIVE_CANON: Mapping[str, str] = {
    "psum": "psum",
    "psum2": "psum",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "reduce_scatter": "psum_scatter",
    "psum_scatter": "psum_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "pgather": "pgather",
    # NOT pbroadcast: it is shard_map's replication-annotation primitive
    # (inserted by the rep-rule rewrite, no data movement) — counting it
    # would fail legitimate programs on shard_map internals.
}

#: primitives that must never appear on a serving path: host round-trips
#: (callbacks) and raw device I/O
DEFAULT_FORBID: tuple[str, ...] = (
    "io_callback",
    "pure_callback",
    "debug_callback",
    "debug_print",
    "outside_call",
    "infeed",
    "outfeed",
)


@dataclass(frozen=True)
class DonateSite:
    """The serving jit site whose ``donate_argnums`` the contract pins."""

    module: str  # repo-relative path
    function: str
    argnums: tuple[int, ...]


@dataclass(frozen=True)
class Contract:
    entrypoint: str
    #: canonical collective name → exact static occurrence count; any
    #: collective not listed is budgeted at 0
    collectives: Mapping[str, int] = field(default_factory=dict)
    #: argnums of the *contract fn* (meshcheck builder order) that serving
    #: donates — checked for aliasing feasibility via lowering
    donate: tuple[int, ...] = ()
    #: the real jit site whose donate_argnums must match (AST-checked)
    donate_site: DonateSite | None = None
    forbid: tuple[str, ...] = DEFAULT_FORBID
    #: dtype names of the flat output leaves (None = unpinned)
    out_dtypes: tuple[str, ...] | None = None
    #: exact static ``pallas_call`` count the program may contain; 0
    #: (default) makes any pallas_call a forbidden-primitive violation
    pallas_calls: int = 0
    notes: str = ""


_CONTRACTS: dict[str, Contract] = {}


def register_contract(con: Contract) -> Contract:
    if con.entrypoint in _CONTRACTS:
        raise ValueError(f"duplicate contract for {con.entrypoint!r}")
    _CONTRACTS[con.entrypoint] = con
    return con


def get_contract(entrypoint: str) -> Contract | None:
    return _CONTRACTS.get(entrypoint)


def iter_contracts() -> list[Contract]:
    return list(_CONTRACTS.values())


# --------------------------------------------------------------------------
# Jaxpr walking
# --------------------------------------------------------------------------


def _subjaxprs(params: Mapping):
    from jax.core import ClosedJaxpr, Jaxpr

    for v in params.values():
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, Jaxpr):
                    yield x


def iter_eqns(jaxpr):
    """All equations of ``jaxpr``, recursing through every inner jaxpr
    (pjit, shard_map, scan, while, cond branches, custom_* rules)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn.params):
            yield from iter_eqns(sub)


def count_collectives(closed_jaxpr) -> Counter:
    """Static occurrence count of each canonical collective in the whole
    (recursively walked) program."""
    counts: Counter = Counter()
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        canon = COLLECTIVE_CANON.get(eqn.primitive.name)
        if canon is not None:
            counts[canon] += 1
    return counts


def forbidden_hits(closed_jaxpr, forbid: Iterable[str]) -> Counter:
    forbid = set(forbid)
    hits: Counter = Counter()
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in forbid:
            hits[eqn.primitive.name] += 1
    return hits


def count_pallas_calls(closed_jaxpr) -> int:
    """Static ``pallas_call`` occurrences in the whole recursively walked
    program (the kernel's inner jaxpr rides the eqn's ``jaxpr`` param, so
    :func:`iter_eqns` also walks INTO kernels — forbidden primitives
    can't hide inside one)."""
    return sum(
        1
        for eqn in iter_eqns(closed_jaxpr.jaxpr)
        if eqn.primitive.name == "pallas_call"
    )


# --------------------------------------------------------------------------
# Donation checks
# --------------------------------------------------------------------------


def _flat_avals(tree) -> list:
    import jax

    return jax.tree_util.tree_leaves(tree)


def _check_donation(con: Contract, fn, args) -> list[dict]:
    """Donation must be (a) accepted by jit for every leaf of every
    contracted argnum and (b) implementable — each donated leaf needs an
    identically shaped+dtyped output buffer to alias, or XLA silently
    degrades the donation to a copy."""
    import jax

    out: list[dict] = []
    jitted = jax.jit(fn, donate_argnums=con.donate)
    lowered = jitted.lower(*args)
    pos_info = lowered.args_info[0]  # (args, kwargs) pytree of ArgInfo
    for argnum in con.donate:
        infos = jax.tree_util.tree_leaves(pos_info[argnum])
        undonated = [i for i, inf in enumerate(infos) if not inf.donated]
        if undonated:
            out.append({
                "diagnostic": "dropped-donation",
                "detail": (
                    f"arg {argnum}: {len(undonated)}/{len(infos)} leaves "
                    f"not donated under donate_argnums={con.donate}"
                ),
            })
    out_leaves = Counter(
        (tuple(l.shape), str(l.dtype))
        for l in _flat_avals(jax.eval_shape(fn, *args))
    )
    donated_leaves = Counter(
        (tuple(l.shape), str(l.dtype))
        for argnum in con.donate
        for l in _flat_avals(args[argnum])
    )
    unaliasable = donated_leaves - out_leaves
    if unaliasable:
        out.append({
            "diagnostic": "donation-unimplementable",
            "detail": (
                "donated buffers with no identically shaped+dtyped output "
                f"to alias (donation degrades to a copy): "
                f"{sorted(unaliasable.elements())[:4]}"
            ),
        })
    return out


def _decorator_donate_argnums(fn_node: ast.AST) -> list[tuple[int, ...]]:
    """Every ``donate_argnums=(...)`` literal attached to ``fn_node`` —
    via ``@partial(jax.jit, ...)`` / ``@jax.jit(...)`` decorators or a
    ``jax.jit(..., donate_argnums=...)`` call in the body (the shard_map
    wrappers jit inside the function)."""
    found: list[tuple[int, ...]] = []
    nodes = list(getattr(fn_node, "decorator_list", []))
    nodes.extend(ast.walk(fn_node))
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            try:
                val = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(val, int):
                val = (val,)
            if isinstance(val, (tuple, list)):
                found.append(tuple(int(v) for v in val))
    return found


def _check_donate_site(site: DonateSite, root: str) -> list[dict]:
    path = os.path.join(root, site.module)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError) as e:
        return [{
            "diagnostic": "donate-site-drift",
            "detail": f"{site.module} unreadable/unparsable: {e}",
        }]
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == site.function
        ):
            declared = _decorator_donate_argnums(node)
            if tuple(site.argnums) in declared:
                return []
            return [{
                "diagnostic": "donate-site-drift",
                "detail": (
                    f"{site.module}::{site.function} declares "
                    f"donate_argnums {declared or 'nothing'}, contract "
                    f"requires {tuple(site.argnums)}"
                ),
            }]
    return [{
        "diagnostic": "donate-site-drift",
        "detail": f"{site.module}::{site.function} not found",
    }]


# --------------------------------------------------------------------------
# The checker
# --------------------------------------------------------------------------


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def check_contract(con: Contract, ep=None, root: str | None = None) -> dict:
    """Verify one contract against its entrypoint's traced program at the
    largest registered mesh size. Returns a result dict with named
    diagnostics (empty ``violations`` ⇔ the contract holds)."""
    import jax

    from fraud_detection_tpu.analysis import meshcheck
    from fraud_detection_tpu.parallel.mesh import MeshSpec, create_mesh

    root = root or _repo_root()
    if ep is None:
        ep = meshcheck._ENTRYPOINTS.get(con.entrypoint)
    res: dict = {
        "entrypoint": con.entrypoint,
        "mesh_size": None,
        "ok": False,
        "violations": [],
    }
    if ep is None:
        res["violations"].append({
            "diagnostic": "unknown-entrypoint",
            "detail": "contract has no matching meshcheck entrypoint",
        })
        return res
    size = ep.mesh_sizes[-1]
    d_ax, m_ax = size if isinstance(size, tuple) else (size, 1)
    res["mesh_size"] = f"{d_ax}x{m_ax}" if isinstance(size, tuple) else size
    try:
        devices = jax.devices()
        if len(devices) < d_ax * m_ax:
            raise RuntimeError(
                f"need {d_ax * m_ax} devices, have {len(devices)} — run "
                "under XLA_FLAGS=--xla_force_host_platform_device_count=8"
            )
        mesh = create_mesh(
            MeshSpec(data=d_ax, model=m_ax), devices=devices[: d_ax * m_ax]
        )
        fn, args = ep.build(mesh)
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # graftcheck: ignore[silent-except] — error is the result (reported + gates CI)
        res["violations"].append({
            "diagnostic": "trace-failure",
            "detail": f"{type(e).__name__}: {e}",
        })
        return res

    counts = count_collectives(closed)
    budget = dict(con.collectives)
    for name in sorted(set(counts) | set(budget)):
        want, got = budget.get(name, 0), counts.get(name, 0)
        if got == want:
            continue
        if want == 0:
            diag = "undeclared-collective"
        elif got == 0:
            diag = "missing-collective"
        else:
            diag = "collective-count"
        res["violations"].append({
            "diagnostic": diag,
            "detail": f"{name}: contract allows {want}, program has {got}",
        })

    hits = forbidden_hits(closed, con.forbid)
    for name, n in sorted(hits.items()):
        res["violations"].append({
            "diagnostic": "forbidden-primitive",
            "detail": f"{name} appears {n}x (host sync on a serving path)",
        })

    pallas_got = count_pallas_calls(closed)
    if pallas_got != con.pallas_calls:
        if con.pallas_calls == 0:
            res["violations"].append({
                "diagnostic": "forbidden-primitive",
                "detail": (
                    f"pallas_call appears {pallas_got}x — not budgeted in "
                    "this entrypoint's contract (declare pallas_calls)"
                ),
            })
        elif pallas_got == 0:
            res["violations"].append({
                "diagnostic": "missing-pallas",
                "detail": (
                    f"contract budgets {con.pallas_calls} pallas_call(s), "
                    "program has none — the dispatch gate fell back to XLA"
                ),
            })
        else:
            res["violations"].append({
                "diagnostic": "pallas-count",
                "detail": (
                    f"contract budgets {con.pallas_calls} pallas_call(s), "
                    f"program has {pallas_got}"
                ),
            })

    if con.out_dtypes is not None:
        got_dtypes = tuple(str(v.aval.dtype) for v in closed.jaxpr.outvars)
        if got_dtypes != tuple(con.out_dtypes):
            res["violations"].append({
                "diagnostic": "output-dtype",
                "detail": (
                    f"contract pins {tuple(con.out_dtypes)}, program "
                    f"returns {got_dtypes}"
                ),
            })

    if con.donate:
        try:
            res["violations"].extend(_check_donation(con, fn, args))
        except Exception as e:  # graftcheck: ignore[silent-except] — error is the result (reported + gates CI)
            res["violations"].append({
                "diagnostic": "dropped-donation",
                "detail": f"lowering failed: {type(e).__name__}: {e}",
            })
    if con.donate_site is not None:
        res["violations"].extend(_check_donate_site(con.donate_site, root))

    res["ok"] = not res["violations"]
    return res


def verify_contracts(
    names: Iterable[str] | None = None, root: str | None = None
) -> list[dict]:
    """Check every contract, plus coverage: a meshcheck entrypoint with no
    contract is a violation (the registry must ride the meshcheck one)."""
    from fraud_detection_tpu.analysis import meshcheck

    wanted = set(names) if names is not None else None
    results: list[dict] = []
    for con in iter_contracts():
        if wanted is not None and con.entrypoint not in wanted:
            continue
        results.append(check_contract(con, root=root))
    if wanted is None:
        for ep in meshcheck.iter_entrypoints():
            if ep.name not in _CONTRACTS:
                results.append({
                    "entrypoint": ep.name,
                    "mesh_size": None,
                    "ok": False,
                    "violations": [{
                        "diagnostic": "uncovered-entrypoint",
                        "detail": (
                            "registered in meshcheck but has no contract "
                            "— declare its collective/donation/wire budget"
                        ),
                    }],
                })
    results.sort(key=lambda r: r["entrypoint"])
    return results


def violation_keys(results: list[dict]) -> list[str]:
    """Stable baseline keys, one per violation: ``entrypoint:diagnostic``."""
    return [
        f"{r['entrypoint']}:{v['diagnostic']}"
        for r in results
        for v in r["violations"]
    ]


# --------------------------------------------------------------------------
# The contract table — one entry per registered entrypoint.
#
# Collective budgets and wire dtypes are the *declared design*, not a
# recording: the serving flushes are zero-collective by construction (the
# bitwise N-shard contract), broadside's 2-D flush spends exactly one
# model-axis psum, and the training epochs spend their documented
# 2004.13336 budgets. Changing any of these is an API change and must
# edit the contract in the same PR.
# --------------------------------------------------------------------------

_DRIFT = "fraud_detection_tpu/monitor/drift.py"
_SHARDFLUSH = "fraud_detection_tpu/mesh/shardflush.py"
_RETRAIN = "fraud_detection_tpu/mesh/retrain.py"

#: the six DriftWindow leaves, in pytree order — every fused flush returns
#: the folded window after its primary outputs
_WINDOW = ("float32",) * 6

for _con in (
    # -- stateless numerics ------------------------------------------------
    Contract("scorer.score", out_dtypes=("float32",)),
    Contract("telemetry.instrumented_score", out_dtypes=("float32",)),
    Contract("logistic.lbfgs_fit", out_dtypes=("float32", "float32")),
    Contract(
        "logistic.sgd_epoch",
        collectives={"psum": 3},
        out_dtypes=("float32",) * 4,
        notes="DP allreduce: coef grad, intercept grad, weight-sum "
        "normalizer — one scan body, counted statically",
    ),
    Contract(
        "gbt.boost_step",
        collectives={"psum": 4},
        out_dtypes=("int32", "int32", "float32"),
        notes="histogram psums per boost level (segment impl), trees "
        "replicated out",
    ),
    Contract("gbt.predict_proba", out_dtypes=("float32",)),
    Contract("smote.oversample", out_dtypes=("float32", "int32")),
    Contract("linear_shap.batch", out_dtypes=("float32",)),
    Contract("tree_shap.batch", out_dtypes=("float32",)),
    Contract("scaler.fit_transform", out_dtypes=("float32",)),
    Contract(
        "lifecycle.gate_eval", out_dtypes=("float32",) * 4,
        notes="one fused program per gate slice; NaN fails closed host-side",
    ),
    # -- watchtower --------------------------------------------------------
    Contract(
        "watchtower.baseline_profile", out_dtypes=("float32", "float32")
    ),
    Contract(
        "watchtower.window_update",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_window_update", (0,)),
        out_dtypes=_WINDOW,
    ),
    # -- fused serving flushes (single device): zero collectives, window
    # donated through, wire dtypes pinned ---------------------------------
    Contract(
        "fastlane.flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush", (0,)),
        out_dtypes=("float32",) + _WINDOW,
    ),
    Contract(
        "quickwire.flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush_quant", (0,)),
        out_dtypes=("uint8",) + _WINDOW,
        notes="uint8 = the compressed d2h return wire",
    ),
    Contract(
        "lantern.flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush_explain", (0,)),
        out_dtypes=("float32", "uint8", "float32") + _WINDOW,
        notes="scores, top-k reason indices (uint8), reason values",
    ),
    Contract(
        "evergreen.flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush_quant_explain", (0,)),
        out_dtypes=("uint8", "uint8", "float16") + _WINDOW,
        notes="GBT quant wire: uint8 scores, uint8 reason idx, f16 values",
    ),
    Contract(
        "ledger.flush",
        donate=(0, 1),
        donate_site=DonateSite(_DRIFT, "_fused_flush_ledger", (0, 1)),
        out_dtypes=("float32",) + _WINDOW
        + ("float32", "float32", "uint32", "float32", "float32"),
        notes="window AND entity table donated through one dispatch",
    ),
    Contract(
        "broadside.flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush_wide", (0,)),
        out_dtypes=("float32", "uint8", "float32") + _WINDOW,
    ),
    # -- chisel: the TreeSHAP Pallas-kernel bodies. Exactly ONE pallas_call
    # budgeted per program (the tree loop rides the kernel grid, not N
    # calls); zero collectives preserved; wire dtypes identical to the XLA
    # bodies they replace — a silent fallback to XLA is a missing-pallas
    # violation, a second kernel creeping in is a count violation ----------
    Contract(
        "chisel.tree_shap",
        out_dtypes=("float32",),
        pallas_calls=1,
        notes="the standalone TreeSHAP batch forced onto the chisel "
        "kernel — same wire as tree_shap.batch",
    ),
    Contract(
        "chisel.lantern_flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush_explain", (0,)),
        out_dtypes=("float32", "uint8", "float32") + _WINDOW,
        pallas_calls=1,
        notes="GBT f32-wire explain flush on the kernel body — wire and "
        "donation identical to lantern.flush",
    ),
    Contract(
        "chisel.evergreen_flush",
        donate=(0,),
        donate_site=DonateSite(_DRIFT, "_fused_flush_quant_explain", (0,)),
        out_dtypes=("uint8", "uint8", "float16") + _WINDOW,
        pallas_calls=1,
        notes="GBT quant-wire explain flush on the kernel body — wire and "
        "donation identical to evergreen.flush",
    ),
    # -- mesh serving flushes: ONE shard_map dispatch, zero collectives
    # (the bitwise N-shard contract), per-shard windows donated ------------
    Contract(
        "mesh.sharded_flush",
        donate=(0,),
        donate_site=DonateSite(_SHARDFLUSH, "_sharded_flush", (0,)),
        out_dtypes=("float32",) + _WINDOW,
    ),
    Contract(
        "mesh.quickwire_flush",
        donate=(0,),
        donate_site=DonateSite(_SHARDFLUSH, "_sharded_flush_quant", (0,)),
        out_dtypes=("uint8",) + _WINDOW,
    ),
    Contract(
        "mesh.lantern_flush",
        donate=(0,),
        donate_site=DonateSite(_SHARDFLUSH, "_sharded_flush_explain", (0,)),
        out_dtypes=("float32", "uint8", "float32") + _WINDOW,
    ),
    Contract(
        "mesh.evergreen_flush",
        donate=(0,),
        donate_site=DonateSite(
            _SHARDFLUSH, "_sharded_flush_quant_explain", (0,)
        ),
        out_dtypes=("uint8", "uint8", "float16") + _WINDOW,
    ),
    Contract(
        "mesh.ledger_flush",
        donate=(0, 1),
        donate_site=DonateSite(_SHARDFLUSH, "_sharded_flush_ledger", (0, 1)),
        out_dtypes=("float32",) + _WINDOW
        + ("float32", "float32", "uint32", "float32", "float32"),
        notes="rows placement-aligned host-side — never a device collective",
    ),
    Contract(
        "mesh.broadside_flush",
        collectives={"psum": 1},
        donate=(0,),
        donate_site=DonateSite(_SHARDFLUSH, "_sharded_flush_wide", (0,)),
        out_dtypes=("float32", "uint8", "float32") + _WINDOW,
        notes="THE one-psum pin (was tests/test_broadside.py's inline "
        "jaxpr assert): exactly one model-axis psum assembles the widened "
        "block; any other collective on the wide hot path is a violation",
    ),
    # -- training epochs: the declared 2004.13336 collective spend ---------
    Contract(
        "mesh.sharded_update",
        collectives={"all_gather": 1, "psum": 2, "psum_scatter": 1},
        donate=(0, 1),
        donate_site=DonateSite(_RETRAIN, "_sharded_update_epoch", (0, 1)),
        out_dtypes=("float32",) * 4,
        notes="full-vector all_gather per forward, grads psum_scatter'd "
        "onto owning shards, intercept psums",
    ),
    Contract(
        "mesh.wide_update",
        collectives={"all_gather": 1, "psum": 4, "psum_scatter": 1},
        donate=(0, 1, 2, 3),
        donate_site=DonateSite(_RETRAIN, "_wide_update_epoch", (0, 1, 2, 3)),
        out_dtypes=("float32",) * 6,
        notes="2-D: model-axis psum assembles the widened logit; data-axis "
        "grad reduction + scatter onto column owners",
    ),
    # -- longhaul fleet MapReduce: map bodies provably collective-free,
    # merge bodies carry the fleet's ENTIRE collective budget --------------
    Contract(
        "longhaul.partial_pool",
        out_dtypes=("float32",) * 5,
        notes="one host's pool partials (map side) — zero collectives by "
        "construction; the reduce rides the transport (mesh psum under "
        "jax.distributed, rank-order socket sum otherwise)",
    ),
    Contract(
        "longhaul.fleet_grad",
        out_dtypes=("float32", "float32"),
        notes="one host's un-normalized gradient sums — zero collectives; "
        "objective scaling happens host-side AFTER the fleet merge so "
        "every host applies identical reduced floats",
    ),
    Contract(
        "longhaul.pool_merge",
        collectives={"psum": 5},
        out_dtypes=("float32",) * 5,
        notes="one psum per pool component (n, n_pos, score_sum, Σx, Σx²) "
        "over the hosts axis; under jax.distributed this axis spans "
        "processes and the SAME program reduces over DCN — proved here on "
        "the single-process degenerate mesh",
    ),
    Contract(
        "longhaul.grad_merge",
        collectives={"psum": 2},
        out_dtypes=("float32", "float32"),
        notes="coef block + intercept: the whole per-step collective "
        "footprint of fleet SGD (2004.13336 at host level)",
    ),
):
    register_contract(_con)
