"""graftcheck: JAX-aware static analysis + virtual-mesh shape verification.

Four passes, one CLI (``python -m fraud_detection_tpu.analysis`` or the
``graftcheck`` console script):

- **Pass 1 — AST lint engine** (:mod:`.core`, :mod:`.rules_jax`,
  :mod:`.rules_service`): a pluggable rule registry walked over every
  module's AST. The rules encode the failure modes pytest-on-CPU cannot see:
  host-device syncs inside jit regions, Python-scalar closure captures that
  trigger recompile storms, tracer leakage into globals, missing donation on
  state-threading jits, and the service-tier analogues (sockets without
  timeouts, silent exception swallowing, non-daemon threads that are never
  joined).
- **Pass 2 — virtual-mesh shape verifier** (:mod:`.meshcheck`): every
  registered jitted entrypoint is abstractly evaluated with
  ``jax.eval_shape`` under CPU meshes of sizes 1/2/8, proving that shapes
  and named shardings compose at every mesh size before code ever reaches a
  real TPU topology.
- **Pass 3 — jaxpr contract prover** (:mod:`.contracts`, ``--contracts``):
  each registered entrypoint carries a declarative contract — allowed
  collectives by primitive and count, required donations, forbidden host
  callbacks, pinned wire dtypes — and the checker traces the entrypoint on
  the virtual mesh, walks the closed jaxpr recursively, and diffs the
  program against the contract.
- **Pass 4 — lock discipline** (:mod:`.lockcheck`, :mod:`.locknames`,
  ``--contracts`` runs it too): the named-lock inventory, a static
  acquisition-order graph with cycle detection, inventory drift against
  the ``lockdep`` creation sites, and the ``blocking-under-lock`` /
  ``lock-in-jit`` lint rules. The runtime half is
  :mod:`fraud_detection_tpu.utils.lockdep` (``LOCKDEP=1``).

Findings are reported as text or JSON (:mod:`.report`) and gated against a
checked-in baseline (:mod:`.baseline`); ``tests/test_static_analysis.py``
asserts the repo itself is clean modulo that baseline, and CI runs the CLI
on every push.
"""

from fraud_detection_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleInfo,
    Rule,
    Severity,
    analyze_file,
    analyze_paths,
    iter_rules,
    register_rule,
)

# Importing the rule modules populates the registry.
from fraud_detection_tpu.analysis import lockcheck  # noqa: F401,E402
from fraud_detection_tpu.analysis import rules_artifacts  # noqa: F401,E402
from fraud_detection_tpu.analysis import rules_jax  # noqa: F401,E402
from fraud_detection_tpu.analysis import rules_monitoring  # noqa: F401,E402
from fraud_detection_tpu.analysis import rules_perf  # noqa: F401,E402
from fraud_detection_tpu.analysis import rules_service  # noqa: F401,E402
