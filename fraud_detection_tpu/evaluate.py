"""Offline evaluation: confusion matrix, classification report, plots.

Rebuild of evaluate_model.py:1-63 — prints the report and renders
``plots/confusion_matrix.png`` + ``plots/roc_curve.png`` (AUC in the
legend) — with the metrics computed on device. The test split is
recomputed deterministically from the data CSV (same seed as train.py)
instead of the reference's preprocessed-npz handoff.
"""

from __future__ import annotations

import argparse
import logging
import os

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.data.loader import load_creditcard_csv, stratified_split
from fraud_detection_tpu.models import load_any_model
from fraud_detection_tpu.models.logistic import FraudLogisticModel
from fraud_detection_tpu.ops.metrics import (
    auc_roc,
    binary_classification_report,
    confusion_matrix,
    roc_curve_points,
)

log = logging.getLogger("fraud_detection_tpu.evaluate")


def _load_model(model_dir: str):
    """Family-agnostic: native artifacts of either model family, else the
    reference's joblib layout (logistic only)."""
    if os.path.exists(os.path.join(model_dir, "model.npz")):
        return load_any_model(model_dir)
    return FraudLogisticModel.load_joblib(
        os.path.join(model_dir, "logistic_model.joblib"),
        os.path.join(model_dir, "scaler.joblib"),
        os.path.join(model_dir, "feature_names.json"),
    )


def evaluate(
    data_csv: str | None = None,
    model_dir: str = "models",
    plots_dir: str = "plots",
    seed: int = 42,
    threshold: float = 0.5,
) -> dict:
    data_csv = data_csv or config.data_csv()
    x, y, _ = load_creditcard_csv(data_csv)
    _, test_idx = stratified_split(y, 0.2, seed)
    x_test, y_test = x[test_idx], y[test_idx]

    model = _load_model(model_dir)
    scores = model.scorer.predict_proba(x_test)
    pred = (scores >= threshold).astype(np.int32)

    cm = np.asarray(confusion_matrix(y_test, pred)).astype(int)
    report = binary_classification_report(y_test, pred)
    auc = float(auc_roc(scores, y_test))

    print("Confusion matrix [[tn fp] [fn tp]]:")
    print(cm)
    print("\nClassification report:")
    for cls in ("0", "1"):
        r = report[cls]
        print(
            f"  class {cls}: precision {r['precision']:.3f} recall {r['recall']:.3f} "
            f"f1 {r['f1-score']:.3f} support {int(r['support'])}"
        )
    print(f"  accuracy {report['accuracy']:.4f}")
    print(f"\nAUC-ROC: {auc:.4f}")

    os.makedirs(plots_dir, exist_ok=True)
    _render_plots(cm, scores, y_test, auc, plots_dir)
    return {"auc": auc, "confusion_matrix": cm.tolist(), "report": report}


def _render_plots(cm, scores, y_test, auc, plots_dir: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(5, 4))
    im = ax.imshow(cm, cmap="Blues")
    for (i, j), v in np.ndenumerate(cm):
        ax.text(j, i, f"{v:,}", ha="center", va="center",
                color="white" if v > cm.max() / 2 else "black")
    ax.set_xlabel("Predicted")
    ax.set_ylabel("Actual")
    ax.set_xticks([0, 1])
    ax.set_yticks([0, 1])
    ax.set_title("Confusion Matrix")
    fig.colorbar(im)
    fig.tight_layout()
    fig.savefig(os.path.join(plots_dir, "confusion_matrix.png"), dpi=120)
    plt.close(fig)

    fpr, tpr, _ = roc_curve_points(scores, y_test, num_thresholds=400)
    fig, ax = plt.subplots(figsize=(5, 4))
    ax.plot(np.asarray(fpr), np.asarray(tpr), label=f"ROC (AUC = {auc:.4f})")
    ax.plot([0, 1], [0, 1], "k--", lw=0.8)
    ax.set_xlabel("False positive rate")
    ax.set_ylabel("True positive rate")
    ax.set_title("ROC curve")
    ax.legend(loc="lower right")
    fig.tight_layout()
    fig.savefig(os.path.join(plots_dir, "roc_curve.png"), dpi=120)
    plt.close(fig)
    log.info("plots written to %s/", plots_dir)


def main(argv=None):
    config.apply_device_backend()  # DEVICE=cpu runs without the TPU tunnel
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None)
    ap.add_argument("--model-dir", default="models")
    ap.add_argument("--plots-dir", default="plots")
    ap.add_argument("--seed", type=int, default=42)
    a = ap.parse_args(argv)
    evaluate(a.data, a.model_dir, a.plots_dir, a.seed)


if __name__ == "__main__":
    main()
