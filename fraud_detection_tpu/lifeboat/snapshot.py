"""Versioned, CRC-stamped snapshots of device-resident serving state.

One snapshot file (``lifeboat-{seq:012d}.snap``) captures everything a
warm restart needs to rebuild the donated pytrees: the ledger's hashed
entity table, the drift window (and the mesh tier's per-shard windows when
present), the :class:`~fraud_detection_tpu.ledger.state.LedgerSpec`
geometry it was built against, and the bookkeeping that anchors the
journal replay — the **flush sequence number** the table covers, the model
slot version serving it, and the spec hash a loader must match.

Layout (little-endian, every section CRC-guarded so truncation at ANY
boundary is detected, never trusted)::

    magic "LBS1" | version u16 | header_len u32 | header JSON
    | header_crc u32 | payload (npz bytes) | payload_crc u32

The header JSON carries ``{seq, slot_version, spec_hash, created_at,
rows_seen, payload_len}``; the payload is a plain ``np.savez`` archive of
the arrays. Files land via the shared atomic helper (``ckpt/atomic``:
tmp → fsync → rename → dir fsync), and K generations are retained — a
torn newest file (crash mid-write on a filesystem without the rename
guarantee, or plain disk corruption) falls back one generation instead of
taking recovery down.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import re
import struct
import time
import zlib
from dataclasses import dataclass

import numpy as np

from fraud_detection_tpu.ckpt.atomic import atomic_write_bytes, savez_bytes
from fraud_detection_tpu.ledger.state import LedgerSpec, LedgerState
from fraud_detection_tpu.monitor.drift import DriftWindow

log = logging.getLogger("fraud_detection_tpu.lifeboat")

MAGIC = b"LBS1"
VERSION = 1

SNAPSHOT_RE = re.compile(r"^lifeboat-(\d{12})\.snap$")

#: sanity bound on the declared header length — a torn length field must
#: not make the reader allocate gigabytes
_MAX_HEADER = 1 << 20


class TornSnapshot(Exception):
    """The file is truncated, CRC-corrupt, or structurally invalid —
    recovery must fall back a generation, never trust partial bytes."""


def spec_hash(spec: LedgerSpec) -> str:
    """Stable 16-hex-char identity of the ledger geometry a snapshot was
    taken under. A snapshot from a DIFFERENT spec (resized table, new decay
    horizon, different clock origin) must be refused loudly — replaying it
    through mismatched geometry would silently scramble every entity's
    aggregates."""
    null = np.asarray(spec.null_features, np.float32).tobytes()
    key = (
        f"{spec.n_base}|{spec.slots}|{spec.halflife_s!r}|{spec.amount_col}"
        f"|{spec.ts_origin!r}|".encode() + null
    )
    return hashlib.sha256(key).hexdigest()[:16]


def snapshot_path(directory: str, seq: int) -> str:
    return os.path.join(directory, f"lifeboat-{seq:012d}.snap")


@dataclass
class Snapshot:
    """A loaded, CRC-valid snapshot."""

    seq: int
    slot_version: int | None
    spec_hash: str
    created_at: float
    rows_seen: int
    spec: LedgerSpec
    ledger: LedgerState
    window: DriftWindow | None
    shard_window: DriftWindow | None
    path: str


def _pack_payload(
    spec: LedgerSpec,
    ledger: LedgerState,
    window: DriftWindow | None,
    shard_window: DriftWindow | None,
) -> bytes:
    arrays: dict[str, np.ndarray] = {
        "spec_n_base": np.int64(spec.n_base),
        "spec_slots": np.int64(spec.slots),
        "spec_halflife_s": np.float64(spec.halflife_s),
        "spec_amount_col": np.int64(spec.amount_col),
        "spec_ts_origin": np.float64(spec.ts_origin),
        "spec_null_features": np.asarray(spec.null_features, np.float32),
        "acc": np.asarray(ledger.acc, np.float32),
        "last_ts": np.asarray(ledger.last_ts, np.float32),
        "fingerprint": np.asarray(ledger.fingerprint, np.uint32),
        "collisions": np.asarray(ledger.collisions, np.float32),
        "evictions": np.asarray(ledger.evictions, np.float32),
    }
    if window is not None:
        for name, leaf in zip(DriftWindow._fields, window):
            arrays[f"win_{name}"] = np.asarray(leaf, np.float32)
    if shard_window is not None:
        for name, leaf in zip(DriftWindow._fields, shard_window):
            arrays[f"sw_{name}"] = np.asarray(leaf, np.float32)
    return savez_bytes(**arrays)


def _unpack_window(z, prefix: str) -> DriftWindow | None:
    first = f"{prefix}{DriftWindow._fields[0]}"
    if first not in z:
        return None
    return DriftWindow(
        *(np.asarray(z[f"{prefix}{name}"]) for name in DriftWindow._fields)
    )


def write_snapshot(
    directory: str,
    seq: int,
    spec: LedgerSpec,
    ledger: LedgerState,
    window: DriftWindow | None = None,
    shard_window: DriftWindow | None = None,
    slot_version: int | None = None,
    rows_seen: int = 0,
    created_at: float | None = None,
) -> str:
    """Serialize and atomically land one generation. Returns the path."""
    payload = _pack_payload(spec, ledger, window, shard_window)
    header = json.dumps(
        {
            "seq": int(seq),
            "slot_version": slot_version,
            "spec_hash": spec_hash(spec),
            "created_at": float(created_at if created_at is not None else time.time()),
            "rows_seen": int(rows_seen),
            "payload_len": len(payload),
        },
        sort_keys=True,
    ).encode()
    blob = b"".join(
        (
            MAGIC,
            struct.pack("<H", VERSION),
            struct.pack("<I", len(header)),
            header,
            struct.pack("<I", zlib.crc32(header)),
            payload,
            struct.pack("<I", zlib.crc32(payload)),
        )
    )
    os.makedirs(directory, exist_ok=True)
    return atomic_write_bytes(snapshot_path(directory, seq), blob)


def load_snapshot(path: str) -> Snapshot:
    """Parse + CRC-validate one snapshot file. Raises :class:`TornSnapshot`
    on ANY truncation or corruption — a partial table must never bind."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise TornSnapshot(f"unreadable snapshot {path}: {e}") from e
    if len(blob) < len(MAGIC) + 2 + 4:
        raise TornSnapshot(f"{path}: truncated before the header ({len(blob)} bytes)")
    if blob[:4] != MAGIC:
        raise TornSnapshot(f"{path}: bad magic {blob[:4]!r}")
    (version,) = struct.unpack_from("<H", blob, 4)
    if version != VERSION:
        raise TornSnapshot(f"{path}: unsupported snapshot version {version}")
    (header_len,) = struct.unpack_from("<I", blob, 6)
    if header_len > _MAX_HEADER:
        raise TornSnapshot(f"{path}: implausible header length {header_len}")
    off = 10
    if len(blob) < off + header_len + 4:
        raise TornSnapshot(f"{path}: truncated inside the header")
    header_bytes = blob[off : off + header_len]
    off += header_len
    (header_crc,) = struct.unpack_from("<I", blob, off)
    off += 4
    if zlib.crc32(header_bytes) != header_crc:
        raise TornSnapshot(f"{path}: header CRC mismatch")
    try:
        header = json.loads(header_bytes)
        payload_len = int(header["payload_len"])
    except (ValueError, KeyError, TypeError) as e:
        raise TornSnapshot(f"{path}: unparseable header: {e}") from e
    if len(blob) < off + payload_len + 4:
        raise TornSnapshot(f"{path}: truncated inside the payload")
    payload = blob[off : off + payload_len]
    off += payload_len
    (payload_crc,) = struct.unpack_from("<I", blob, off)
    if zlib.crc32(payload) != payload_crc:
        raise TornSnapshot(f"{path}: payload CRC mismatch")
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            spec = LedgerSpec(
                n_base=int(z["spec_n_base"]),
                slots=int(z["spec_slots"]),
                halflife_s=float(z["spec_halflife_s"]),
                amount_col=int(z["spec_amount_col"]),
                ts_origin=float(z["spec_ts_origin"]),
                null_features=np.asarray(z["spec_null_features"], np.float32),
            )
            ledger = LedgerState(
                acc=np.asarray(z["acc"], np.float32),
                last_ts=np.asarray(z["last_ts"], np.float32),
                fingerprint=np.asarray(z["fingerprint"], np.uint32),
                collisions=np.asarray(z["collisions"], np.float32),
                evictions=np.asarray(z["evictions"], np.float32),
            )
            window = _unpack_window(z, "win_")
            shard_window = _unpack_window(z, "sw_")
    except (ValueError, KeyError, OSError) as e:
        # CRC passed but the archive is malformed — treat as torn: the
        # loader's job is a binary trust decision, not forensics
        raise TornSnapshot(f"{path}: corrupt payload archive: {e}") from e
    return Snapshot(
        seq=int(header["seq"]),
        slot_version=header.get("slot_version"),
        spec_hash=str(header["spec_hash"]),
        created_at=float(header.get("created_at", 0.0)),
        rows_seen=int(header.get("rows_seen", 0)),
        spec=spec,
        ledger=ledger,
        window=window,
        shard_window=shard_window,
        path=path,
    )


def list_snapshots(directory: str) -> list[tuple[int, str]]:
    """(seq, path) pairs, oldest → newest."""
    out: list[tuple[int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = SNAPSHOT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def load_latest(directory: str) -> tuple[Snapshot | None, int]:
    """Newest CRC-valid snapshot, falling back a generation per torn file.
    Returns ``(snapshot_or_None, generations_skipped)``."""
    skipped = 0
    for seq, path in reversed(list_snapshots(directory)):
        try:
            return load_snapshot(path), skipped
        except TornSnapshot as e:
            skipped += 1
            log.error(
                "lifeboat: snapshot generation %d is torn (%s) — falling "
                "back a generation",
                seq,
                e,
            )
    return None, skipped


def prune_snapshots(directory: str, keep: int) -> list[int]:
    """Drop all but the newest ``keep`` generations; returns pruned seqs."""
    snaps = list_snapshots(directory)
    pruned: list[int] = []
    for seq, path in snaps[: max(0, len(snaps) - max(keep, 1))]:
        try:
            os.unlink(path)
            pruned.append(seq)
        except OSError:  # graftcheck: ignore[silent-except] — already gone
            pass
    return pruned
