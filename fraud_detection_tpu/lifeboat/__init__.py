"""lifeboat: crash-consistent durability + warm restart for device-resident
serving state (ISSUE 15).

The ledger's per-entity velocity table and the drift windows live ONLY in
donated device pytrees — a crash erases every aggregate accumulated since
the train-time stamp. This package is the durability layer: CRC-stamped
generational snapshots (:mod:`.snapshot`), a write-ahead entity journal
(:mod:`.journal`), the traced-body replay that rebuilds state on restart
(:mod:`.recovery`), and the :class:`~.boat.Lifeboat` manager that wires
them into the serving process. See docs/runbooks/DisasterRecovery.md.
"""

from fraud_detection_tpu.lifeboat.boat import IDLE, READY, RECOVERING, Lifeboat  # noqa: F401
from fraud_detection_tpu.lifeboat.journal import (  # noqa: F401
    Journal,
    JournalTail,
    list_journals,
    read_journal_file,
    read_tail,
)
from fraud_detection_tpu.lifeboat.recovery import (  # noqa: F401
    RecoveryReport,
    recover,
    replay_records,
    replay_rows,
)
from fraud_detection_tpu.lifeboat.snapshot import (  # noqa: F401
    Snapshot,
    TornSnapshot,
    list_snapshots,
    load_latest,
    load_snapshot,
    spec_hash,
    write_snapshot,
)
