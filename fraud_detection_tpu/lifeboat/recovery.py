"""Warm restart: snapshot + journal tail → the recovered entity table.

The replay discipline is the SERVING one, exactly: journal records fold in
sequence (= dispatch) order, **one jitted dispatch per record**, through a
trace of the SAME ``ledger/features._ledger_read_update`` body the fused
serving flush dispatches. The per-record framing matters as much as the
shared body: the traced fold decays each dispatch's slots to a
per-dispatch anchor, so it is order-insensitive *within* a dispatch but
segmentation-sensitive *across* dispatches — replaying a flattened tail in
arbitrary fixed-size chunks lands ulp-level off the table the serving
process computed. One body + one segmentation means recovery **cannot**
skew from serving, and the chaos invariant pins the recovered table
bitwise against both an independent replay of the same snapshot + journal
bytes and a clean uninterrupted serve of the identical traffic.

Refusal is loud: a snapshot whose spec hash does not match the served
model's :class:`~fraud_detection_tpu.ledger.state.LedgerSpec` is rejected
(the caller keeps serving from the train-time stamp), never reinterpreted
through mismatched hash geometry.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

import numpy as np

from fraud_detection_tpu.ledger.replay import REPLAY_BATCH
from fraud_detection_tpu.ledger.state import (
    LedgerSpec,
    LedgerState,
    _MULT,
    device_state,
)
from fraud_detection_tpu.lifeboat import journal as journal_mod
from fraud_detection_tpu.lifeboat import snapshot as snapshot_mod
from fraud_detection_tpu.monitor.drift import DriftWindow
from fraud_detection_tpu.range.faults import fire

log = logging.getLogger("fraud_detection_tpu.lifeboat")


def slots_for(fp: np.ndarray, log2_slots: int) -> np.ndarray:
    """Vectorized multiply-shift slot hash — the array twin of
    ``ledger.state.entity_slot``, bit-identical per element."""
    prod = (fp.astype(np.uint64) * np.uint64(_MULT)) & np.uint64(0xFFFFFFFF)
    return (prod >> np.uint64(32 - log2_slots)).astype(np.int32)


def replay_rows(
    spec: LedgerSpec,
    state: LedgerState | None,
    fp: np.ndarray,
    ts: np.ndarray,
    amount: np.ndarray,
    batch: int = REPLAY_BATCH,
) -> LedgerState:
    """Fold loose journal triples onto ``state`` through the traced body,
    in timestamp order (stable sort — same-ts rows keep input order), in
    fixed-size batches. Deterministic (two replays of the same bytes are
    bitwise-identical), but NOT the recovery discipline: warm restart uses
    :func:`replay_records`, whose per-record segmentation is what makes
    recovery bitwise-equal to serving. This generic form serves tooling
    that has rows without flush framing."""
    import jax.numpy as jnp

    n = int(fp.shape[0])
    dev = device_state(state, spec.slots)
    if n == 0:
        return LedgerState(*(np.asarray(leaf) for leaf in dev))
    order = np.argsort(np.asarray(ts, np.float32), kind="stable")
    fp_o = np.ascontiguousarray(np.asarray(fp, np.uint32)[order])
    ts_o = np.ascontiguousarray(np.asarray(ts, np.float32)[order])
    amt_o = np.ascontiguousarray(np.asarray(amount, np.float32)[order])
    slots_o = slots_for(fp_o, spec.log2_slots)
    has_o = (fp_o != 0).astype(np.float32)

    step = _jitted_step()
    null = jnp.asarray(spec.null_features)
    hl = jnp.float32(spec.halflife_s)
    for lo in range(0, n, batch):
        hi = min(lo + batch, n)
        pad = batch - (hi - lo)
        sl = np.pad(slots_o[lo:hi], (0, pad))
        fb = np.pad(fp_o[lo:hi], (0, pad))
        tb = np.pad(ts_o[lo:hi], (0, pad))
        ab = np.pad(amt_o[lo:hi], (0, pad))
        hb = np.pad(has_o[lo:hi], (0, pad))
        _feats, dev = step(
            dev,
            jnp.asarray(sl), jnp.asarray(fb), jnp.asarray(tb),
            jnp.asarray(ab), jnp.asarray(hb), null, hl,
        )
    return LedgerState(*(np.asarray(leaf) for leaf in dev))


#: one process-wide jitted trace of the body — a fresh ``jax.jit`` wrapper
#: per replay would carry a fresh executable cache and recompile every
#: warm restart (recovery is off the hot path, but a shard-revive storm
#: recovering N tables must not pay N compiles of the same shapes)
_STEP = None


def _jitted_step():
    global _STEP
    if _STEP is None:
        import jax

        from fraud_detection_tpu.ledger.features import _ledger_read_update

        _STEP = jax.jit(_ledger_read_update)
    return _STEP


def _bucket(n: int, floor: int = REPLAY_BATCH) -> int:
    """Replay dispatch shape for an ``n``-row record: the smallest
    power-of-two bucket ≥ max(n, floor). Bucketing keeps the jitted step's
    compile count at a handful of shapes across arbitrarily mixed record
    sizes; padding rows carry ``has_entity=0`` and the traced body leaves
    every slot bitwise unchanged for them."""
    b = max(int(floor), 1)
    while b < n:
        b <<= 1
    return b


def replay_records(
    spec: LedgerSpec,
    state: LedgerState | None,
    records,
    batch_floor: int = REPLAY_BATCH,
) -> LedgerState:
    """Fold journal records onto ``state`` with the serving segmentation:
    one dispatch per record, records in sequence order, rows in journal
    (= staging) order. This is THE recovery replay — bitwise-equal to the
    table an uninterrupted serve of the same flushes carries."""
    import jax.numpy as jnp

    dev = device_state(state, spec.slots)
    step = _jitted_step()
    null = jnp.asarray(spec.null_features)
    hl = jnp.float32(spec.halflife_s)
    for _seq, fp, ts, amt in records:
        n = int(fp.shape[0])
        if n == 0:
            continue
        fp_c = np.ascontiguousarray(fp, np.uint32)
        b = _bucket(n, batch_floor)
        pad = b - n
        sl = np.pad(slots_for(fp_c, spec.log2_slots), (0, pad))
        fb = np.pad(fp_c, (0, pad))
        tb = np.pad(np.ascontiguousarray(ts, np.float32), (0, pad))
        ab = np.pad(np.ascontiguousarray(amt, np.float32), (0, pad))
        hb = np.pad((fp_c != 0).astype(np.float32), (0, pad))
        _feats, dev = step(
            dev,
            jnp.asarray(sl), jnp.asarray(fb), jnp.asarray(tb),
            jnp.asarray(ab), jnp.asarray(hb), null, hl,
        )
    return LedgerState(*(np.asarray(leaf) for leaf in dev))


@dataclass
class RecoveryReport:
    """What a warm restart did — the ``/health`` + metrics + runbook
    evidence."""

    ok: bool = True
    restored: bool = False  # a snapshot (or tail) actually bound
    refused_reason: str | None = None
    snapshot_seq: int = 0
    snapshot_path: str | None = None
    snapshot_created_at: float = 0.0
    slot_version: int | None = None
    generations_skipped: int = 0
    replayed_rows: int = 0
    torn_rows: int = 0
    corrupt_mid_file: int = 0
    resume_seq: int = 0  # the journal continues from here
    duration_s: float = 0.0
    rows_seen: int = 0
    state: LedgerState | None = None
    window: DriftWindow | None = None
    shard_window: DriftWindow | None = None

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "restored": self.restored,
            "refused_reason": self.refused_reason,
            "snapshot_seq": self.snapshot_seq,
            "generations_skipped": self.generations_skipped,
            "replayed_rows": self.replayed_rows,
            "torn_rows": self.torn_rows,
            "corrupt_mid_file": self.corrupt_mid_file,
            "resume_seq": self.resume_seq,
            "duration_s": round(self.duration_s, 6),
        }


def recover(directory: str, spec: LedgerSpec) -> RecoveryReport:
    """Load the newest CRC-valid generation (falling back per torn file),
    replay the journal tail through the traced body, and return the
    recovered state — pure of any serving wiring so the chaos harness and
    the bench drive it exactly as the app does."""
    t0 = time.perf_counter()
    rep = RecoveryReport()
    # range injection point: the crash_warm_restart scenario stalls here
    # to pin the `/health` 503-while-recovering contract
    fire("lifeboat.recover", directory=directory)
    snap, skipped = snapshot_mod.load_latest(directory)
    rep.generations_skipped = skipped
    expect = snapshot_mod.spec_hash(spec)
    if snap is not None and snap.spec_hash != expect:
        # refuse loudly: replaying a snapshot through mismatched hash
        # geometry silently scrambles every entity — the caller serves
        # from the train-time stamp instead
        rep.ok = False
        rep.refused_reason = (
            f"snapshot {snap.path} was taken under LedgerSpec hash "
            f"{snap.spec_hash}, served model expects {expect} — refusing; "
            "serving from the train-time stamp"
        )
        log.error("lifeboat: %s", rep.refused_reason)
        # resume journaling PAST everything on disk: restarting at seq 0
        # would land every new-spec generation BELOW the stale snapshot's
        # seq, so load_latest would refuse forever and pruning would
        # preferentially delete the valid new-spec generations — the
        # durability layer silently bricked. Sequencing past the stale
        # file lets the next snapshot supersede it and rotation age it out.
        old_tail = journal_mod.read_tail(directory, 0)
        rep.resume_seq = max(snap.seq, old_tail.max_seq)
        rep.duration_s = time.perf_counter() - t0
        return rep
    if snap is None:
        # no (valid) snapshot: replay whatever journal exists from a fresh
        # table — a process that crashed before its first snapshot still
        # recovers its journaled rows (hash-checked per journal header:
        # records written under a different LedgerSpec are refused, the
        # snapshot discipline applied to the journal side)
        tail = journal_mod.read_tail(directory, 0, expect_hash=expect)
        rep.torn_rows = tail.torn_rows
        rep.corrupt_mid_file = tail.corrupt_mid_file
        rep.resume_seq = tail.max_seq
        if tail.fp.shape[0]:
            rep.state = replay_records(spec, None, tail.records)
            rep.replayed_rows = int(tail.fp.shape[0])
            rep.restored = True
        rep.duration_s = time.perf_counter() - t0
        return rep
    tail = journal_mod.read_tail(directory, snap.seq, expect_hash=expect)
    rep.snapshot_seq = snap.seq
    rep.snapshot_path = snap.path
    rep.snapshot_created_at = snap.created_at
    rep.slot_version = snap.slot_version
    rep.rows_seen = snap.rows_seen
    rep.torn_rows = tail.torn_rows
    rep.corrupt_mid_file = tail.corrupt_mid_file
    rep.resume_seq = max(tail.max_seq, snap.seq)
    rep.state = replay_records(spec, snap.ledger, tail.records)
    rep.replayed_rows = int(tail.fp.shape[0])
    rep.window = snap.window
    rep.shard_window = snap.shard_window
    rep.restored = True
    rep.duration_s = time.perf_counter() - t0
    log.info(
        "lifeboat: warm restart from seq %d (%d generation(s) skipped), "
        "replayed %d journaled row(s) in %.3fs, %d torn row(s) lost",
        snap.seq, skipped, rep.replayed_rows, rep.duration_s, rep.torn_rows,
    )
    return rep
