"""The entity journal: a host-side append-only log of the ledger's inputs.

Every fused stateful flush folds exactly three per-row quantities into the
donated entity table (``ledger/features._ledger_read_update``): the entity
fingerprint, the event timestamp (origin-relative), and the amount **as
the traced body consumes it** (the dequantized lattice value on a quant
wire, the bf16-rounded value on the bf16 wire). The journal records those
triples — nothing else — so a warm restart can replay the tail through the
SAME traced body and land within journal-lag rows of the crashed table.

File layout (``journal-{base_seq:012d}.wal``; ``base_seq`` = the flush
sequence number of the snapshot this file was rotated at — records in the
file all carry ``seq > base_seq``)::

    header:  "LBJ1" | version u16 | base_seq u64 | spec_hash 16s | crc u32
    record:  "LR" | n u32 | seq u64 | fp u32[n] | ts f32[n] | amt f32[n]
             | crc u32  (over the n/seq fields + payload)

Appends are batch-buffered (one record per flush) with a configurable
fsync cadence (``LIFEBOAT_FSYNC_S``; 0 = fsync every append): the rows
buffered-but-not-yet-synced are exactly the recovery staleness bound,
exported as ``lifeboat_journal_lag_rows``. The reader CRC-validates every
record and **resyncs on the record magic** past a corrupt region, so a
torn tail (the normal crash shape — the final record half-written) is
skipped with its rows counted on ``lifeboat_torn_tail_rows_total``, and a
corrupt record MID-file (disk damage, not a crash) is skipped loudly while
every later valid record still replays.
"""

from __future__ import annotations

import logging
import os
import re
import struct
import threading
import zlib
from dataclasses import dataclass, field

import numpy as np

from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.lifeboat")

J_MAGIC = b"LBJ1"
REC_MAGIC = b"LR"
J_VERSION = 1

JOURNAL_RE = re.compile(r"^journal-(\d{12})\.wal$")

_HDR = struct.Struct("<4sHQ16s")  # magic, version, base_seq, spec_hash
_HDR_CRC = struct.Struct("<I")
_REC = struct.Struct("<2sIQ")  # magic, n, seq
_REC_CRC = struct.Struct("<I")

#: rows-per-record sanity bound for the resyncing reader — a corrupt
#: length field must not be trusted into a gigabyte read
_MAX_REC_ROWS = 1 << 22


def journal_path(directory: str, base_seq: int) -> str:
    return os.path.join(directory, f"journal-{base_seq:012d}.wal")


def list_journals(directory: str) -> list[tuple[int, str]]:
    """(base_seq, path), oldest → newest."""
    out: list[tuple[int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = JOURNAL_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


class Journal:
    """The write side. One open file, records appended under the caller's
    serialization (the lifeboat flush lock couples append order to dispatch
    order); ``sync()``/``rotate()`` are internally locked so the
    maintenance thread's fsync tick can run beside appends."""

    def __init__(
        self,
        directory: str,
        spec_hash: str,
        base_seq: int = 0,
        fsync_s: float = 0.5,
    ):
        self.directory = directory
        self.spec_hash = spec_hash
        self.fsync_s = float(fsync_s)
        self.seq = int(base_seq)  # last assigned flush sequence number
        self.pending_rows = 0  # appended but not yet fsynced (the lag bound)
        self.rows_appended = 0
        self._lock = lockdep.lock("lifeboat.journal")
        self._f = None
        os.makedirs(directory, exist_ok=True)
        self._open(int(base_seq))

    def _open(self, base_seq: int) -> None:
        path = journal_path(self.directory, base_seq)
        f = open(path, "ab")
        if f.tell() == 0:
            header = _HDR.pack(
                J_MAGIC, J_VERSION, base_seq,
                self.spec_hash.encode()[:16].ljust(16, b"\0"),
            )
            f.write(header + _HDR_CRC.pack(zlib.crc32(header)))
            f.flush()
            os.fsync(f.fileno())
        self._f = f
        self.base_seq = base_seq

    def append(self, fp: np.ndarray, ts: np.ndarray, amount: np.ndarray) -> int:
        """Append one flush's entity triples as a single CRC-framed record;
        returns the record's flush sequence number. Arrays must be aligned
        1-D; rows are copied into the record bytes immediately, so staging
        buffers can recycle the moment this returns."""
        n = int(fp.shape[0])
        fp = np.ascontiguousarray(fp, np.uint32)
        ts = np.ascontiguousarray(ts, np.float32)
        amount = np.ascontiguousarray(amount, np.float32)
        if ts.shape[0] != n or amount.shape[0] != n:
            raise ValueError("journal triple arrays must be aligned")
        with self._lock:
            if self._f is None:
                # closed (shutdown raced an in-flight flush): the rows
                # still dispatch, they just aren't journaled — the same
                # bounded loss as a crash in the fsync window, not an
                # AttributeError inside the flush lock
                return self.seq
            self.seq += 1
            seq = self.seq
            head = _REC.pack(REC_MAGIC, n, seq)
            payload = fp.tobytes() + ts.tobytes() + amount.tobytes()
            crc = zlib.crc32(head[2:])  # n + seq fields
            crc = zlib.crc32(payload, crc)
            self._f.write(head + payload + _REC_CRC.pack(crc))
            self.pending_rows += n
            self.rows_appended += n
            if self.fsync_s == 0:
                self._sync_locked()  # graftcheck: ignore[blocking-under-lock] -- fsync_s=0 is group-commit-per-append by contract; the fsync IS the critical section
        return seq

    def _sync_locked(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self.pending_rows = 0

    def sync(self) -> None:
        """Make every appended record durable; zeroes the lag bound."""
        with self._lock:
            if self._f is not None:
                self._sync_locked()  # graftcheck: ignore[blocking-under-lock] -- durability tick: appends must not interleave with the sync point

    def rotate(self, new_base_seq: int) -> None:
        """Close the current file (synced) and start a fresh one — called
        at snapshot boundaries with the snapshot's sequence number, so each
        journal file spans exactly one inter-snapshot interval and pruning
        by base sequence is safe."""
        with self._lock:
            if self._f is not None:
                self._sync_locked()  # graftcheck: ignore[blocking-under-lock] -- rotation seals the old file; a racing append must land in the new one
                self._f.close()
            self._open(int(new_base_seq))  # graftcheck: ignore[blocking-under-lock] -- dir fsync making the rotated file durable; same seal

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._sync_locked()  # graftcheck: ignore[blocking-under-lock] -- close drains under the lock so no append races the final sync
                self._f.close()
                self._f = None


@dataclass
class JournalTail:
    """Everything read back past a snapshot point.

    ``records`` preserves the per-flush framing — one entry per journaled
    flush, in sequence (= dispatch) order. Recovery MUST fold these one
    dispatch per record: the traced body decays each dispatch's slots to a
    per-dispatch anchor, so the fold is order-insensitive *within* a
    record but segmentation-sensitive *across* them — replaying a
    flattened tail in arbitrary chunks lands ulp-level off the table the
    serving process computed, and the chaos parity invariant is bitwise.
    The flattened ``fp``/``ts``/``amount`` views remain for accounting and
    order-insensitive consumers."""

    fp: np.ndarray  # (n,) uint32
    ts: np.ndarray  # (n,) f32
    amount: np.ndarray  # (n,) f32
    records: list = field(default_factory=list)  # [(seq, fp, ts, amount)]
    n_records: int = 0
    torn_rows: int = 0  # rows in CRC-failed/truncated records (bounded loss)
    corrupt_mid_file: int = 0  # corrupt records NOT at a file tail
    max_seq: int = 0


def read_journal_file(path: str):
    """Yield ``(seq, fp, ts, amount)`` per valid record, plus a summary.

    Returns ``(records, torn_rows, mid_file_corruptions, header_ok,
    header_spec_hash)`` — the hash is the 16-hex-char ``LedgerSpec``
    identity the writer stamped (``None`` when the header is torn), so
    callers can refuse records written under different hash geometry.
    The reader is resyncing: after a CRC/length failure it scans forward
    for the next record magic, so one damaged record never hides the rest
    of the file. Rows lost to damage are counted from the failed record's
    parsed length when plausible."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        log.error("lifeboat: unreadable journal %s: %s", path, e)
        return [], 0, 0, False, None
    records: list[tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
    good_offsets: list[int] = []  # start offsets of CRC-valid records
    torn_rows = 0
    failures: list[int] = []  # byte offsets of failed parses
    hdr_len = _HDR.size + _HDR_CRC.size
    header_ok = False
    header_hash = None
    off = 0
    if len(blob) >= hdr_len and blob[:4] == J_MAGIC:
        head = blob[: _HDR.size]
        (crc,) = _HDR_CRC.unpack_from(blob, _HDR.size)
        if zlib.crc32(head) == crc:
            header_ok = True
            off = hdr_len
            _magic, _ver, _base, hash_bytes = _HDR.unpack(head)
            header_hash = hash_bytes.rstrip(b"\0").decode(
                "ascii", "replace"
            )
    if not header_ok:
        log.error("lifeboat: journal %s has a bad/torn header", path)
        # resync into the body anyway — records are self-framed
        off = 0
    n_bytes = len(blob)
    while off < n_bytes:
        idx = blob.find(REC_MAGIC, off)
        if idx < 0:
            if off < n_bytes:
                failures.append(off)
            break
        if idx != off:
            failures.append(off)
        off = idx
        if off + _REC.size > n_bytes:
            failures.append(off)
            break
        magic, n, seq = _REC.unpack_from(blob, off)
        if n > _MAX_REC_ROWS:
            failures.append(off)
            off += len(REC_MAGIC)
            continue
        body_len = n * 12
        end = off + _REC.size + body_len + _REC_CRC.size
        if end > n_bytes:
            # truncated record — the torn-tail shape. Keep scanning rather
            # than stopping: a spurious magic match inside a corrupt
            # region can also land here, and breaking would drop every
            # valid record after the damage.
            torn_rows += n
            failures.append(off)
            off += len(REC_MAGIC)
            continue
        payload = blob[off + _REC.size : off + _REC.size + body_len]
        (crc,) = _REC_CRC.unpack_from(blob, off + _REC.size + body_len)
        calc = zlib.crc32(blob[off + 2 : off + _REC.size])
        calc = zlib.crc32(payload, calc)
        if calc != crc:
            torn_rows += n
            failures.append(off)
            off += len(REC_MAGIC)  # resync past the bad magic
            continue
        fp = np.frombuffer(payload, np.uint32, count=n)
        ts = np.frombuffer(payload, np.float32, count=n, offset=4 * n)
        amt = np.frombuffer(payload, np.float32, count=n, offset=8 * n)
        records.append((int(seq), fp, ts, amt))
        good_offsets.append(off)
        off = end
    # a failure with a CRC-VALID record after it is mid-file corruption
    # (disk damage — a crash can only tear the tail); failures past the
    # last good record are the ordinary torn tail
    last_good = good_offsets[-1] if good_offsets else -1
    mid_file = sum(1 for x in failures if x < last_good)
    if mid_file:
        log.error(
            "lifeboat: journal %s has %d corrupt region(s) MID-file (valid "
            "records follow) — this is disk damage, not a torn tail; "
            "replaying around it",
            path,
            mid_file,
        )
    return records, torn_rows, mid_file, header_ok, header_hash


def read_tail(
    directory: str, after_seq: int, expect_hash: str | None = None
) -> JournalTail:
    """Collect every journal record with ``seq > after_seq`` across all
    journal files, in sequence (= dispatch) order — the replay input for a
    snapshot taken at ``after_seq``. Per-flush framing is preserved in
    ``records``; the flattened arrays are concatenated views of the same
    rows.

    ``expect_hash`` (the served spec's identity, as the snapshot side
    checks it) refuses files whose VALID header was stamped under a
    different ``LedgerSpec`` — replaying old-geometry triples into a new
    table silently scrambles entities, the same hazard the snapshot
    refusal guards. A torn header can't be judged and still replays (the
    crash shape, bounded by the fsync cadence — not a spec change)."""
    torn = 0
    mid = 0
    max_seq = int(after_seq)
    collected: list[tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
    for base, path in list_journals(directory):
        records, t, m, header_ok, header_hash = read_journal_file(path)
        if (
            expect_hash is not None
            and header_ok
            and header_hash != expect_hash[:16]
        ):
            log.error(
                "lifeboat: journal %s was written under LedgerSpec hash "
                "%s, served model expects %s — refusing its records "
                "(serving geometry changed; the stale file ages out at "
                "the next snapshot rotation)",
                path, header_hash, expect_hash[:16],
            )
            continue
        torn += t
        mid += m
        for seq, fp, ts, amt in records:
            if seq > after_seq:
                collected.append((seq, fp, ts, amt))
                max_seq = max(max_seq, seq)
    collected.sort(key=lambda r: r[0])
    if collected:
        return JournalTail(
            fp=np.concatenate([r[1] for r in collected]),
            ts=np.concatenate([r[2] for r in collected]),
            amount=np.concatenate([r[3] for r in collected]),
            records=collected,
            n_records=len(collected),
            torn_rows=torn,
            corrupt_mid_file=mid,
            max_seq=max_seq,
        )
    return JournalTail(
        fp=np.zeros(0, np.uint32),
        ts=np.zeros(0, np.float32),
        amount=np.zeros(0, np.float32),
        records=[],
        n_records=0,
        torn_rows=torn,
        corrupt_mid_file=mid,
        max_seq=max_seq,
    )


def prune_journals(directory: str, keep_after_base: int) -> list[int]:
    """Drop journal files whose base sequence predates the oldest retained
    snapshot — rotation happens AT snapshot boundaries, so a file with
    ``base < oldest_snapshot_seq`` contains only records the oldest
    retained snapshot already covers."""
    pruned: list[int] = []
    for base, path in list_journals(directory):
        if base < keep_after_base:
            try:
                os.unlink(path)
                pruned.append(base)
            except OSError:  # graftcheck: ignore[silent-except] — already gone
                pass
    return pruned
