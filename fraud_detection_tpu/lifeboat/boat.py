"""The lifeboat: crash-consistent durability for device-resident state.

One :class:`Lifeboat` per serving process owns three jobs:

1. **Journal** (write-ahead, on the flush path): the micro-batcher calls
   :meth:`journal_staged` under :attr:`flush_lock` immediately before the
   fused stateful dispatch, appending the flush's entity triples (fp, ts,
   wire-consumed amount) as one CRC-framed record. The lock couples the
   journal's sequence numbers to dispatch order, so a snapshot cut is
   always consistent: every flush with ``seq ≤ snapshot_seq`` has been
   dispatched into the table the snapshot reads.
2. **Async snapshotter** (maintenance thread, off the hot path): every
   ``LIFEBOAT_SNAPSHOT_S`` seconds (or ``LIFEBOAT_SNAPSHOT_FLUSHES``
   flushes), fetch the donated ledger table + drift windows between
   flushes (a d2h materialization of the live pytrees — zero extra device
   dispatches), rotate the journal at the captured sequence number, and
   land a CRC-stamped generation via the atomic writer, retaining
   ``LIFEBOAT_KEEP`` generations. The same thread drives the journal's
   fsync cadence (``LIFEBOAT_FSYNC_S``) and refreshes the snapshot-age
   gauge.
3. **Warm restart** (:meth:`recover`): load the newest valid generation
   (falling back per torn file), replay the journal tail through the SAME
   traced ledger body — one dispatch per journaled flush, the serving
   segmentation (see :func:`~.recovery.replay_records` for why that is
   what makes the result bitwise) — bind the recovered table + windows
   into the drift monitor (same shapes/dtypes — zero new compiles), and flip
   :attr:`state` ``recovering → ready``. The serving edges 503 with
   ``Retry-After`` while ``recovering`` so traffic can't fold into a table
   about to be replaced.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.ledger.state import LedgerSpec
from fraud_detection_tpu.lifeboat import journal as journal_mod
from fraud_detection_tpu.lifeboat import recovery as recovery_mod
from fraud_detection_tpu.lifeboat import snapshot as snapshot_mod
from fraud_detection_tpu.range.faults import fire
from fraud_detection_tpu.service import metrics
from fraud_detection_tpu.utils import lockdep

log = logging.getLogger("fraud_detection_tpu.lifeboat")

IDLE = "idle"
RECOVERING = "recovering"
READY = "ready"

#: maintenance-thread tick — the resolution of the fsync cadence and the
#: snapshot-age gauge, far below any sane LIFEBOAT_SNAPSHOT_S
_TICK_S = 0.2


class Lifeboat:
    def __init__(
        self,
        directory: str,
        spec: LedgerSpec,
        drift=None,
        slot=None,
        snapshot_s: float | None = None,
        snapshot_flushes: int | None = None,
        keep: int | None = None,
        fsync_s: float | None = None,
    ):
        self.directory = directory
        self.spec = spec
        self.drift = drift
        self.slot = slot  # lifecycle ModelSlot (snapshot version stamp)
        self.snapshot_s = (
            snapshot_s if snapshot_s is not None else config.lifeboat_snapshot_s()
        )
        self.snapshot_flushes = (
            snapshot_flushes
            if snapshot_flushes is not None
            else config.lifeboat_snapshot_flushes()
        )
        self.keep = keep if keep is not None else config.lifeboat_keep()
        self.fsync_s = (
            fsync_s if fsync_s is not None else config.lifeboat_fsync_s()
        )
        self.spec_hash = snapshot_mod.spec_hash(spec)
        self.state = IDLE
        #: couples {journal append → fused dispatch} on the flush path and
        #: {table+window read → seq capture → rotate} on the snapshot path:
        #: both sides hold it, so a snapshot cut can never split a flush
        #: from its journal record
        self.flush_lock = lockdep.lock("lifeboat.flush")
        self.journal: journal_mod.Journal | None = None
        self.last_report: recovery_mod.RecoveryReport | None = None
        self._flushes_since_snapshot = 0
        self._last_snapshot_t = time.time()
        self._snapshot_requested = threading.Event()
        self._last_fsync_t = time.time()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        metrics.lifeboat_journal_lag_rows.set(0)

    # -- warm restart ------------------------------------------------------
    def recover(self) -> recovery_mod.RecoveryReport:
        """Run the warm restart and bind the result. Idempotent per
        process start; flips ``state`` recovering → ready (ready even on a
        refused/empty recovery — the process then serves the train-time
        stamp, which is the documented fallback, and journaling starts
        fresh either way)."""
        self.state = RECOVERING
        t0 = time.perf_counter()
        try:
            rep = recovery_mod.recover(self.directory, self.spec)
            self.last_report = rep
            if rep.restored and rep.state is not None and self.drift is not None:
                # same shapes/dtypes as the table already bound → the warmed
                # fused executables keep serving with ZERO new compiles
                self.drift.bind_ledger(self.spec, rep.state)
                if rep.window is not None and hasattr(
                    self.drift, "restore_window"
                ):
                    self.drift.restore_window(
                        rep.window,
                        shard_window=rep.shard_window,
                        rows_seen=rep.rows_seen or None,
                    )
            metrics.lifeboat_replayed_rows.inc(rep.replayed_rows)
            if rep.torn_rows:
                metrics.lifeboat_torn_tail_rows.inc(rep.torn_rows)
            metrics.lifeboat_recovery_duration.set(rep.duration_s)
            # snapshot age continues from the generation we restored — a
            # process that restarts every few minutes without snapshotting
            # must still trip SnapshotStale
            if rep.snapshot_created_at:
                self._last_snapshot_t = rep.snapshot_created_at
            self.journal = journal_mod.Journal(
                self.directory,
                self.spec_hash,
                base_seq=rep.resume_seq,
                fsync_s=self.fsync_s,
            )
            return rep
        finally:
            self.state = READY
            metrics.lifeboat_recovery_duration.set(time.perf_counter() - t0)
            metrics.lifeboat_snapshot_age.set(
                max(0.0, time.time() - self._last_snapshot_t)
            )

    # -- the flush-path hook ----------------------------------------------
    def journal_staged(self, slot, hx, dequant_scale, n_rows: int) -> None:
        """Append one staged flush's entity triples. Called by the
        micro-batcher UNDER :attr:`flush_lock`, immediately before the
        fused dispatch. ``hx`` is the wire-encoded batch the program will
        consume; the journaled amount is computed from it exactly as the
        traced body will (dequantized codes on the int8 wire, upcast on
        bf16), so replay folds the same floats serving folded."""
        journal = self.journal
        if journal is None or self.state != READY:
            return
        self._flushes_since_snapshot += 1
        lh = slot.lh
        mask = lh != 0
        n = int(mask.sum())
        if not n:
            return
        fp = slot.lf[mask]
        ts = slot.lt[mask]
        # mask BEFORE the f32 upcast: the copy is n rows, not the bucket
        # (this hook is on the flush hot path — the bench recovery gate
        # prices it at ≤5% of the fused flush loop)
        col = np.asarray(hx)[: lh.shape[0], self.spec.amount_col]
        amt = col[mask].astype(np.float32)
        if dequant_scale is not None:
            scale = np.asarray(dequant_scale, np.float32).reshape(-1)
            amt = amt * scale[self.spec.amount_col]
        seq = journal.append(fp, ts, amt)
        metrics.lifeboat_journal_lag_rows.set(journal.pending_rows)
        # range injection point: crash_warm_restart kills here — AFTER the
        # record is durable (fsync-per-append in the scenario), BEFORE the
        # dispatch lands, pinning journal-ahead consistency
        fire("lifeboat.journal", seq=seq, rows=n)

    # -- snapshotting ------------------------------------------------------
    def take_snapshot(self) -> str | None:
        """Capture a consistent {table, windows, seq} cut and land one
        generation. The lock is held only for the d2h materialization +
        journal rotation; serialization and the atomic file write run
        outside it."""
        drift = self.drift
        journal = self.journal
        if drift is None or journal is None:
            return None
        with self.flush_lock:
            table = drift.ledger_snapshot()
            if table is None:
                return None
            window = (
                drift.window_snapshot()
                if hasattr(drift, "window_snapshot")
                else None
            )
            shard_window = (
                drift.shard_window_snapshot()
                if hasattr(drift, "shard_window_snapshot")
                else None
            )
            rows_seen = int(getattr(drift, "rows_seen", 0))
            seq = journal.seq
            # everything ≤ seq is in the table we just read; make it
            # durable and start the next inter-snapshot journal interval
            journal.rotate(seq)
            self._flushes_since_snapshot = 0
        # range injection point: kill_mid_snapshot fires here — the
        # generation file has NOT landed yet, so a kill leaves the previous
        # generation + a rotated journal, exactly what fallback replays
        fire("lifeboat.snapshot", seq=seq)
        path = snapshot_mod.write_snapshot(
            self.directory,
            seq,
            self.spec,
            table,
            window=window,
            shard_window=shard_window,
            slot_version=getattr(self.slot, "version", None),
            rows_seen=rows_seen,
        )
        self._last_snapshot_t = time.time()
        metrics.lifeboat_snapshot_age.set(0.0)
        metrics.lifeboat_journal_lag_rows.set(journal.pending_rows)
        snapshot_mod.prune_snapshots(self.directory, self.keep)
        kept = snapshot_mod.list_snapshots(self.directory)
        if kept:
            journal_mod.prune_journals(self.directory, kept[0][0])
        log.info(
            "lifeboat: snapshot generation %d landed (%s)", seq, path
        )
        return path

    def request_snapshot(self) -> None:
        """Ask the maintenance thread for an immediate snapshot — the
        shard-front revive hook (a revive follows an outage; capture a
        durable point now rather than a full interval later)."""
        self._snapshot_requested.set()

    # -- maintenance thread ------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="lifeboat", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(_TICK_S):
            try:
                now = time.time()
                metrics.lifeboat_snapshot_age.set(
                    max(0.0, now - self._last_snapshot_t)
                )
                journal = self.journal
                if (
                    journal is not None
                    and self.fsync_s > 0
                    and journal.pending_rows
                    and now - self._last_fsync_t >= self.fsync_s
                ):
                    journal.sync()
                    self._last_fsync_t = now
                    metrics.lifeboat_journal_lag_rows.set(0)
                due = (
                    self._snapshot_requested.is_set()
                    or (now - self._last_snapshot_t) >= self.snapshot_s
                    or (
                        self.snapshot_flushes > 0
                        and self._flushes_since_snapshot
                        >= self.snapshot_flushes
                    )
                )
                if due and self.state == READY:
                    self._snapshot_requested.clear()
                    self.take_snapshot()
            except Exception:
                log.exception("lifeboat maintenance tick failed")

    def close(self, final_snapshot: bool = False) -> None:
        """Stop the maintenance thread; sync (and optionally snapshot) so
        a clean shutdown loses nothing."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_snapshot and self.state == READY:
            try:
                self.take_snapshot()
            except Exception:
                log.exception("lifeboat final snapshot failed")
        if self.journal is not None:
            self.journal.close()

    # -- status ------------------------------------------------------------
    def status(self) -> dict:
        journal = self.journal
        return {
            "state": self.state,
            "directory": self.directory,
            "snapshot_age_s": max(0.0, time.time() - self._last_snapshot_t),
            "journal_seq": journal.seq if journal else 0,
            "journal_lag_rows": journal.pending_rows if journal else 0,
            "generations": [s for s, _ in snapshot_mod.list_snapshots(self.directory)],
            "last_recovery": (
                self.last_report.to_dict() if self.last_report else None
            ),
        }
