"""Elastic training checkpoints for the DP SGD solver.

The reference has no training checkpoint/resume story at all — training is a
one-shot script (SURVEY.md §5 "Checkpoint/resume: none in the ML sense").
For the 10M-row data-parallel configuration that's a real gap: a preempted
pod restarts the whole fit. This module adds the TPU-native story:

- **atomic**: state is written to a temp file in the target directory and
  ``os.replace``-d into place, so a crash mid-write never corrupts the
  latest checkpoint;
- **versioned**: one file per epoch (``sgd_epoch_{e:05d}.npz``), with a
  retention window (default: keep the last 3);
- **exact**: optimizer velocity and the host PRNG bit-generator state ride
  along, so an interrupted fit resumed from epoch *e* is **bit-identical**
  to one that never stopped (pinned by tests/test_checkpoint.py);
- **device-aware**: arrays come off device once per epoch (tiny: the
  logistic state is ~240 bytes); the data matrix never leaves the device.

Usage::

    ck = SGDCheckpointer(dir)
    params = logistic_fit_sgd(x, y, epochs=8,
                              epoch_callback=ck.epoch_callback,
                              resume=ck.latest())   # None on first run
"""

from __future__ import annotations

import json
import os
import re

import numpy as np

from fraud_detection_tpu.ckpt.atomic import atomic_savez

_FILE_RE = re.compile(r"^sgd_epoch_(\d{5})\.npz$")


class SGDCheckpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    # -- write -------------------------------------------------------------
    def epoch_callback(
        self, epoch: int, params, velocity, rng, fingerprint: dict | None = None
    ) -> str:
        """``logistic_fit_sgd(epoch_callback=...)`` adapter: persist one
        epoch's full training state atomically, then prune old epochs.
        ``fingerprint`` (the fit's shape/hyperparameter identity) rides
        along so a mismatched resume is rejected, not silently wrong."""
        state = {
            "coef": np.asarray(params.coef, np.float32),
            "intercept": np.asarray(params.intercept, np.float32),
            "v_coef": np.asarray(velocity.coef, np.float32),
            "v_intercept": np.asarray(velocity.intercept, np.float32),
            "epoch": np.int64(epoch),
            # PRNG state is a nested dict of (arbitrarily large) ints —
            # JSON round-trips it exactly; store as a 0-d string array.
            "rng_state": np.array(json.dumps(rng.bit_generator.state)),
        }
        if fingerprint is not None:
            state["fingerprint"] = np.array(json.dumps(fingerprint))
        path = os.path.join(self.directory, f"sgd_epoch_{epoch:05d}.npz")
        # previously a hand-rolled mkstemp+replace WITHOUT fsync: a power
        # cut could still surface a torn checkpoint. The shared helper adds
        # the data + directory fsyncs (ckpt/atomic).
        atomic_savez(path, **state)
        self._prune()
        return path

    def _prune(self) -> None:
        epochs = sorted(self._epochs())
        for e in epochs[: max(0, len(epochs) - self.keep)]:
            try:
                os.unlink(os.path.join(self.directory, f"sgd_epoch_{e:05d}.npz"))
            except FileNotFoundError:
                pass

    # -- read --------------------------------------------------------------
    def _epochs(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _FILE_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return out

    def latest(self) -> dict | None:
        """Most recent saved state as ``logistic_fit_sgd(resume=...)``
        expects, or None when the directory holds no checkpoint."""
        epochs = self._epochs()
        if not epochs:
            return None
        return self.load(max(epochs))

    def load(self, epoch: int) -> dict:
        path = os.path.join(self.directory, f"sgd_epoch_{epoch:05d}.npz")
        with np.load(path) as z:
            out = {
                "coef": np.asarray(z["coef"]),
                "intercept": np.asarray(z["intercept"]),
                "v_coef": np.asarray(z["v_coef"]),
                "v_intercept": np.asarray(z["v_intercept"]),
                "epoch": int(z["epoch"]),
                "rng_state": json.loads(str(z["rng_state"])),
            }
            if "fingerprint" in z:
                out["fingerprint"] = json.loads(str(z["fingerprint"]))
        return out

    def clear(self) -> None:
        """Remove all checkpoints — called after a fit *completes* so a later
        run with the same directory starts fresh instead of resuming past
        its final epoch with another run's params."""
        for e in self._epochs():
            try:
                os.unlink(os.path.join(self.directory, f"sgd_epoch_{e:05d}.npz"))
            except FileNotFoundError:
                pass
