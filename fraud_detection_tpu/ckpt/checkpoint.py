"""Model artifact persistence.

Two formats:

- **Native**: a single ``.npz`` of the flat numeric state + a JSON sidecar
  for feature names — fast, dependency-free, the framework's source of truth
  (TPU equivalent of the reference's joblib dumps, train_model.py:112-115).
- **joblib interchange**: import of the reference's artifact layout
  (``logistic_model.joblib`` — sklearn LogisticRegression with coef (1,30);
  ``scaler.joblib`` — StandardScaler; ``columns.joblib``;
  ``feature_names.json`` — SURVEY.md §1 L2→L6 interface) and export back to
  it, so reference clients and the checked-in-artifact fallback behavior
  (api/app.py:41-44) keep working against models trained here.

joblib/sklearn are optional: import/export raise a clear error when absent.
"""

from __future__ import annotations

import json
import os

import numpy as np

from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.scaler import ScalerParams

NATIVE_FILE = "model.npz"
FEATURES_FILE = "feature_names.json"


def save_artifacts(
    directory: str,
    params: LogisticParams,
    scaler: ScalerParams | None,
    feature_names: list[str],
) -> str:
    os.makedirs(directory, exist_ok=True)
    state = {
        "coef": np.asarray(params.coef, np.float64),
        "intercept": np.asarray(params.intercept, np.float64),
    }
    if scaler is not None:
        state.update(
            scaler_mean=np.asarray(scaler.mean, np.float64),
            scaler_scale=np.asarray(scaler.scale, np.float64),
            scaler_var=np.asarray(scaler.var, np.float64),
            scaler_n=np.asarray(scaler.n_samples, np.float64),
        )
    np.savez(os.path.join(directory, NATIVE_FILE), **state)
    with open(os.path.join(directory, FEATURES_FILE), "w") as f:
        json.dump(list(feature_names), f)
    return directory


def load_artifacts(
    directory: str,
) -> tuple[LogisticParams, ScalerParams | None, list[str]]:
    with np.load(os.path.join(directory, NATIVE_FILE)) as z:
        params = LogisticParams(
            coef=np.asarray(z["coef"], np.float32),
            intercept=np.asarray(z["intercept"], np.float32),
        )
        scaler = None
        if "scaler_mean" in z:
            scaler = ScalerParams(
                mean=np.asarray(z["scaler_mean"], np.float32),
                scale=np.asarray(z["scaler_scale"], np.float32),
                var=np.asarray(z["scaler_var"], np.float32),
                n_samples=np.asarray(z["scaler_n"], np.float32),
            )
    with open(os.path.join(directory, FEATURES_FILE)) as f:
        feature_names = json.load(f)
    return params, scaler, feature_names


def export_joblib_artifacts(
    directory: str,
    params: LogisticParams,
    scaler: ScalerParams | None,
    feature_names: list[str],
    model_filename: str = "logistic_model.joblib",
) -> None:
    """Write the reference's artifact layout from native params (real sklearn
    estimator objects, loadable by any sklearn client)."""
    try:
        import joblib
        from sklearn.linear_model import LogisticRegression
        from sklearn.preprocessing import StandardScaler
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "joblib/sklearn are required for joblib export; install the "
            "'tools' extra"
        ) from e

    os.makedirs(directory, exist_ok=True)
    model = LogisticRegression()
    model.classes_ = np.array([0, 1])
    model.coef_ = np.asarray(params.coef, np.float64)[None, :]
    model.intercept_ = np.asarray([float(params.intercept)])
    model.n_features_in_ = len(feature_names)
    model.n_iter_ = np.array([1])
    joblib.dump(model, os.path.join(directory, model_filename))

    if scaler is not None:
        sk = StandardScaler()
        sk.mean_ = np.asarray(scaler.mean, np.float64)
        sk.scale_ = np.asarray(scaler.scale, np.float64)
        sk.var_ = np.asarray(scaler.var, np.float64)
        sk.n_features_in_ = len(feature_names)
        sk.n_samples_seen_ = int(np.asarray(scaler.n_samples))
        sk.with_mean = sk.with_std = True
        joblib.dump(sk, os.path.join(directory, "scaler.joblib"))

    joblib.dump(list(feature_names), os.path.join(directory, "columns.joblib"))
    with open(os.path.join(directory, FEATURES_FILE), "w") as f:
        json.dump(list(feature_names), f)


def import_joblib_artifacts(
    model_path: str,
    scaler_path: str | None = None,
    feature_names_path: str | None = None,
) -> tuple[LogisticParams, ScalerParams | None, list[str] | None]:
    """Load reference-format joblib artifacts into native params (the
    serving-side fallback path, api/app.py:41-48)."""
    try:
        import joblib
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("joblib is required to import joblib artifacts") from e

    model = joblib.load(model_path)
    params = LogisticParams(
        coef=np.asarray(model.coef_, np.float32).reshape(-1),
        intercept=np.asarray(model.intercept_, np.float32).reshape(()),
    )
    scaler = None
    if scaler_path:
        if not os.path.exists(scaler_path):
            # Scoring raw inputs with coefficients trained on scaled data
            # yields silently wrong probabilities — fail loudly instead.
            raise FileNotFoundError(f"scaler artifact not found: {scaler_path}")
        sk = joblib.load(scaler_path)
        scaler = ScalerParams(
            mean=np.asarray(sk.mean_, np.float32),
            scale=np.asarray(sk.scale_, np.float32),
            var=np.asarray(sk.var_, np.float32),
            n_samples=np.float32(getattr(sk, "n_samples_seen_", 0)),
        )
    feature_names = None
    if feature_names_path and os.path.exists(feature_names_path):
        with open(feature_names_path) as f:
            feature_names = json.load(f)
    return params, scaler, feature_names
