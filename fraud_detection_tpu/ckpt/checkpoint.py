"""Model artifact persistence.

Two formats:

- **Native**: a single ``.npz`` of the flat numeric state + a JSON sidecar
  for feature names — fast, dependency-free, the framework's source of truth
  (TPU equivalent of the reference's joblib dumps, train_model.py:112-115).
- **joblib interchange**: import of the reference's artifact layout
  (``logistic_model.joblib`` — sklearn LogisticRegression with coef (1,30);
  ``scaler.joblib`` — StandardScaler; ``columns.joblib``;
  ``feature_names.json`` — SURVEY.md §1 L2→L6 interface) and export back to
  it, so reference clients and the checked-in-artifact fallback behavior
  (api/app.py:41-44) keep working against models trained here.

joblib/sklearn are optional: import/export raise a clear error when absent.
"""

from __future__ import annotations

import json
import os

import numpy as np

from fraud_detection_tpu.ckpt.atomic import atomic_savez
from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.scaler import ScalerParams

NATIVE_FILE = "model.npz"
FEATURES_FILE = "feature_names.json"


def artifact_kind(directory: str) -> str:
    """``'logistic'`` | ``'gbt'`` | ``'absent'`` — dispatch key for loaders
    (the serving path accepts either family from the registry)."""
    path = os.path.join(directory, NATIVE_FILE)
    if not os.path.exists(path):
        return "absent"
    with np.load(path) as z:
        return "gbt" if "gbt_leaf_value" in z else "logistic"


def save_artifacts(
    directory: str,
    params: LogisticParams,
    scaler: ScalerParams | None,
    feature_names: list[str],
) -> str:
    os.makedirs(directory, exist_ok=True)
    state = {
        "coef": np.asarray(params.coef, np.float64),
        "intercept": np.asarray(params.intercept, np.float64),
    }
    if scaler is not None:
        state.update(
            scaler_mean=np.asarray(scaler.mean, np.float64),
            scaler_scale=np.asarray(scaler.scale, np.float64),
            scaler_var=np.asarray(scaler.var, np.float64),
            scaler_n=np.asarray(scaler.n_samples, np.float64),
        )
    atomic_savez(os.path.join(directory, NATIVE_FILE), **state)
    with open(os.path.join(directory, FEATURES_FILE), "w") as f:
        json.dump(list(feature_names), f)
    return directory


def load_artifacts(
    directory: str,
) -> tuple[LogisticParams, ScalerParams | None, list[str]]:
    with np.load(os.path.join(directory, NATIVE_FILE)) as z:
        if "coef" not in z:
            raise ValueError(
                f"{directory} holds {artifact_kind(directory)} artifacts, "
                "not logistic"
            )
        params = LogisticParams(
            coef=np.asarray(z["coef"], np.float32),
            intercept=np.asarray(z["intercept"], np.float32),
        )
        scaler = None
        if "scaler_mean" in z:
            scaler = ScalerParams(
                mean=np.asarray(z["scaler_mean"], np.float32),
                scale=np.asarray(z["scaler_scale"], np.float32),
                var=np.asarray(z["scaler_var"], np.float32),
                n_samples=np.asarray(z["scaler_n"], np.float32),
            )
    with open(os.path.join(directory, FEATURES_FILE)) as f:
        feature_names = json.load(f)
    return params, scaler, feature_names


def save_gbt_artifacts(
    directory: str,
    model,
    feature_names: list[str],
    background: np.ndarray | None = None,
) -> str:
    """Persist a :class:`~fraud_detection_tpu.ops.gbt.GBTModel` forest (the
    TPU-native analogue of the reference's ``xgb_model.joblib`` dump,
    train_model.py:112-113). Same ``model.npz`` + ``feature_names.json``
    layout as the logistic artifacts, keys prefixed ``gbt_``. ``background``
    is an optional (m, d) raw-space sample for interventional TreeSHAP."""
    os.makedirs(directory, exist_ok=True)
    state = {
        "gbt_split_feature": np.asarray(model.split_feature, np.int32),
        "gbt_split_bin": np.asarray(model.split_bin, np.int32),
        "gbt_leaf_value": np.asarray(model.leaf_value, np.float32),
        "gbt_bin_edges": np.asarray(model.bin_edges, np.float32),
        "gbt_base_logit": np.asarray(model.base_logit, np.float32),
    }
    if background is not None:
        state["gbt_background"] = np.asarray(background, np.float32)
    atomic_savez(os.path.join(directory, NATIVE_FILE), **state)
    with open(os.path.join(directory, FEATURES_FILE), "w") as f:
        json.dump(list(feature_names), f)
    return directory


def load_gbt_artifacts(directory: str):
    """Inverse of :func:`save_gbt_artifacts`; returns (GBTModel, names,
    background-or-None)."""
    from fraud_detection_tpu.ops.gbt import GBTModel

    with np.load(os.path.join(directory, NATIVE_FILE)) as z:
        if "gbt_leaf_value" not in z:
            raise ValueError(
                f"{directory} holds {artifact_kind(directory)} artifacts, "
                "not gbt"
            )
        model = GBTModel(
            split_feature=np.asarray(z["gbt_split_feature"]),
            split_bin=np.asarray(z["gbt_split_bin"]),
            leaf_value=np.asarray(z["gbt_leaf_value"]),
            bin_edges=np.asarray(z["gbt_bin_edges"]),
            base_logit=np.asarray(z["gbt_base_logit"]),
        )
        background = (
            np.asarray(z["gbt_background"]) if "gbt_background" in z else None
        )
    with open(os.path.join(directory, FEATURES_FILE)) as f:
        feature_names = json.load(f)
    return model, feature_names, background


def export_joblib_artifacts(
    directory: str,
    params: LogisticParams,
    scaler: ScalerParams | None,
    feature_names: list[str],
    model_filename: str = "logistic_model.joblib",
) -> None:
    """Write the reference's artifact layout from native params (real sklearn
    estimator objects, loadable by any sklearn client)."""
    try:
        import joblib
        from sklearn.linear_model import LogisticRegression
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "joblib/sklearn are required for joblib export; install the "
            "'tools' extra"
        ) from e

    os.makedirs(directory, exist_ok=True)
    model = LogisticRegression()
    model.classes_ = np.array([0, 1])
    model.coef_ = np.asarray(params.coef, np.float64)[None, :]
    model.intercept_ = np.asarray([float(params.intercept)])
    model.n_features_in_ = len(feature_names)
    model.n_iter_ = np.array([1])
    joblib.dump(model, os.path.join(directory, model_filename))
    export_scaler_artifacts(directory, scaler, feature_names)


def export_scaler_artifacts(
    directory: str,
    scaler: ScalerParams | None,
    feature_names: list[str],
) -> None:
    """The model-free slice of the reference artifact layout: scaler.joblib +
    columns.joblib + feature_names.json (what preprocess.py:51-57 emits
    before any model exists)."""
    try:
        import joblib
        from sklearn.preprocessing import StandardScaler
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "joblib/sklearn are required for joblib export; install the "
            "'tools' extra"
        ) from e

    os.makedirs(directory, exist_ok=True)
    if scaler is not None:
        sk = StandardScaler()
        sk.mean_ = np.asarray(scaler.mean, np.float64)
        sk.scale_ = np.asarray(scaler.scale, np.float64)
        sk.var_ = np.asarray(scaler.var, np.float64)
        sk.n_features_in_ = len(feature_names)
        sk.n_samples_seen_ = int(np.asarray(scaler.n_samples))
        sk.with_mean = sk.with_std = True
        joblib.dump(sk, os.path.join(directory, "scaler.joblib"))

    joblib.dump(list(feature_names), os.path.join(directory, "columns.joblib"))
    with open(os.path.join(directory, FEATURES_FILE), "w") as f:
        json.dump(list(feature_names), f)


def import_joblib_artifacts(
    model_path: str,
    scaler_path: str | None = None,
    feature_names_path: str | None = None,
) -> tuple[LogisticParams, ScalerParams | None, list[str] | None]:
    """Load reference-format joblib artifacts into native params (the
    serving-side fallback path, api/app.py:41-48)."""
    try:
        import joblib
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("joblib is required to import joblib artifacts") from e

    model = joblib.load(model_path)
    params = LogisticParams(
        coef=np.asarray(model.coef_, np.float32).reshape(-1),
        intercept=np.asarray(model.intercept_, np.float32).reshape(()),
    )
    scaler = None
    if scaler_path:
        if not os.path.exists(scaler_path):
            # Scoring raw inputs with coefficients trained on scaled data
            # yields silently wrong probabilities — fail loudly instead.
            raise FileNotFoundError(f"scaler artifact not found: {scaler_path}")
        sk = joblib.load(scaler_path)
        scaler = ScalerParams(
            mean=np.asarray(sk.mean_, np.float32),
            scale=np.asarray(sk.scale_, np.float32),
            var=np.asarray(sk.var_, np.float32),
            n_samples=np.float32(getattr(sk, "n_samples_seen_", 0)),
        )
    feature_names = None
    if feature_names_path and os.path.exists(feature_names_path):
        with open(feature_names_path) as f:
            feature_names = json.load(f)
    return params, scaler, feature_names
