"""Crash-consistent artifact writes: the ONE tmp→fsync→rename helper.

Every ``.npz`` artifact the system trusts at load time — ``model.npz``,
``quant_calibration.npz``, ``ledger_state.npz``, ``wide_params.npz``,
``monitor_profile.npz``, the SGD epoch checkpoints — was previously written
with a bare ``np.savez(path)``: a crash (OOM-kill, power, disk-full) mid-
write leaves a TORN file at the final name, and every loader in the repo
trusts whatever bytes sit there. The lifeboat durability work (ISSUE 15)
makes torn-artifact handling a first-class contract, and this module is the
write side of it:

- bytes land in a temp file **in the same directory** (same filesystem, so
  the rename is atomic),
- the temp file is flushed and ``fsync``-ed (data durable before the name
  flips),
- ``os.replace`` swaps it in (readers see the old bytes or the new bytes,
  never a mixture),
- the **directory** is fsynced afterwards (the rename itself durable —
  without it a power cut can roll the directory entry back to the old
  file even though the data blocks were synced).

The graftcheck rule ``artifact-nonatomic-write`` (ERROR) flags any bare
``np.savez``/``np.savez_compressed`` outside this module, so the eight
call sites this helper replaced can't silently regrow.
"""

from __future__ import annotations

import io
import os
import tempfile

import numpy as np


def fsync_dir(directory: str) -> None:
    """Best-effort directory fsync — makes a just-completed rename durable.
    Platforms/filesystems that refuse O_RDONLY dir fds (some network
    mounts) degrade to the rename-only guarantee."""
    try:
        fd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # graftcheck: ignore[silent-except] — best-effort on fs without dir fsync
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Write ``data`` to ``path`` crash-consistently: tmp file beside the
    target, fsync, atomic rename, directory fsync. A reader concurrent
    with (or interrupted by) the write sees either the complete old file
    or the complete new one."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(path) + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # a crash-simulating BaseException (range ReplicaKilled) or a real
        # failure: never leave the temp file to be mistaken for an artifact
        try:
            os.unlink(tmp)
        except OSError:  # graftcheck: ignore[silent-except] — tmp already renamed/gone
            pass
        raise
    fsync_dir(directory)
    return path


def atomic_savez(path: str, **arrays) -> str:
    """``np.savez`` with the atomic-write discipline. The archive is
    serialized in memory first (artifacts here are small — model weights,
    histograms, the hashed entity table), then lands via
    :func:`atomic_write_bytes`, so a crash mid-stamp can never leave a
    torn ``.npz`` at the trusted name."""
    return atomic_write_bytes(path, savez_bytes(**arrays))


def savez_bytes(**arrays) -> bytes:
    """Serialize an npz archive to bytes — for callers that embed the
    archive inside a larger CRC-framed container (the lifeboat snapshot)
    and land THAT via :func:`atomic_write_bytes`."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()
