"""Checkpointing and artifact interchange."""

from fraud_detection_tpu.ckpt.checkpoint import (  # noqa: F401
    export_joblib_artifacts,
    import_joblib_artifacts,
    load_artifacts,
    save_artifacts,
)
from fraud_detection_tpu.ckpt.train_state import SGDCheckpointer  # noqa: F401
