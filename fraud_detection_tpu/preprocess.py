"""Standalone preprocessing pipeline (the reference's legacy/linear path).

Mirrors preprocess.py:1-59's offline contract — produce
``data/preprocessed_data.npz`` (X_res, y_res, X_test, y_test) plus scaler and
feature-name artifacts — but with the train-only scaler fit (the reference's
scale-before-split here was a leakage bug its own train_model.py fixed;
SURVEY.md §2 component 2 note) and the numerics on device.
"""

from __future__ import annotations

import argparse
import json
import logging
import os

import jax
import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.ckpt.atomic import atomic_savez
from fraud_detection_tpu.ckpt.checkpoint import export_scaler_artifacts
from fraud_detection_tpu.data.loader import load_creditcard_csv, stratified_split
from fraud_detection_tpu.ops.scaler import scaler_fit, scaler_transform
from fraud_detection_tpu.ops.smote import smote

log = logging.getLogger("fraud_detection_tpu.preprocess")


def preprocess(
    data_csv: str | None = None,
    out_npz: str = "data/preprocessed_data.npz",
    models_dir: str = "models",
    seed: int = 42,
) -> dict:
    data_csv = data_csv or config.data_csv()
    x, y, feature_names = load_creditcard_csv(data_csv)
    train_idx, test_idx = stratified_split(y, 0.2, seed)

    scaler = scaler_fit(x[train_idx])
    xs_train = scaler_transform(scaler, x[train_idx])
    xs_test = np.asarray(scaler_transform(scaler, x[test_idx]))

    x_res, y_res = smote(xs_train, y[train_idx], jax.random.key(seed))

    os.makedirs(os.path.dirname(out_npz) or ".", exist_ok=True)
    atomic_savez(
        out_npz,
        X_res=np.asarray(x_res),
        y_res=np.asarray(y_res),
        X_test=xs_test,
        y_test=y[test_idx],
    )

    # Scaler + feature-name artifacts (preprocess.py:51-57's layout).
    os.makedirs(models_dir, exist_ok=True)
    try:
        export_scaler_artifacts(models_dir, scaler, feature_names)
    except RuntimeError:  # joblib absent — native feature list still lands
        with open(os.path.join(models_dir, "feature_names.json"), "w") as f:
            json.dump(feature_names, f)

    log.info(
        "preprocessed: resampled %d rows (from %d), test %d rows → %s",
        len(y_res), len(train_idx), len(test_idx), out_npz,
    )
    return {
        "n_resampled": int(len(y_res)),
        "n_test": int(len(test_idx)),
        "out": out_npz,
    }


def main(argv=None):
    config.apply_device_backend()  # DEVICE=cpu runs without the TPU tunnel
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None)
    ap.add_argument("--out", default="data/preprocessed_data.npz")
    ap.add_argument("--models-dir", default="models")
    ap.add_argument("--seed", type=int, default=42)
    a = ap.parse_args(argv)
    print(preprocess(a.data, a.out, a.models_dir, a.seed))


if __name__ == "__main__":
    main()
