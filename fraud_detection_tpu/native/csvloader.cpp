// Native CSV loader for the data tier.
//
// The reference's L1 is pandas.read_csv (reference train_model.py:22,
// preprocess.py:15) — a C parser under a Python API. This is the framework's
// own native equivalent: mmap the file once, index newlines, then parse rows
// to float32 in parallel across threads — zero Python-object churn, output
// written straight into a caller-provided (numpy) buffer.
//
// C ABI (consumed via ctypes from fraud_detection_tpu/data/native.py):
//   csv_dims(path, &rows, &cols)          -> 0 ok; rows exclude the header
//   csv_header(path, buf, buflen)         -> header line copied into buf
//   csv_read(path, out, rows, cols, nthr) -> 0 ok; out is row-major float32
//
// Error codes: -1 io/open, -2 shape mismatch, -3 parse error.

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Mapped {
  const char *data = nullptr;
  size_t size = 0;
  int fd = -1;

  bool open_file(const char *path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size == 0) return false;
    size = static_cast<size_t>(st.st_size);
    void *p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) return false;
    data = static_cast<const char *>(p);
    // The scan is strictly sequential per thread chunk.
    madvise(p, size, MADV_SEQUENTIAL);
    return true;
  }

  ~Mapped() {
    if (data) munmap(const_cast<char *>(data), size);
    if (fd >= 0) ::close(fd);
  }
};

// End offset (one past) of the header line.
size_t header_end(const Mapped &m) {
  const char *nl = static_cast<const char *>(memchr(m.data, '\n', m.size));
  return nl ? static_cast<size_t>(nl - m.data) + 1 : m.size;
}

size_t count_cols(const Mapped &m) {
  size_t end = header_end(m);
  size_t cols = 1;
  for (size_t i = 0; i < end; ++i)
    if (m.data[i] == ',') ++cols;
  return cols;
}

// Newline offsets after the header (data-row terminators; a missing final
// newline counts the last partial line as a row).
void index_rows(const Mapped &m, std::vector<size_t> &starts) {
  size_t pos = header_end(m);
  while (pos < m.size) {
    starts.push_back(pos);
    const char *nl = static_cast<const char *>(
        memchr(m.data + pos, '\n', m.size - pos));
    if (!nl) break;
    pos = static_cast<size_t>(nl - m.data) + 1;
  }
}

// Powers of ten for the fast float path (double keeps f32 round-trips exact).
const double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                         1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                         1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// Fast decimal float parse: sign, up-to-18-digit mantissa accumulated as
// int64, optional fraction and e±dd exponent. Bails to strtof (locale-safe,
// handles inf/nan/hex/overlong) by returning false with *end untouched —
// ~4× faster than strtof on typical CSV numerics.
inline bool fast_float(const char *p, const char *limit, float *out,
                       const char **end) {
  const char *s = p;
  bool neg = false;
  if (s < limit && (*s == '-' || *s == '+')) neg = (*s++ == '-');
  long long mant = 0;
  int digits = 0, frac_digits = 0;
  while (s < limit && *s >= '0' && *s <= '9') {
    mant = mant * 10 + (*s++ - '0');
    if (++digits > 18) return false;
  }
  if (s < limit && *s == '.') {
    ++s;
    while (s < limit && *s >= '0' && *s <= '9') {
      mant = mant * 10 + (*s++ - '0');
      ++frac_digits;
      if (++digits > 18) return false;
    }
  }
  if (digits == 0) return false;  // "", ".", "nan", "inf" → slow path
  int exp10 = -frac_digits;
  if (s < limit && (*s == 'e' || *s == 'E')) {
    const char *es = s + 1;
    bool eneg = false;
    if (es < limit && (*es == '-' || *es == '+')) eneg = (*es++ == '-');
    int ev = 0, ed = 0;
    while (es < limit && *es >= '0' && *es <= '9') {
      ev = ev * 10 + (*es++ - '0');
      if (++ed > 3) return false;
    }
    if (ed == 0) return false;
    exp10 += eneg ? -ev : ev;
    s = es;
  }
  if (exp10 < -22 || exp10 > 22) return false;  // outside exact pow10 table
  double v = static_cast<double>(mant);
  v = exp10 >= 0 ? v * kPow10[exp10] : v / kPow10[-exp10];
  *out = static_cast<float>(neg ? -v : v);
  *end = s;
  return true;
}

// Parse one data row (cols comma-separated floats) at data[start..).
// Returns false on malformed input.
bool parse_row(const char *p, const char *limit, long cols, float *out) {
  for (long c = 0; c < cols; ++c) {
    const char *end = nullptr;
    if (!fast_float(p, limit, &out[c], &end)) {
      char *send = nullptr;
      errno = 0;
      float v = strtof(p, &send);
      if (send == p) return false;  // empty/garbage field
      out[c] = v;
      end = send;
    }
    p = end;
    if (c + 1 < cols) {
      if (p >= limit || *p != ',') return false;
      ++p;
    }
  }
  return true;
}

}  // namespace

extern "C" {

int csv_dims(const char *path, long *rows, long *cols) {
  Mapped m;
  if (!m.open_file(path)) return -1;
  *cols = static_cast<long>(count_cols(m));
  std::vector<size_t> starts;
  index_rows(m, starts);
  *rows = static_cast<long>(starts.size());
  return 0;
}

int csv_header(const char *path, char *buf, long buflen) {
  Mapped m;
  if (!m.open_file(path)) return -1;
  size_t end = header_end(m);
  size_t n = end;
  while (n > 0 && (m.data[n - 1] == '\n' || m.data[n - 1] == '\r')) --n;
  if (static_cast<long>(n) + 1 > buflen) return -2;
  memcpy(buf, m.data, n);
  buf[n] = '\0';
  return 0;
}

int csv_read(const char *path, float *out, long rows, long cols,
             int n_threads) {
  Mapped m;
  if (!m.open_file(path)) return -1;
  std::vector<size_t> starts;
  index_rows(m, starts);
  if (static_cast<long>(starts.size()) != rows ||
      static_cast<long>(count_cols(m)) != cols)
    return -2;

  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads < 1) n_threads = 1;
  if (static_cast<long>(n_threads) > rows) n_threads = static_cast<int>(rows);

  const char *limit = m.data + m.size;
  std::vector<int> status(static_cast<size_t>(n_threads), 0);
  std::vector<std::thread> pool;
  pool.reserve(static_cast<size_t>(n_threads));
  long chunk = (rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    long lo = static_cast<long>(t) * chunk;
    long hi = lo + chunk < rows ? lo + chunk : rows;
    pool.emplace_back([&, t, lo, hi]() {
      for (long r = lo; r < hi; ++r) {
        if (!parse_row(m.data + starts[static_cast<size_t>(r)], limit, cols,
                       out + r * cols)) {
          status[static_cast<size_t>(t)] = -3;
          return;
        }
      }
    });
  }
  for (auto &th : pool) th.join();
  for (int s : status)
    if (s != 0) return s;
  return 0;
}

}  // extern "C"
