// Native CSV loader for the data tier.
//
// The reference's L1 is pandas.read_csv (reference train_model.py:22,
// preprocess.py:15) — a C parser under a Python API. This is the framework's
// own native equivalent: mmap the file once, index newlines once, then parse
// rows to float32 in parallel across threads — zero Python-object churn,
// output written straight into a caller-provided (numpy) buffer.
//
// C ABI (consumed via ctypes from fraud_detection_tpu/data/native.py).
// Handle-based so the file is opened/mapped/indexed exactly once per load:
//   csv_open(path) -> handle (NULL on error)
//   csv_dims_h(h, &rows, &cols)        -> 0 ok; rows exclude header + blanks
//   csv_header_h(h, buf, buflen)       -> header line copied into buf
//   csv_read_h(h, out, rows, cols, nt) -> 0 ok; out is row-major float32
//   csv_close(h)
//
// Error codes: -1 io/open, -2 shape mismatch, -3 parse error.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Mapped {
  const char *data = nullptr;
  size_t size = 0;
  int fd = -1;

  bool open_file(const char *path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size == 0) return false;
    size = static_cast<size_t>(st.st_size);
    void *p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) return false;
    data = static_cast<const char *>(p);
    // The scan is strictly sequential per thread chunk.
    madvise(p, size, MADV_SEQUENTIAL);
    return true;
  }

  ~Mapped() {
    if (data) munmap(const_cast<char *>(data), size);
    if (fd >= 0) ::close(fd);
  }
};

// One mapped file + its row index, built once at csv_open.
struct Handle {
  Mapped m;
  std::vector<size_t> starts;  // row start offsets
  std::vector<size_t> ends;    // row end offsets (exclusive; '\r' stripped)
  size_t hdr_end = 0;          // one past the header line
  size_t cols = 0;
};

// End offset (one past) of the header line.
size_t header_end(const Mapped &m) {
  const char *nl = static_cast<const char *>(memchr(m.data, '\n', m.size));
  return nl ? static_cast<size_t>(nl - m.data) + 1 : m.size;
}

size_t count_cols(const Mapped &m, size_t hdr_end) {
  size_t cols = 1;
  for (size_t i = 0; i < hdr_end; ++i)
    if (m.data[i] == ',') ++cols;
  return cols;
}

// Index data rows after the header: [start, end) per row with trailing '\r'
// stripped; blank lines (empty or CR-only — e.g. a trailing "\n\n" at EOF)
// are skipped rather than surfaced as unparseable rows. A missing final
// newline counts the last partial line as a row.
void index_rows(const Mapped &m, size_t hdr_end, std::vector<size_t> &starts,
                std::vector<size_t> &ends) {
  size_t pos = hdr_end;
  while (pos < m.size) {
    const char *nl = static_cast<const char *>(
        memchr(m.data + pos, '\n', m.size - pos));
    size_t end = nl ? static_cast<size_t>(nl - m.data) : m.size;
    size_t next = nl ? end + 1 : m.size;
    if (end > pos && m.data[end - 1] == '\r') --end;
    if (end > pos) {
      starts.push_back(pos);
      ends.push_back(end);
    }
    pos = next;
  }
}

Handle *open_handle(const char *path) {
  Handle *h = new Handle();
  if (!h->m.open_file(path)) {
    delete h;
    return nullptr;
  }
  h->hdr_end = header_end(h->m);
  h->cols = count_cols(h->m, h->hdr_end);
  index_rows(h->m, h->hdr_end, h->starts, h->ends);
  return h;
}

// Powers of ten for the fast float path (double keeps f32 round-trips exact).
const double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                         1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                         1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// Fast decimal float parse: sign, up-to-18-digit mantissa accumulated as
// int64, optional fraction and e±dd exponent. Bails to the slow path
// (locale-safe strtof; handles inf/nan/hex/overlong) by returning false with
// *end untouched — ~4× faster than strtof on typical CSV numerics.
inline bool fast_float(const char *p, const char *limit, float *out,
                       const char **end) {
  const char *s = p;
  bool neg = false;
  if (s < limit && (*s == '-' || *s == '+')) neg = (*s++ == '-');
  long long mant = 0;
  int digits = 0, frac_digits = 0;
  while (s < limit && *s >= '0' && *s <= '9') {
    if (digits >= 18) return false;  // reject BEFORE the accumulate: 19
    ++digits;                        // digits would overflow int64 (UB)
    mant = mant * 10 + (*s++ - '0');
  }
  if (s < limit && *s == '.') {
    ++s;
    while (s < limit && *s >= '0' && *s <= '9') {
      if (digits >= 18) return false;
      ++digits;
      mant = mant * 10 + (*s++ - '0');
      ++frac_digits;
    }
  }
  if (digits == 0) return false;  // "", ".", "nan", "inf" → slow path
  int exp10 = -frac_digits;
  if (s < limit && (*s == 'e' || *s == 'E')) {
    const char *es = s + 1;
    bool eneg = false;
    if (es < limit && (*es == '-' || *es == '+')) eneg = (*es++ == '-');
    int ev = 0, ed = 0;
    while (es < limit && *es >= '0' && *es <= '9') {
      ev = ev * 10 + (*es++ - '0');
      if (++ed > 3) return false;
    }
    if (ed == 0) return false;
    exp10 += eneg ? -ev : ev;
    s = es;
  }
  if (exp10 < -22 || exp10 > 22) return false;  // outside exact pow10 table
  double v = static_cast<double>(mant);
  v = exp10 >= 0 ? v * kPow10[exp10] : v / kPow10[-exp10];
  *out = static_cast<float>(neg ? -v : v);
  *end = s;
  return true;
}

// Slow-path parse of one field via strtof. The mmap'd buffer is neither
// NUL-terminated nor row-scoped, so the field (bounded by the next comma or
// the row end) is copied into a NUL-terminated stack buffer first — strtof
// can never read past the row, let alone past the mapping.
inline bool slow_field(const char *p, const char *row_end, float *out,
                       const char **end) {
  size_t len = static_cast<size_t>(row_end - p);
  const char *comma = static_cast<const char *>(memchr(p, ',', len));
  size_t flen = comma ? static_cast<size_t>(comma - p) : len;
  char buf[96];
  if (flen == 0 || flen >= sizeof(buf)) return false;
  memcpy(buf, p, flen);
  buf[flen] = '\0';
  char *send = nullptr;
  float v = strtof(buf, &send);
  if (send == buf) return false;  // empty/garbage field
  if (*send != '\0') return false;  // trailing junk within the field
  *out = v;
  *end = p + flen;
  return true;
}

// Parse one data row (cols comma-separated floats) spanning [p, row_end).
// Returns false on malformed input, including ragged rows with missing or
// extra trailing fields (the row must end exactly at row_end).
bool parse_row(const char *p, const char *row_end, long cols, float *out) {
  for (long c = 0; c < cols; ++c) {
    const char *end = nullptr;
    if (!fast_float(p, row_end, &out[c], &end) &&
        !slow_field(p, row_end, &out[c], &end))
      return false;
    p = end;
    if (c + 1 < cols) {
      if (p >= row_end || *p != ',') return false;
      ++p;
    }
  }
  return p == row_end;
}

int read_rows(const Handle *h, float *out, long rows, long cols,
              int n_threads) {
  if (static_cast<long>(h->starts.size()) != rows ||
      static_cast<long>(h->cols) != cols)
    return -2;
  if (rows == 0) return 0;  // header-only file: nothing to parse

  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads < 1) n_threads = 1;
  if (static_cast<long>(n_threads) > rows) n_threads = static_cast<int>(rows);

  std::vector<int> status(static_cast<size_t>(n_threads), 0);
  std::vector<std::thread> pool;
  pool.reserve(static_cast<size_t>(n_threads));
  long chunk = (rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    long lo = static_cast<long>(t) * chunk;
    long hi = lo + chunk < rows ? lo + chunk : rows;
    pool.emplace_back([&, t, lo, hi]() {
      for (long r = lo; r < hi; ++r) {
        size_t i = static_cast<size_t>(r);
        if (!parse_row(h->m.data + h->starts[i], h->m.data + h->ends[i], cols,
                       out + r * cols)) {
          status[static_cast<size_t>(t)] = -3;
          return;
        }
      }
    });
  }
  for (auto &th : pool) th.join();
  for (int s : status)
    if (s != 0) return s;
  return 0;
}

int copy_header(const Handle *h, char *buf, long buflen) {
  size_t n = h->hdr_end;
  while (n > 0 &&
         (h->m.data[n - 1] == '\n' || h->m.data[n - 1] == '\r'))
    --n;
  if (static_cast<long>(n) + 1 > buflen) return -2;
  memcpy(buf, h->m.data, n);
  buf[n] = '\0';
  return 0;
}

}  // namespace

extern "C" {

void *csv_open(const char *path) { return open_handle(path); }

void csv_close(void *h) { delete static_cast<Handle *>(h); }

int csv_dims_h(void *vh, long *rows, long *cols) {
  const Handle *h = static_cast<const Handle *>(vh);
  *rows = static_cast<long>(h->starts.size());
  *cols = static_cast<long>(h->cols);
  return 0;
}

int csv_header_h(void *vh, char *buf, long buflen) {
  return copy_header(static_cast<const Handle *>(vh), buf, buflen);
}

int csv_read_h(void *vh, float *out, long rows, long cols, int n_threads) {
  return read_rows(static_cast<const Handle *>(vh), out, rows, cols,
                   n_threads);
}

}  // extern "C"
