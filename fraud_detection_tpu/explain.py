"""Offline SHAP explainability: summary + dependence plots.

Rebuild of explain_model.py:1-49 — interventional SHAP over the test set
(closed-form linear SHAP for the flagship, exact TreeSHAP for the GBT
family), a summary (beeswarm-style) plot, and dependence plots for the top-3
features by mean |SHAP| — with the attribution computed as one vmapped XLA
call instead of the shap library's per-row loop.
"""

from __future__ import annotations

import argparse
import logging
import os

import numpy as np

from fraud_detection_tpu import config
from fraud_detection_tpu.data.loader import load_creditcard_csv, stratified_split
from fraud_detection_tpu.evaluate import _load_model

log = logging.getLogger("fraud_detection_tpu.explain")


def explain(
    data_csv: str | None = None,
    model_dir: str = "models",
    plots_dir: str = "plots",
    seed: int = 42,
    max_rows: int = 20000,
) -> dict:
    data_csv = data_csv or config.data_csv()
    x, y, _ = load_creditcard_csv(data_csv)
    _, test_idx = stratified_split(y, 0.2, seed)
    x_test = x[test_idx][:max_rows]

    model = _load_model(model_dir)
    # Family-agnostic: closed-form linear SHAP or TreeSHAP, one device call.
    phi, _ = model.explain_batch(x_test)

    mean_abs = np.abs(phi).mean(axis=0)
    order = np.argsort(mean_abs)[::-1]
    top = [(model.feature_names[i], float(mean_abs[i])) for i in order[:10]]
    print("Top features by mean |SHAP|:")
    for name, v in top:
        print(f"  {name:8s} {v:.4f}")

    os.makedirs(plots_dir, exist_ok=True)
    _render(phi, x_test, model.feature_names, order, plots_dir)
    return {"mean_abs_shap": dict(top), "n_rows": int(len(x_test))}


def _render(phi, x_test, names, order, plots_dir: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    # Summary: per-feature SHAP distributions (top 15, violin-style).
    top15 = order[:15][::-1]
    fig, ax = plt.subplots(figsize=(7, 6))
    sample = phi[: 2000, :]
    parts = ax.violinplot(
        [sample[:, i] for i in top15], orientation="horizontal", showextrema=False
    )
    for pc in parts["bodies"]:
        pc.set_alpha(0.6)
    ax.set_yticks(range(1, len(top15) + 1))
    ax.set_yticklabels([names[i] for i in top15])
    ax.set_xlabel("SHAP value (margin space)")
    ax.set_title("SHAP summary")
    fig.tight_layout()
    fig.savefig(os.path.join(plots_dir, "shap_summary.png"), dpi=120)
    plt.close(fig)

    # Dependence plots for the top-3 features (explain_model.py:37-47).
    for rank, i in enumerate(order[:3]):
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.scatter(x_test[:2000, i], phi[:2000, i], s=4, alpha=0.4)
        ax.set_xlabel(names[i])
        ax.set_ylabel(f"SHAP({names[i]})")
        ax.set_title(f"Dependence: {names[i]}")
        fig.tight_layout()
        fig.savefig(
            os.path.join(plots_dir, f"shap_dependence_{rank}_{names[i]}.png"),
            dpi=120,
        )
        plt.close(fig)
    log.info("SHAP plots written to %s/", plots_dir)


def main(argv=None):
    config.apply_device_backend()  # DEVICE=cpu runs without the TPU tunnel
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None)
    ap.add_argument("--model-dir", default="models")
    ap.add_argument("--plots-dir", default="plots")
    ap.add_argument("--seed", type=int, default=42)
    a = ap.parse_args(argv)
    explain(a.data, a.model_dir, a.plots_dir, a.seed)


if __name__ == "__main__":
    main()
