"""Broadside: hashed feature crosses — the tensor-parallel wide family.

Every family served so far is ~30 features wide, so the serving mesh's
model axis was vestigial. This module gives the linear scorer a genuinely
wide signal surface: multiply-shift hashed feature crosses derived from
fields the wire ALREADY carries — the entity fingerprint (the ledger's
u32, host-hashed once at the edge), the transaction amount bucket, the
hour-of-day from the ``Time`` column, and the sign pattern of the V
features — at ``d = WIDE_BUCKETS`` (power of two, 2¹⁴ default). The
30-feature request block stays the wire format; the crosses materialize
device-side inside the fused flush.

The serving representation is the ledger's "widened feature block"
discipline: each of the ``n_cross`` cross templates contributes ONE column
— the hashed bucket's learned weight, ``contrib[:, c] = w_wide[idx_c]`` —
so the widened block ``[x, contrib]`` feeds the EXISTING linear score
body, the existing shared drift fold (drift monitoring covers the cross
contributions for free), and the existing linear-SHAP explain leg (φ for a
cross column is its contribution — reason codes can name a cross). The
only wide-specific device math is the hash, the table gather, and — on the
2-D mesh — exactly ONE ``psum`` over the model axis assembling the
column-sharded partial gathers (mesh/shardflush).

Hashing is pure uint32 arithmetic (multiply-shift with fixed constants —
murmur3 finalizer over Fibonacci-mixed keys), so cross indices are
bitwise-identical across processes, mesh shapes, and shard placements:
the 2-D-shard-vs-single-device bitwise contract starts here. Rows without
an entity fingerprint (legacy clients, padding) leave the ENTIRE wide
block zeroed — every template crosses the entity, so a null entity scores
base-only, and an all-padding warmup batch cannot touch the drift window.
"""

from __future__ import annotations

import logging
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("fraud_detection_tpu.broadside")

WIDE_FILE = "wide_params.npz"

#: number of cross templates (the widened block gains this many columns)
N_CROSS = 4

#: names of the widened columns, in template order — feature_names of a
#: wide model are the base schema followed by these (reason codes and the
#: drift top-features list name crosses by them)
CROSS_NAMES = (
    "cross_entity_amount",
    "cross_entity_hour",
    "cross_entity_signs",
    "cross_entity_amount_hour",
)

# Fixed hash constants: Fibonacci multiplier + the murmur3 finalizer pair,
# with one odd salt per cross template. Changing ANY of these changes every
# learned table's meaning — they are part of the artifact contract (the
# sidecar stamps a hash_version).
_KNUTH = np.uint32(2654435761)
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_SALTS = (
    np.uint32(0x9E3779B1),
    np.uint32(0x7F4A7C15),
    np.uint32(0x94D049BB),
    np.uint32(0xD6E8FEB9),
)
HASH_VERSION = 1


class CrossSpec(NamedTuple):
    """Static geometry of the wide family — a NamedTuple of ints so it is
    hashable and rides the fused programs as a jit static argument (one
    executable per geometry, exactly the score_fn discipline)."""

    n_base: int  # width of the wire schema the crosses derive from
    log2_buckets: int  # wide table size = 1 << log2_buckets
    amount_col: int  # Amount column in the base row (resolved, >= 0)
    time_col: int = 0  # Time column (seconds) for the hour-of-day key
    n_cross: int = N_CROSS

    @property
    def buckets(self) -> int:
        return 1 << self.log2_buckets

    @property
    def n_features(self) -> int:
        return self.n_base + self.n_cross

    @property
    def cross_names(self) -> tuple[str, ...]:
        return CROSS_NAMES[: self.n_cross]


def spec_from_config(n_base: int, amount_col: int | None = None) -> CrossSpec:
    from fraud_detection_tpu import config

    buckets = config.wide_buckets()
    if buckets < 2 or buckets & (buckets - 1):
        raise ValueError(f"WIDE_BUCKETS must be a power of two, got {buckets}")
    a = amount_col if amount_col is not None else config.ledger_amount_col()
    if a < 0:
        a += n_base
    return CrossSpec(
        n_base=n_base, log2_buckets=buckets.bit_length() - 1, amount_col=a
    )


# --------------------------------------------------------------------------
# The traced hash + gather bodies (shared by every fused wide program)
# --------------------------------------------------------------------------


def _mix32(h: jax.Array) -> jax.Array:
    """murmur3 finalizer over uint32 — wraps deterministically on device."""
    h = h ^ (h >> 16)
    h = h * _M1
    h = h ^ (h >> 13)
    h = h * _M2
    h = h ^ (h >> 16)
    return h


def _raw_cross_indices(xb: jax.Array, fp: jax.Array, *, spec: CrossSpec):
    """Per-row hashed cross indices, ``(b, n_cross)`` int32 in
    ``[0, buckets)``. ``xb`` is the f32 base block the model actually
    scores (dequantized on a quant wire — the histogram-shared multiply),
    ``fp`` the uint32 entity fingerprint (0 = none; the gather masks those
    rows regardless, so index content for them is irrelevant). Pure
    integer mixing after the key quantization, so same rows → bitwise
    identical indices on every process and mesh shape."""
    fp = fp.astype(jnp.uint32)
    amount = xb[:, spec.amount_col]
    # log-spaced amount buckets: 8 per decade-ish, clipped to one byte
    abucket = jnp.clip(
        jnp.floor(jnp.log1p(jnp.abs(amount)) * 8.0), 0.0, 255.0
    ).astype(jnp.uint32)
    t = jnp.maximum(xb[:, spec.time_col], 0.0)
    hour = jnp.mod(jnp.floor(t / 3600.0), 24.0).astype(jnp.uint32)
    # sign pattern over the (up to 24) base columns that are neither the
    # time nor the amount key — the V-feature half-space signature
    sign_cols = tuple(
        j for j in range(spec.n_base)
        if j not in (spec.time_col, spec.amount_col)
    )[:24]
    weights = jnp.asarray(
        [np.uint32(1) << np.uint32(k) for k in range(len(sign_cols))],
        jnp.uint32,
    )
    bits = (xb[:, list(sign_cols)] > 0.0).astype(jnp.uint32)
    # elementwise-and-reduce, not a dot: integer matmul support varies by
    # backend, and this is 24 adds per row fused into the flush anyway
    signs = (
        jnp.sum(bits * weights[None, :], axis=1, dtype=jnp.uint32)
        if len(sign_cols)
        else jnp.zeros_like(fp)
    )
    fields = (
        abucket,
        hour,
        signs,
        abucket * jnp.uint32(24) + hour,
    )[: spec.n_cross]
    shift = jnp.uint32(32 - spec.log2_buckets)
    cols = [
        (_mix32((fp ^ (f * _KNUTH)) + _SALTS[c]) >> shift).astype(jnp.int32)
        for c, f in enumerate(fields)
    ]
    return jnp.stack(cols, axis=1)


def _gather_contrib(
    wide_table: jax.Array, idx: jax.Array, has_entity: jax.Array
) -> jax.Array:
    """Single-device widened block: ``contrib[:, c] = w_wide[idx_c]``,
    zeroed for entity-less rows (every template crosses the entity)."""
    return wide_table[idx] * has_entity[:, None]


def _gather_contrib_shard(
    wide_local: jax.Array, idx: jax.Array, has_entity: jax.Array, model_axis
) -> jax.Array:
    """One model shard's partial of the widened block: its column slice's
    weights where the index falls in-range, exact zeros elsewhere. The
    caller ``psum``s over the model axis — each index lives on exactly one
    shard, so the reduce adds one real value and M−1 exact zeros, and the
    assembled block is BITWISE the single-device gather."""
    size = wide_local.shape[0]
    lo = (jax.lax.axis_index(model_axis) * size).astype(jnp.int32)
    rel = idx.astype(jnp.int32) - lo
    inb = (rel >= 0) & (rel < size)
    g = jnp.where(inb, wide_local[jnp.clip(rel, 0, size - 1)], 0.0)
    return g * has_entity[:, None]


# --------------------------------------------------------------------------
# Host helpers (training replay, gate slices, tests)
# --------------------------------------------------------------------------


def cross_indices(x: np.ndarray, fps: np.ndarray, spec: CrossSpec) -> np.ndarray:
    """Host wrapper: cross indices for raw base rows + fingerprints (the
    values serving hashes — RAW feature space, never scaled)."""
    out = _raw_cross_indices(
        jnp.asarray(np.asarray(x, np.float32)),
        jnp.asarray(np.asarray(fps, np.uint32)),
        spec=spec,
    )
    return np.asarray(out, np.int32)


def widen_with_crosses(
    x: np.ndarray, fps: np.ndarray, table: np.ndarray, spec: CrossSpec
) -> np.ndarray:
    """``[x, contrib]`` for offline evaluation (the gate's holdout slices)
    — the same widened block the fused flush materializes, so offline
    scores match what serving would produce for those rows."""
    x = np.asarray(x, np.float32)
    fps = np.asarray(fps, np.uint32)
    idx = cross_indices(x, fps, spec)
    contrib = np.asarray(table, np.float32)[idx] * (
        (fps != 0).astype(np.float32)[:, None]
    )
    return np.concatenate([x, contrib], axis=1).astype(np.float32)


def widen_scaler(scaler, n_cross: int):
    """Extend a base-schema scaler with identity columns for the cross-
    contribution block: contributions are raw table weights (mean 0 /
    scale 1 — never standardized), so the widened scaler folds into the
    widened coef without touching them."""
    from fraud_detection_tpu.ops.scaler import ScalerParams

    if scaler is None:
        return None
    return ScalerParams(
        mean=np.concatenate(
            [np.asarray(scaler.mean, np.float32), np.zeros(n_cross, np.float32)]
        ),
        scale=np.concatenate(
            [np.asarray(scaler.scale, np.float32), np.ones(n_cross, np.float32)]
        ),
        var=np.concatenate(
            [np.asarray(scaler.var, np.float32), np.ones(n_cross, np.float32)]
        ),
        n_samples=scaler.n_samples,
    )


def entity_fingerprints(entities, n: int) -> np.ndarray:
    """uint32 fingerprints for a list of entity ids (None → 0, the null
    path) — the ledger's edge hash, one keyspace across subsystems."""
    from fraud_detection_tpu.ledger.state import entity_fingerprint

    fps = np.zeros(n, np.uint32)
    for i, e in enumerate(entities or []):
        if i >= n:
            break
        if e is not None:
            fps[i] = entity_fingerprint(e)
    return fps


def save_wide(directory: str, spec: CrossSpec, table: np.ndarray) -> str:
    """Stamp ``wide_params.npz`` (geometry + learned cross-weight table)
    beside the model — the widened coef is meaningless without it."""
    from fraud_detection_tpu.ckpt.atomic import atomic_savez

    path = os.path.join(directory, WIDE_FILE)
    atomic_savez(
        path,
        hash_version=np.int64(HASH_VERSION),
        n_base=np.int64(spec.n_base),
        log2_buckets=np.int64(spec.log2_buckets),
        amount_col=np.int64(spec.amount_col),
        time_col=np.int64(spec.time_col),
        n_cross=np.int64(spec.n_cross),
        table=np.asarray(table, np.float32),
    )
    return path


def load_wide(directory: str) -> tuple[CrossSpec, np.ndarray] | None:
    path = os.path.join(directory, WIDE_FILE)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        if int(z["hash_version"]) != HASH_VERSION:
            raise ValueError(
                f"wide sidecar hash_version {int(z['hash_version'])} != "
                f"{HASH_VERSION} — the table was learned under different "
                "cross-hash constants and cannot serve"
            )
        spec = CrossSpec(
            n_base=int(z["n_base"]),
            log2_buckets=int(z["log2_buckets"]),
            amount_col=int(z["amount_col"]),
            time_col=int(z["time_col"]),
            n_cross=int(z["n_cross"]),
        )
        return spec, np.asarray(z["table"], np.float32)
