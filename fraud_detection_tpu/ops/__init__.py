"""Pure-JAX numerics kernels.

Replaces the reference's library-native cores (sklearn Cython, imblearn SMOTE,
shap's C extension, XGBoost C++ — SURVEY.md §2 "Languages") with jittable,
shardable XLA programs. Everything here is functional: pytree params in,
pytree results out, explicit PRNG keys, static shapes.
"""

from fraud_detection_tpu.ops.scaler import (  # noqa: F401
    ScalerParams,
    scaler_fit,
    scaler_fit_sharded,
    scaler_transform,
)
from fraud_detection_tpu.ops.logistic import (  # noqa: F401
    LogisticParams,
    logistic_fit_lbfgs,
    logistic_fit_sgd,
    predict_logits,
    predict_proba,
)
from fraud_detection_tpu.ops.metrics import (  # noqa: F401
    auc_roc,
    binary_classification_report,
    confusion_matrix,
)
from fraud_detection_tpu.ops.linear_shap import (  # noqa: F401
    linear_shap,
    linear_shap_single,
)
from fraud_detection_tpu.ops.smote import smote  # noqa: F401
from fraud_detection_tpu.ops.gbt import (  # noqa: F401
    GBTConfig,
    GBTModel,
    gbt_fit,
    gbt_predict_logits,
    gbt_predict_proba,
)
from fraud_detection_tpu.ops.tree_shap import (  # noqa: F401
    TreeShapExplainer,
    build_tree_explainer,
    tree_shap,
    tree_shap_single,
)
from fraud_detection_tpu.ops.scorer import (  # noqa: F401
    BatchScorer,
    fold_scaler_into_linear,
)
