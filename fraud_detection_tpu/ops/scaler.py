"""StandardScaler as a sharded XLA reduction.

Matches sklearn ``StandardScaler`` semantics (ddof=0 population variance,
zero-variance columns scale by 1.0 — reference uses it at train_model.py:36-40
and preprocess.py's scale-then-split variant). Fitting is a single pass of
per-shard partial sums followed by an allreduce, so it scales to row counts
that never fit one device (the 10M-row config in BASELINE.json).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.parallel.sharding import shard_batch


class ScalerParams(NamedTuple):
    mean: jax.Array   # (d,)
    scale: jax.Array  # (d,) — std, with 0 → 1.0 like sklearn
    var: jax.Array    # (d,)
    n_samples: jax.Array  # () float — rows seen


@partial(jax.jit, static_argnames=("n_valid",))
def _fit(x: jax.Array, n_valid: int) -> ScalerParams:
    # Two fused reductions: mean first, then E[(x-mean)²]. The one-pass
    # E[x²]−E[x]² form catastrophically cancels in f32 for high-mean/low-std
    # columns (e.g. the Kaggle `Time` column), silently collapsing their
    # variance to 0 — so we pay the second (XLA-fused) pass for exactness.
    # Padded rows are masked via the row-index weight, not assumed zero.
    n = jnp.asarray(n_valid, dtype=x.dtype)
    w = (jnp.arange(x.shape[0]) < n_valid).astype(x.dtype)[:, None]
    mean = jnp.sum(w * x, axis=0) / n
    centered = (x - mean) * w
    var = jnp.sum(centered * centered, axis=0) / n
    std = jnp.sqrt(var)
    scale = jnp.where(std == 0.0, 1.0, std)
    return ScalerParams(mean=mean, scale=scale, var=var, n_samples=n)


def scaler_fit(x: jax.Array | np.ndarray, n_valid: int | None = None) -> ScalerParams:
    """Fit on a (possibly padded) device array. ``n_valid`` defaults to all
    rows."""
    x = jnp.asarray(x)
    if n_valid is None:
        n_valid = x.shape[0]
    return _fit(x, n_valid)


def scaler_fit_sharded(x: np.ndarray, mesh=None) -> ScalerParams:
    """Host rows → row-sharded device array → one-pass sharded fit.

    The partial sums reduce over the data axis via the allreduce XLA inserts
    for the row-sharded → replicated transition (rides ICI on a real pod).
    """
    arr, n_valid = shard_batch(x, mesh)
    return _fit(arr, n_valid)


@jax.jit
def scaler_transform(params: ScalerParams, x: jax.Array) -> jax.Array:
    return (x - params.mean) / params.scale


@jax.jit
def scaler_inverse_transform(params: ScalerParams, x: jax.Array) -> jax.Array:
    return x * params.scale + params.mean
