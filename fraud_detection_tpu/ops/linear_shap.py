"""Closed-form interventional linear SHAP.

For a linear model ``f(x) = wᵀx + b`` with an independent (interventional)
background distribution, the exact SHAP values are ``φⱼ = wⱼ·(xⱼ − μⱼ)`` with
base value ``E[f] = wᵀμ + b`` — the closed form that
``shap.LinearExplainer(model, X, feature_perturbation="interventional")``
computes (reference: explain_model.py:24-27 and api/worker.py:52-53,75).

The reference's *deployed* worker shipped the wrong formula (raw ``coef·x``,
xai_tasks.py:106-107 — SURVEY.md §2.3.3); this module implements the real one
everywhere, vmapped so a whole batch of explanations is one device launch.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class LinearShapExplainer(NamedTuple):
    coef: jax.Array        # (d,)
    background_mean: jax.Array  # (d,) — μ of the background set
    expected_value: jax.Array   # () — wᵀμ + b (margin space)


def make_explainer(coef, intercept, background_x=None, background_mean=None):
    coef = jnp.asarray(coef).reshape(-1)
    if background_mean is None:
        if background_x is None:
            background_mean = jnp.zeros_like(coef)
        else:
            background_mean = jnp.mean(jnp.asarray(background_x), axis=0)
    background_mean = jnp.asarray(background_mean).reshape(-1)
    ev = jnp.dot(coef, background_mean) + jnp.asarray(intercept).reshape(())
    return LinearShapExplainer(coef, background_mean, ev)


def _raw_linear_shap(
    coef: jax.Array, background_mean: jax.Array, x: jax.Array
) -> jax.Array:
    """Un-jitted batched linear-SHAP body — the lantern fusion surface.

    The fused flush programs (monitor/drift ``_fused_flush_explain`` and
    siblings) trace THIS expression inline so the serve-time reason codes
    are bitwise the standalone :func:`linear_shap` attributions: both paths
    share one body, so the parity contract holds by construction rather
    than by floating-point luck."""
    return coef[None, :] * (x - background_mean[None, :])


@jax.jit
def linear_shap_single(explainer: LinearShapExplainer, x: jax.Array) -> jax.Array:
    """SHAP values (d,) for one row; Σφ + E[f] = f(x) exactly."""
    return explainer.coef * (x - explainer.background_mean)


@jax.jit
def linear_shap(explainer: LinearShapExplainer, x: jax.Array) -> jax.Array:
    """SHAP values (n, d) for a batch — one fused elementwise kernel."""
    return _raw_linear_shap(explainer.coef, explainer.background_mean, x)


def topk_reasons(phi: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Arg-top-k reason codes over per-feature attributions (n, d) →
    ``(indices (n, k) int32, values (n, k))``, ranked by SIGNED attribution
    (the features pushing the score hardest toward fraud come first).

    Deterministic: ``jax.lax.top_k`` resolves ties toward the lower feature
    index, so two runs over identical inputs emit identical reason codes —
    the property the consistency check between the serve-time codes and the
    worker's full-vector backfill relies on."""
    val, idx = jax.lax.top_k(phi, k)
    return idx.astype(jnp.int32), val


@partial(jax.jit, static_argnames=("k",))
def linear_shap_topk(
    explainer: LinearShapExplainer, x: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Standalone top-k reason codes — the parity reference the fused
    score+explain flush (lantern) is gated against bitwise on the f32 wire."""
    return topk_reasons(
        _raw_linear_shap(explainer.coef, explainer.background_mean, x), k
    )
