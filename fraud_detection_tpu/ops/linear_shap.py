"""Closed-form interventional linear SHAP.

For a linear model ``f(x) = wᵀx + b`` with an independent (interventional)
background distribution, the exact SHAP values are ``φⱼ = wⱼ·(xⱼ − μⱼ)`` with
base value ``E[f] = wᵀμ + b`` — the closed form that
``shap.LinearExplainer(model, X, feature_perturbation="interventional")``
computes (reference: explain_model.py:24-27 and api/worker.py:52-53,75).

The reference's *deployed* worker shipped the wrong formula (raw ``coef·x``,
xai_tasks.py:106-107 — SURVEY.md §2.3.3); this module implements the real one
everywhere, vmapped so a whole batch of explanations is one device launch.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LinearShapExplainer(NamedTuple):
    coef: jax.Array        # (d,)
    background_mean: jax.Array  # (d,) — μ of the background set
    expected_value: jax.Array   # () — wᵀμ + b (margin space)


def make_explainer(coef, intercept, background_x=None, background_mean=None):
    coef = jnp.asarray(coef).reshape(-1)
    if background_mean is None:
        if background_x is None:
            background_mean = jnp.zeros_like(coef)
        else:
            background_mean = jnp.mean(jnp.asarray(background_x), axis=0)
    background_mean = jnp.asarray(background_mean).reshape(-1)
    ev = jnp.dot(coef, background_mean) + jnp.asarray(intercept).reshape(())
    return LinearShapExplainer(coef, background_mean, ev)


@jax.jit
def linear_shap_single(explainer: LinearShapExplainer, x: jax.Array) -> jax.Array:
    """SHAP values (d,) for one row; Σφ + E[f] = f(x) exactly."""
    return explainer.coef * (x - explainer.background_mean)


@jax.jit
def linear_shap(explainer: LinearShapExplainer, x: jax.Array) -> jax.Array:
    """SHAP values (n, d) for a batch — one fused elementwise kernel."""
    return explainer.coef[None, :] * (x - explainer.background_mean[None, :])
