"""L2-regularized logistic regression, solved on device.

Replaces sklearn ``LogisticRegression`` (the reference's canonical serving
model — SURVEY.md §2.3.1; trained implicitly, served at api/app.py:44,209).

Two solvers:

- :func:`logistic_fit_lbfgs` — full-batch L-BFGS matching sklearn's
  ``lbfgs`` objective exactly: ``0.5·wᵀw + C·Σᵢ sᵢ·log(1+exp(-ỹᵢ(xᵢᵀw+b)))``
  with the intercept unregularized and ỹ∈{−1,+1}. Row-sharded X → the loss
  gradient reduction becomes an allreduce XLA lowers onto ICI. This is the
  AUC-parity path.
- :func:`logistic_fit_sgd` — minibatch momentum-SGD under ``shard_map`` with
  an explicit ``psum`` gradient allreduce, for row counts where full-batch
  L-BFGS materialization is wasteful (the 10M-row config). Demonstrates the
  explicit-collective path of SURVEY.md §2.4.

Both return a :class:`LogisticParams` pytree; downstream (scorer, SHAP,
artifact export) is solver-agnostic.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
import optax.tree_utils as otu
from jax.sharding import PartitionSpec as P

from fraud_detection_tpu.parallel.compat import shard_map
from fraud_detection_tpu.parallel.mesh import DATA_AXIS, default_mesh
from fraud_detection_tpu.parallel.sharding import (
    as_device_f32,
    pad_to_multiple,
    shard_batch,
    sync_fetch,
)


class LogisticParams(NamedTuple):
    coef: jax.Array       # (d,)
    intercept: jax.Array  # ()


def _resolve_sample_weight(
    y_np: np.ndarray, sample_weight, class_weight: dict | str | None
) -> np.ndarray:
    """sklearn's sample-weight composition: explicit weights × class weights
    ('balanced' → n/(2·n_class), or a {label: w} dict — covers the reference's
    scale_pos_weight concept from train_model.py:52-54)."""
    n = y_np.shape[0]
    sw = (
        np.ones((n,), dtype=np.float32)
        if sample_weight is None
        else np.asarray(sample_weight, dtype=np.float32).copy()
    )
    if class_weight == "balanced":
        n_pos = max(int((y_np > 0).sum()), 1)
        n_neg = max(int((y_np <= 0).sum()), 1)
        sw *= np.where(y_np > 0, n / (2.0 * n_pos), n / (2.0 * n_neg)).astype(
            np.float32
        )
    elif isinstance(class_weight, dict):
        sw *= np.where(
            y_np > 0, float(class_weight.get(1, 1.0)), float(class_weight.get(0, 1.0))
        ).astype(np.float32)
    return sw


def _penalized_loss(params: LogisticParams, x, y_pm, sample_weight, c: float):
    """sklearn's primal objective (liblinear/lbfgs parameterization)."""
    z = x @ params.coef + params.intercept
    # log(1 + exp(-y z)) — numerically stable softplus
    losses = jax.nn.softplus(-y_pm * z)
    data_term = jnp.sum(sample_weight * losses)
    reg = 0.5 * jnp.dot(params.coef, params.coef)
    return reg + c * data_term


@jax.jit
def predict_logits(params: LogisticParams, x: jax.Array) -> jax.Array:
    return x @ params.coef + params.intercept


@jax.jit
def predict_proba(params: LogisticParams, x: jax.Array) -> jax.Array:
    """P(class=1). Two-column form is ``stack([1-p, p])`` at the caller."""
    return jax.nn.sigmoid(predict_logits(params, x))


def _run_lbfgs(loss_fn, init_params, max_iter: int, tol: float):
    """L-BFGS with zoom linesearch, stopping on ‖grad‖∞ < tol (sklearn's
    convergence criterion for the lbfgs solver)."""
    opt = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=loss_fn
        )
        params = optax.apply_updates(params, updates)
        return params, state

    def cont(carry):
        _, state = carry
        count = otu.tree_get(state, "count")
        grad = otu.tree_get(state, "grad")
        # max |g| over all leaves, written with jax.tree_util primitives so
        # it runs on optax versions without tree_utils.tree_max
        err = jax.tree_util.tree_reduce(
            jnp.maximum, jax.tree.map(lambda t: jnp.max(jnp.abs(t)), grad)
        )
        return (count == 0) | ((count < max_iter) & (err >= tol))

    init = (init_params, opt.init(init_params))
    params, _ = jax.lax.while_loop(cont, step, init)
    return params


@partial(jax.jit, static_argnames=("c", "max_iter", "tol"))
def _fit_lbfgs(x, y, sample_weight, init: LogisticParams, c: float,
               max_iter: int, tol: float):
    y_pm = jnp.where(y > 0, 1.0, -1.0).astype(x.dtype)
    loss_fn = lambda p: _penalized_loss(p, x, y_pm, sample_weight, c)
    return _run_lbfgs(loss_fn, init, max_iter, tol)


def logistic_fit_lbfgs(
    x,
    y,
    c: float = 1.0,
    max_iter: int = 100,
    tol: float = 1e-5,
    sample_weight=None,
    class_weight: dict | str | None = None,
    mesh=None,
    sharded: bool = False,
    warm_start: LogisticParams | None = None,
) -> LogisticParams:
    """Fit with sklearn-equivalent hyperparameters.

    ``class_weight`` accepts ``'balanced'`` or a ``{0: w0, 1: w1}`` dict
    (covers the reference's ``scale_pos_weight`` concept from
    train_model.py:52-54). With ``sharded=True`` rows are padded and sharded
    over the mesh's data axis (padded rows get weight 0). ``warm_start``
    seeds the solver with existing params (the conductor's retrain
    executor starts from the incumbent champion) — same optimum, far fewer
    linesearch passes when the data shifted only at the margin.
    """
    # Only y comes to host (tiny — needed for class counts); X stays on
    # device when it already lives there (e.g. straight out of smote()).
    y_np = np.asarray(y)
    sw = _resolve_sample_weight(y_np, sample_weight, class_weight)
    x_in = as_device_f32(x)
    if warm_start is None:
        init = LogisticParams(
            coef=jnp.zeros((x_in.shape[1],), jnp.float32),
            intercept=jnp.zeros((), jnp.float32),
        )
    else:
        init = LogisticParams(
            coef=jnp.asarray(warm_start.coef, jnp.float32),
            intercept=jnp.asarray(warm_start.intercept, jnp.float32),
        )

    if sharded:
        x_dev, _ = shard_batch(x_in, mesh)
        y_dev, _ = shard_batch(y_np.astype(np.float32), mesh)
        sw_dev, _ = shard_batch(sw, mesh)  # pad weight 0 ⇒ padded rows inert
    else:
        x_dev, y_dev, sw_dev = jnp.asarray(x_in), jnp.asarray(y_np), jnp.asarray(sw)
    # Synchronous like the SGD path (sklearn contract + XLA-teardown
    # safety); sync_fetch's docstring has the tunneled-PJRT rationale.
    return sync_fetch(
        _fit_lbfgs(
            x_dev, y_dev, sw_dev, init, float(c), int(max_iter), float(tol)
        )
    )


# ---------------------------------------------------------------------------
# Minibatch SGD path with explicit collectives (10M-row scale)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _sharded_epoch(mesh, c: float, n_total: int, momentum: float, batch: int):
    """Jitted shard_map SGD epoch for these hyperparameters — cached at
    module level so repeated fits (bench warmup→timed, back-to-back
    training jobs in one process) compile the epoch program ONCE. A
    per-call jax.jit(shard_map(...)) (the pre-r5 shape) recompiled on
    every logistic_fit_sgd invocation. The device count comes from the
    mesh (already in the key) so the psum-scaled reg term can never see
    a mismatched ndev."""
    return jax.jit(
        shard_map(
            _sgd_epoch_fn(c, n_total, mesh.shape[DATA_AXIS], momentum, batch),
            mesh=mesh,
            in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P(), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def _sgd_epoch_fn(
    c: float, n_total: int, n_devices: int, momentum: float, batch: int
):
    """Build the per-shard epoch function run under shard_map.

    Each device scans over its local minibatches; the per-batch gradient is
    ``psum``-allreduced over the data axis before the momentum update, so all
    devices hold identical params throughout (synchronous DP).

    The per-step loss is an unbiased estimate of the 1/n-scaled sklearn
    objective: ``(C/B_valid)·Σ_batch sw·softplus + (0.5/n)·wᵀw``, where
    B_valid is the *global count of non-padding rows in this batch* (padding
    rows carry validity 0 — dividing by the nominal batch size instead would
    silently shrink the data gradient by the padding fraction). The reg term
    is divided across devices so the psum reconstitutes it once.
    """

    def epoch(params, velocity, x_local, y_pm_local, sw_local, valid_local, perm, lr):
        n_local = x_local.shape[0]
        n_batches = n_local // batch

        def grad_fn(p, xb, yb, swb, b_valid):
            def loss(p):
                z = xb @ p.coef + p.intercept
                data = jnp.sum(swb * jax.nn.softplus(-yb * z)) * (c / b_valid)
                reg = 0.5 * jnp.dot(p.coef, p.coef) / (n_total * n_devices)
                return data + reg

            return jax.grad(loss)(p)

        def body(carry, i):
            p, v = carry
            idx = jax.lax.dynamic_slice_in_dim(perm, i * batch, batch)
            xb = x_local[idx]
            yb = y_pm_local[idx]
            swb = sw_local[idx]
            b_valid = jnp.maximum(
                jax.lax.psum(jnp.sum(valid_local[idx]), DATA_AXIS), 1.0
            )
            g = grad_fn(p, xb, yb, swb, b_valid)
            g = jax.tree.map(lambda t: jax.lax.psum(t, DATA_AXIS), g)
            v = jax.tree.map(lambda v_, g_: momentum * v_ - lr * g_, v, g)
            p = jax.tree.map(lambda p_, v_: p_ + v_, p, v)
            return (p, v), None

        (params, velocity), _ = jax.lax.scan(
            body, (params, velocity), jnp.arange(n_batches)
        )
        return params, velocity

    return epoch


def _cap_batch_size(n: int, ndev: int, batch_size: int) -> int:
    """Cap the minibatch at the per-device shard size so small datasets don't
    pad up to a mostly-empty giant batch."""
    per_dev = max((n + ndev - 1) // ndev, 1)
    return max(min(batch_size, per_dev), 1)


def logistic_fit_sgd(
    x,
    y,
    c: float = 1.0,
    epochs: int = 5,
    batch_size: int = 8192,
    lr: float = 0.5,
    momentum: float = 0.9,
    class_weight: dict | str | None = None,
    seed: int = 0,
    mesh=None,
    epoch_callback=None,
    resume: dict | None = None,
) -> LogisticParams:
    """Data-parallel minibatch SGD with explicit ``psum`` allreduce.

    The objective is the sklearn one scaled by 1/n (so lr is row-count
    independent). Not bit-identical to L-BFGS but converges to the same
    optimum; used for the 10M-row configuration where L-BFGS full-batch
    linesearch passes are wasteful.

    Elastic-training hooks (the reference has no checkpoint/resume story —
    SURVEY.md §5): ``epoch_callback(epoch, params, velocity, rng)`` fires
    after each completed epoch (``ckpt.SGDCheckpointer.epoch_callback``
    persists it atomically), and ``resume`` is that checkpointer's saved
    state — training continues at the next epoch with the exact optimizer
    velocity and host PRNG stream, so an interrupted+resumed fit is
    bit-identical to an uninterrupted one.
    """
    mesh = mesh or default_mesh()
    ndev = mesh.shape[DATA_AXIS]
    # X stays on device when it already lives there (SGD is the >2M-row
    # solver — a host round-trip of the SMOTE'd matrix is the expensive
    # mistake); y comes to host (small) for class counts.
    x_in = as_device_f32(x)
    y_np = np.asarray(y)
    n = x_in.shape[0]
    sw = _resolve_sample_weight(y_np, None, class_weight)
    batch_size = _cap_batch_size(n, ndev, batch_size)

    # Pad rows so every device gets an equal, batch-divisible shard; padded
    # rows carry weight 0 and validity 0 so they're inert in the loss.
    mult = ndev * batch_size
    x_pad, _ = pad_to_multiple(x_in, mult)
    y_np, _ = pad_to_multiple(y_np, mult)
    sw, _ = pad_to_multiple(sw, mult)
    valid = np.zeros((x_pad.shape[0],), np.float32)
    valid[:n] = 1.0
    y_pm = np.where(y_np > 0, 1.0, -1.0).astype(np.float32)

    x_dev, _ = shard_batch(x_pad, mesh)
    y_dev, _ = shard_batch(y_pm, mesh)
    sw_dev, _ = shard_batch(sw, mesh)
    valid_dev, _ = shard_batch(valid, mesh)

    n_local = x_pad.shape[0] // ndev
    sharded_epoch = _sharded_epoch(mesh, float(c), n, momentum, batch_size)

    d = x_pad.shape[1]
    params = LogisticParams(coef=jnp.zeros((d,), jnp.float32), intercept=jnp.zeros(()))
    velocity = LogisticParams(
        coef=jnp.zeros((d,), jnp.float32), intercept=jnp.zeros(())
    )
    rng = np.random.default_rng(seed)
    start_epoch = 0
    # Everything the LR schedule / permutation stream / shapes depend on.
    # A checkpoint taken under a different fingerprint cannot resume this
    # fit bit-identically, so it is rejected instead of silently reused.
    fingerprint = {
        "n": int(n), "d": int(d), "epochs": int(epochs),
        "batch_size": int(batch_size), "lr": float(lr),
        "momentum": float(momentum), "seed": int(seed), "ndev": int(ndev),
    }
    if resume is not None:
        saved_fp = resume.get("fingerprint")
        if saved_fp is not None and saved_fp != fingerprint:
            diff = {
                k: (saved_fp.get(k), fingerprint[k])
                for k in fingerprint
                if saved_fp.get(k) != fingerprint[k]
            }
            raise ValueError(
                f"checkpoint does not match this fit (saved vs current): {diff}"
            )
        if np.asarray(resume["coef"]).shape != (d,):
            raise ValueError(
                f"checkpoint coef shape {np.asarray(resume['coef']).shape} "
                f"does not match {d} features"
            )
        params = LogisticParams(
            coef=jnp.asarray(resume["coef"], jnp.float32),
            intercept=jnp.asarray(resume["intercept"], jnp.float32),
        )
        velocity = LogisticParams(
            coef=jnp.asarray(resume["v_coef"], jnp.float32),
            intercept=jnp.asarray(resume["v_intercept"], jnp.float32),
        )
        rng.bit_generator.state = resume["rng_state"]
        start_epoch = int(resume["epoch"]) + 1
    for e in range(start_epoch, epochs):
        # Cosine-decayed lr: converges to the optimum instead of hovering at
        # the SGD noise floor (needed for AUC parity with the L-BFGS path).
        lr_e = jnp.float32(lr * 0.5 * (1.0 + np.cos(np.pi * e / max(epochs, 1))))
        params, velocity = sharded_epoch(
            params, velocity, x_dev, y_dev, sw_dev, valid_dev,
            jnp.asarray(rng.permutation(n_local)), lr_e,
        )
        if epoch_callback is not None:
            epoch_callback(e, params, velocity, rng, fingerprint)
    # fit() is synchronous (sklearn contract) — and exiting a process while
    # the cached shard_map epoch program is still executing asynchronously
    # segfaults in XLA teardown. sync_fetch's docstring has the
    # tunneled-PJRT rationale for the real d2h fetch.
    return sync_fetch(params)
