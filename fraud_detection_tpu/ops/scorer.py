"""Batched online scorer.

Serving-path replacement for the reference's ``SCALER.transform`` +
``MODEL.predict_proba`` sequence (api/app.py:194-240, predict_single.py:28-32).

TPU-first design decisions (SURVEY.md §7 hard part c):

- **Scaler folding.** Standardize-then-score for a linear model is itself
  linear: ``σ((x−μ)/s·w + b) = σ(x·w′ + b′)`` with ``w′ = w/s`` and
  ``b′ = b − μ·(w/s)``. We fold the scaler into the weights once at load
  time, so the serving path never materializes a scaled copy of the input —
  one GEMV + sigmoid per batch, zero preprocessing launches.
- **Static shape buckets.** ``jit`` compiles one executable per shape; the
  scorer pads request batches up to power-of-two buckets so a handful of
  cached executables serve every batch size without recompilation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.ops.logistic import LogisticParams
from fraud_detection_tpu.ops.scaler import ScalerParams


def fold_scaler_into_linear(
    params: LogisticParams, scaler: ScalerParams | None
) -> LogisticParams:
    """Return params ``(w′, b′)`` scoring *raw* inputs identically to scoring
    scaled inputs with the original params."""
    if scaler is None:
        return params
    w = params.coef / scaler.scale
    b = params.intercept - jnp.dot(scaler.mean, w)
    return LogisticParams(coef=w, intercept=b)


@jax.jit
def _score(coef: jax.Array, intercept: jax.Array, x: jax.Array) -> jax.Array:
    # bf16-IO inputs upcast here, inside jit — the convert fuses into the
    # scoring kernel instead of dispatching separately.
    return jax.nn.sigmoid(x.astype(jnp.float32) @ coef + intercept)


def _np_bfloat16():
    import ml_dtypes  # ships with jax

    return ml_dtypes.bfloat16


def _bucket(n: int, min_bucket: int = 8) -> int:
    b = min_bucket
    while b < n:
        b *= 2
    return b


class _BucketedScorer:
    """Shared serving mechanics: pad request batches up to power-of-two shape
    buckets (one cached XLA executable per bucket) and score on device.

    Thread-safe for concurrent callers (JAX dispatch is); the async
    micro-batching queue in :mod:`fraud_detection_tpu.service.microbatch`
    sits in front of this for the online path. Subclasses provide
    ``n_features`` and ``_score_padded``.
    """

    min_bucket: int
    n_features: int
    _io_np_dtype = np.float32  # overridden for bf16 host↔device IO

    def _score_padded(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def warmup(self, max_bucket: int = 4096) -> None:
        """Pre-compile the bucket ladder so first requests don't pay XLA
        compile latency."""
        b = self.min_bucket
        while b <= max_bucket:
            self.predict_proba(np.zeros((b, self.n_features), np.float32))
            b *= 2

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        n = x.shape[0]
        b = _bucket(n, self.min_bucket)
        if b != n:
            x = np.concatenate([x, np.zeros((b - n, x.shape[1]), np.float32)])
        x = x.astype(self._io_np_dtype, copy=False)  # host-side cast: the
        return np.asarray(                           # transfer ships io_dtype
            self._score_padded(jnp.asarray(x)), dtype=np.float32
        )[:n]

    def predict(self, x: np.ndarray, threshold: float = 0.5) -> np.ndarray:
        return (self.predict_proba(x) >= threshold).astype(np.int64)


class BatchScorer(_BucketedScorer):
    """Scaler-folded linear scorer: one GEMV + sigmoid per bucket (the
    Pallas fused kernel when ``USE_PALLAS=1`` — ops/pallas_kernels)."""

    def __init__(
        self,
        params: LogisticParams,
        scaler: ScalerParams | None = None,
        min_bucket: int = 8,
        io_dtype: str = "float32",
    ):
        folded = fold_scaler_into_linear(params, scaler)
        self.coef = jnp.asarray(folded.coef, dtype=jnp.float32)
        self.intercept = jnp.asarray(folded.intercept, dtype=jnp.float32)
        self.n_features = int(self.coef.shape[0])
        self.min_bucket = min_bucket
        # bf16 IO halves host↔device bytes on the bandwidth-bound online
        # path; compute stays f32 (upcast on device). Input quantization to
        # 8 mantissa bits moves scores by ~1e-3 — see test_scorer bf16 parity.
        if io_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"io_dtype must be float32|bfloat16, got {io_dtype}")
        self._io_np_dtype = (
            np.float32 if io_dtype == "float32" else _np_bfloat16()
        )
        from fraud_detection_tpu.ops.pallas_kernels import pallas_enabled

        self._use_pallas = pallas_enabled()

    def _score_padded(self, x: jax.Array) -> jax.Array:
        # bf16-IO inputs stay bf16 here; the f32 upcast happens inside the
        # jitted kernels so it compiles into the same executable.
        if self._use_pallas:
            from fraud_detection_tpu.ops.pallas_kernels import fused_score

            return fused_score(self.coef, self.intercept, x)
        return _score(self.coef, self.intercept, x)


class GBTBatchScorer(_BucketedScorer):
    """Forest scorer over a :class:`~fraud_detection_tpu.ops.gbt.GBTModel` —
    same protocol as :class:`BatchScorer` so the micro-batcher and serving
    path are model-family agnostic. Expects a model whose bin edges are
    already in raw input space (``fold_scaler_into_gbt``), mirroring the
    linear scaler fold."""

    def __init__(self, model, min_bucket: int = 8):
        from fraud_detection_tpu.ops.gbt import gbt_predict_proba

        self._model = model
        self._predict = gbt_predict_proba
        self.n_features = int(model.bin_edges.shape[0])
        self.min_bucket = min_bucket

    def _score_padded(self, x: jax.Array) -> jax.Array:
        return self._predict(self._model, x)
